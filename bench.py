"""Benchmark: tpu_binpack placement throughput, SYSTEM headline + kernel.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Headline (r4+): the END-TO-END system rate at C1M shape — real jobs
through the real server (broker -> workers -> eval-batched engine -> plan
queue -> raft/FSM -> state store), 256K placements of identical containers
(the authentic Million Container Challenge workload) over 5K nodes with
exact int-spec deterministic scoring, on one chip. BASELINE.md bar: 1M in
<10s on v5e-8 = 100K placements/s; per-chip share 12.5K/s
(vs_baseline = measured / 12_500). The eval axis shards across chips with
zero cross-chip traffic (dryrun_multichip executes that sharding).

Diagnostics on stderr + the JSON line's "extra": the device-kernel rate
(the r1-r3 headline), plan-queue drain at 10K nodes (BASELINE metric #2),
chunked throughput mode, and the remaining BASELINE system configs.
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Crash-proof artifacts: every config's JSON lands on disk the moment it
# finishes, and the long headline window also writes periodic in-flight
# progress snapshots — so a later SIGSEGV/OOM/timeout in an unrelated
# diagnostic can never erase results already earned (the "parsed: null"
# failure mode: one crash at minute 40 used to lose the whole run).
# ---------------------------------------------------------------------------

_ARTIFACT_DIR = os.environ.get("NOMAD_BENCH_ARTIFACT_DIR", "bench_artifacts")


def write_artifact(name, payload):
    """Atomically persist one JSON artifact under ``_ARTIFACT_DIR``.

    Failures are logged, never raised — persistence must not be able to
    break the bench it is protecting."""
    try:
        os.makedirs(_ARTIFACT_DIR, exist_ok=True)
        path = os.path.join(_ARTIFACT_DIR, f"{name}.json")
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, sort_keys=True, default=str)
        os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001
        log(f"artifact write failed for {name}: {e}")


# ---------------------------------------------------------------------------
# Flight recorder: every system config runs with the recorder armed and
# spilling {server}.flight.jsonl under the artifact dir (written+flushed
# every tick by the recorder itself, so a SIGKILL loses at most one frame).
# The derived ranked bottleneck report lands as {config}.bottleneck.json
# from the config's normal path, its finally, AND an atexit hook — a
# timed-out headline is still self-diagnosing from disk.
# ---------------------------------------------------------------------------

_PENDING_FLIGHT = {}


def _flush_flight(name, server):
    """Write the ranked critical-path bottleneck report (+ recorder
    overhead) for one system config. Idempotent and never raises."""
    try:
        from nomad_tpu.trace import attribution

        report = attribution.bottleneck_report()
        report["flight"] = dict(armed=server.flight.armed,
                                **server.flight.overhead())
        write_artifact(f"{name}.bottleneck", report)
        return report
    except Exception as e:  # noqa: BLE001
        log(f"flight flush failed for {name}: {e}")
        return None


@atexit.register
def _flush_pending_flight():
    for name, fn in list(_PENDING_FLIGHT.items()):
        fn()
    _PENDING_FLIGHT.clear()


# ---------------------------------------------------------------------------
# Headline: eval-batched C1M with exact parity semantics
# ---------------------------------------------------------------------------

def bench_batched_parity_c1m(total=1_000_000, n_nodes=5000, batch=512,
                             per_eval=200, budget_s=75.0):
    """C1M as independent evals: ``batch`` evals x ``per_eval`` placements
    per device dispatch, exact sequential parity semantics inside each
    eval (exact INTEGER scoring — tpu/intscore.py — and the ring-ordered
    limit iterator emulation; bit-identical selections on any backend).
    Jobs are C1M-shaped (1-2 task groups per job — the challenge scheduled
    simple single-container jobs) with a spread stanza active so the full
    rank stack runs."""
    import jax

    from nomad_tpu.tpu.engine import (
        _build_batched_scan,
        _build_place_scan,
        example_scan_inputs,
    )

    evals = [
        example_scan_inputs(
            n_nodes=n_nodes, n_tgs=2, n_placements=per_eval, seed=s % 16,
            dtype=np.int32,  # exact-integer parity spec (tpu/intscore.py)
        )
        for s in range(batch)
    ]
    n_pad = evals[0][0]
    static_b = tuple(
        np.stack([e[1][i] for e in evals]) for i in range(len(evals[0][1]))
    )
    carry_b = tuple(
        np.stack([e[2][i] for e in evals]) for i in range(len(evals[0][2]))
    )
    xs_b = tuple(
        np.stack([e[3][i] for e in evals]) for i in range(len(evals[0][3]))
    )

    scan = _build_batched_scan()
    # keep inputs resident: the loop measures device rate; host->device
    # transfer cost is covered by the system benches below
    static_b = jax.device_put(static_b)
    carry_b = jax.device_put(carry_b)
    xs_b = jax.device_put(xs_b)

    t0 = time.perf_counter()
    _carry, outs = jax.block_until_ready(scan(static_b, carry_b, xs_b))
    log(f"batched-parity compile+first dispatch: {time.perf_counter()-t0:.1f}s")

    # -- in-bench parity assertion: sampled evals must match the
    # single-eval exact scan bit-for-bit
    single = _build_place_scan()
    chosen_b = np.asarray(outs[0])
    for k in (0, batch // 2, batch - 1):
        ref_carry, ref_outs = single(n_pad, evals[k][1], evals[k][2], evals[k][3])
        if not (np.asarray(ref_outs[0]) == chosen_b[k]).all():
            raise AssertionError(
                f"PARITY VIOLATION: batched eval {k} diverged from the "
                "single-eval exact scan"
            )
    log(f"parity asserted: batched == single-eval scan on 3/{batch} sampled evals")

    placed_per_dispatch = batch * per_eval
    done = 0
    t0 = time.perf_counter()
    while done < total:
        # materialize to host: block_until_ready under-reports on some
        # tunneled backends
        np.asarray(scan(static_b, carry_b, xs_b)[1][0])
        done += placed_per_dispatch
        if time.perf_counter() - t0 > budget_s:
            break
    elapsed = time.perf_counter() - t0
    rate = done / elapsed
    eta_1m = 1_000_000 / rate
    log(
        f"C1M eval-batched PARITY: {done:,} placements / {n_nodes} nodes in "
        f"{elapsed:.2f}s -> {rate:,.0f} placements/s on ONE chip "
        f"(batch={batch} evals x {per_eval}; 1M ETA {eta_1m:.1f}s single-chip, "
        f"~{eta_1m/8:.1f}s projected v5e-8: the eval axis shards with zero "
        f"cross-chip traffic — dryrun_multichip executes that sharding)"
    )
    return rate


# ---------------------------------------------------------------------------
# Diagnostics: chunked throughput mode (non-parity) + single parity scan
# ---------------------------------------------------------------------------

def c1m_inputs(n_nodes=5000, n_tgs=8, seed=0):
    from nomad_tpu.tpu.engine import DIM_CPU, DIM_MEM, example_scan_inputs

    n_pad, static, carry, _ = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=n_tgs, n_placements=64, seed=seed
    )
    static = list(static)
    asks = np.zeros_like(static[2])  # same capacity dims as the encode
    asks[:, DIM_CPU] = 15
    asks[:, DIM_MEM] = 30
    static[2] = asks
    static[3] = np.ones_like(static[3])  # no constraint filtering in C1M

    def f32(t):
        return tuple(
            np.asarray(a).astype(np.float32)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a)
            for a in t
        )

    return n_pad, f32(static), f32(carry), None


BULK_K = 1024
TAIL_K = 256

# [B, N]-plane traffic model per scan step, in int32-equivalent passes —
# the roofline accounting PARITY.md §"Kernel roofline" documents. The
# parity step's pre-change count (~40 passes, ~210MB/step at B=256,
# N=5120) is kept as the baseline the packed-mask refactor is measured
# against: packing feasibility+affinity presence into one uint8 plane,
# fusing the two ring cumsums into one int32 lane-packed cumsum and
# collapsing the num_terms chain into one popcount removes ~13
# full-plane passes. The chunked tier touches far fewer planes per step
# (no ring machinery, one top_k) but each step covers up to K placements.
PARITY_PASSES_EQ_PRE = 40.0   # r5 baseline (PARITY.md)
PARITY_PASSES_EQ = 27.0       # post packed-mask fusion
CHUNKED_PASSES_EQ = 14.0


def step_traffic_bytes(tier, b, n):
    """Estimated [B, N]-plane bytes ONE scan step moves for a tier."""
    passes = PARITY_PASSES_EQ if tier == "parity" else CHUNKED_PASSES_EQ
    return passes * b * n * 4


def bench_c1m_chunked():
    """Chunked throughput tier (top-K chunks; sampled parity, NOT
    plan-identical to the host — reported as a diagnostic artifact with
    its divergence rate, never the headline)."""
    from nomad_tpu.tpu.engine import _build_chunk_scan, chunk_schedule

    scan_bulk = _build_chunk_scan(BULK_K)
    scan_tail = _build_chunk_scan(TAIL_K)
    total = 1_000_000
    n_tgs = 8
    per_tg = total // n_tgs
    bulk = int(per_tg * 0.88)
    xs_bulk = chunk_schedule([(g, bulk) for g in range(n_tgs)], chunk=BULK_K)
    xs_tail = chunk_schedule(
        [(g, per_tg - bulk) for g in range(n_tgs)], chunk=TAIL_K, retry_rounds=12
    )
    n_steps = len(xs_bulk[0]) + len(xs_tail[0])

    def run(seed):
        n_pad, static, carry, _ = c1m_inputs(seed=seed)
        t0 = time.perf_counter()
        mid_carry, deficit, out_b = scan_bulk(n_pad, static, carry, xs_bulk)
        _, _, out_t = scan_tail(n_pad, static, mid_carry, xs_tail, deficit)
        # materialize to host: block_until_ready under-reports on some
        # tunneled backends
        placed = int(np.asarray(out_b[3]).sum() + np.asarray(out_t[3]).sum())
        return time.perf_counter() - t0, placed, n_pad

    t, placed, n_pad = run(seed=0)
    best = float("inf")
    for r in range(2):
        t, placed, n_pad = run(seed=100 + r)
        best = min(best, t)
    rate = total / best
    bps = step_traffic_bytes("chunked", 1, n_pad)
    gbps = bps * n_steps / best / 1e9
    log(
        f"C1M chunked (throughput tier, sampled parity): {total:,} in {best:.2f}s "
        f"-> {rate:,.0f} placements/s ({placed:,} placed; "
        f"~{bps/1e6:.0f}MB/step x {n_steps} steps -> {gbps:.1f} GB/s effective)"
    )
    parity = _chunked_divergence_sample()
    write_artifact("c1m-chunked", {
        "tier": "tpu_binpack_chunked",
        "placements_per_s": round(rate, 1),
        "placed": placed,
        "wall_s": round(best, 3),
        "chunk_bulk": BULK_K,
        "chunk_tail": TAIL_K,
        "bytes_per_step": bps,
        "effective_gbps": round(gbps, 2),
        "parity_sample": parity,
    })
    # dict (not a bare rate) so main() can stamp the sampled-parity
    # divergence next to the tier's rate in the round record
    return {"placements_per_s": rate, "parity_sample": parity}


def _chunked_divergence_sample(n_evals=3, n_nodes=512, p=200):
    """Production-tier sampled parity: run a few evals through the REAL
    chunked path (engine.run_chunked) and re-run every one through the
    bit-parity scan, recording the per-TG multiset divergence rate the
    engine tallies (parity_sample_stats). This is the artifact-recorded
    bound on how far the throughput tier drifts from the host oracle."""
    from nomad_tpu.tpu import engine as _eng
    from nomad_tpu.tpu.engine import (
        EncodedEval,
        TpuPlacementEngine,
        example_scan_inputs,
    )

    engine = TpuPlacementEngine.shared()
    engine.reset_parity_samples()
    _eng._PARITY_SAMPLE_RNG.seed(0xBE7C)
    for s in range(n_evals):
        n_pad, static, carry, xs = example_scan_inputs(
            n_nodes=n_nodes, n_tgs=2, n_placements=p, seed=s
        )
        static = list(static)
        static[3] = np.ones_like(static[3])  # open feasibility (C1M shape)
        f32 = lambda t: tuple(  # noqa: E731
            np.asarray(a).astype(np.float32)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a)
            for a in t
        )
        enc = EncodedEval(
            n_real=n_nodes, n_pad=n_pad, g=2, s=static[9].shape[1],
            v=static[10].shape[2], p=p, dtype=np.float32,
            static=f32(tuple(static)), carry=f32(carry), xs=xs,
            missing_list=[None] * p, nodes=[], table=None,
            start_ns=time.monotonic_ns(), dense_ok=True,
        )
        assert engine._chunk_eligible(enc) is None
        chosen, _scores, _pulls, _skipped, _evict = engine.run_chunked(enc)
        engine._maybe_sample_parity(enc, chosen, rate=1.0)
    stats = engine.parity_sample_stats()
    log(
        f"chunked sampled parity: {stats['evals_sampled']} evals, "
        f"{stats['placements_diverged']}/{stats['placements_checked']} "
        f"placements diverged (rate {stats['divergence_rate']:.4f})"
    )
    return stats


def bench_kernel_roofline(budget_s=150.0):
    """Roofline diagnostic sweep (PARITY.md §"Kernel roofline"): the
    p/B/N grids of the r5 measurement, re-run against the packed-mask
    step, with outputs materialized to host (the tunneled backend's
    block_until_ready under-reports). Each row records wall, ms/step,
    placements/s and the modeled bytes/step -> effective GB/s so the
    pass-count claim in PARITY.md is checkable from the artifact. Rows
    land incrementally; configs skipped on budget overrun are LISTED in
    the artifact rather than silently dropped."""
    import jax

    from nomad_tpu.tpu.engine import _build_batched_scan, example_scan_inputs

    grids = (
        [("p", 256, 5000, p) for p in (50, 100, 200, 400)]
        + [("B", b, 5000, 200) for b in (32, 64, 128, 256, 512)]
        + [("N", 256, n, 200) for n in (1250, 2500, 5000, 10000)]
    )
    scan = _build_batched_scan()
    rows, skipped = [], []
    t_start = time.perf_counter()
    for sweep, b, n_nodes, p in grids:
        if time.perf_counter() - t_start > budget_s:
            skipped.append({"sweep": sweep, "B": b, "N": n_nodes, "p": p})
            continue
        evals = [
            example_scan_inputs(n_nodes=n_nodes, n_tgs=2, n_placements=p,
                                seed=s % 16, dtype=np.int32)
            for s in range(b)
        ]
        n_pad = evals[0][0]
        static_b = jax.device_put(tuple(
            np.stack([e[1][i] for e in evals]) for i in range(len(evals[0][1]))
        ))
        carry_b = jax.device_put(tuple(
            np.stack([e[2][i] for e in evals]) for i in range(len(evals[0][2]))
        ))
        xs_b = jax.device_put(tuple(
            np.stack([e[3][i] for e in evals]) for i in range(len(evals[0][3]))
        ))
        np.asarray(scan(static_b, carry_b, xs_b)[1][0])  # warm compile
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            np.asarray(scan(static_b, carry_b, xs_b)[1][0])
            best = min(best, time.perf_counter() - t0)
        bps = step_traffic_bytes("parity", b, n_pad)
        row = {
            "sweep": sweep, "B": b, "N": n_nodes, "p": p,
            "wall_s": round(best, 4),
            "ms_per_step": round(best / p * 1e3, 3),
            "placements_per_s": round(b * p / best, 1),
            "bytes_per_step": bps,
            "effective_gbps": round(bps * p / best / 1e9, 2),
        }
        rows.append(row)
        log(f"roofline {sweep}-sweep B={b} N={n_nodes} p={p}: "
            f"{row['wall_s']}s, {row['placements_per_s']:,} placements/s, "
            f"{row['effective_gbps']} GB/s effective")
        # incremental persistence: a later crash keeps earned rows
        write_artifact("kernel-roofline", _roofline_payload(rows, skipped))
    write_artifact("kernel-roofline", _roofline_payload(rows, skipped))
    return rows


def _roofline_payload(rows, skipped):
    return {
        "tier": "tpu_binpack (bit-parity, packed-mask step)",
        "passes_eq_per_step": PARITY_PASSES_EQ,
        "passes_eq_per_step_pre_packing": PARITY_PASSES_EQ_PRE,
        "rows": rows, "skipped_on_budget": skipped,
    }


def bench_parity_scan_single(n_nodes=5000, n_placements=10_000):
    from nomad_tpu.tpu.engine import _build_place_scan, example_scan_inputs

    scan = _build_place_scan()
    n_pad, static, carry, xs = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=8, n_placements=n_placements, seed=0,
        dtype=np.int32,
    )
    np.asarray(scan(n_pad, static, carry, xs)[1][0])  # warm
    t0 = time.perf_counter()
    np.asarray(scan(n_pad, static, carry, xs)[1][0])
    dt = time.perf_counter() - t0
    log(
        f"single-eval parity scan: {n_placements:,} / {n_nodes} nodes in "
        f"{dt*1000:.0f}ms -> {n_placements/dt:,.0f} placements/s"
    )


# ---------------------------------------------------------------------------
# End-to-end SYSTEM benches: jobs -> broker -> workers -> engine -> plan
# queue -> raft/FSM (BASELINE benchmark configs, scaled for wall time)
# ---------------------------------------------------------------------------

def bench_system(name, n_nodes, jobs, workers=32, device_batch=16,
                 timeout=180.0, node_seed=0, warmup=None,
                 node_factory=None, expected=None, done=None,
                 deterministic=False, window_ms=None, idle_ms=None,
                 device_min_placements=None, tranches=0):
    """Run ``jobs`` through a real in-proc server; returns metrics dict.

    ``workers`` is 2x the device batch so the next wave encodes while the
    current batch is on the device. ``warmup`` (a job factory) runs one
    throwaway job through the full path first so jit compiles for this
    cluster's shape buckets land outside the timed wall (and the
    persistent XLA cache makes repeat runs cheap). ``node_factory`` and
    ``done``/``expected`` override the default cluster and completion
    check for shapes (system jobs, preemption) where per-TG counts don't
    describe the goal.

    Gather-cadence knobs (``window_ms``/``idle_ms``/
    ``device_min_placements``) default to None = the PRODUCTION
    ServerConfig defaults, so what a bench row measures by default is
    what an operator actually gets; rows that pass explicit values are
    measuring a deliberate experiment and record it in batcher_config."""
    from nomad_tpu import mock
    from nomad_tpu.server.fsm import NODE_REGISTER
    from nomad_tpu.server.server import Server, ServerConfig

    if window_ms is None:
        window_ms = ServerConfig.device_batch_window_ms
    if idle_ms is None:
        idle_ms = ServerConfig.device_batch_idle_ms
    if device_min_placements is None:
        device_min_placements = ServerConfig.device_min_placements

    rng = np.random.default_rng(node_seed)
    server = Server(ServerConfig(
        num_schedulers=0, device_batch=device_batch,
        device_batch_window_ms=window_ms, device_batch_idle_ms=idle_ms,
        deterministic=deterministic,
        device_min_placements=device_min_placements,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
        flight_spill_dir=_ARTIFACT_DIR,
    ), name=name)
    server.start()
    # crash/timeout insurance: the bottleneck report flushes from the
    # normal path below, this config's finally, or process atexit —
    # whichever comes first
    _PENDING_FLIGHT[name] = lambda: _flush_flight(name, server)
    try:
        if node_factory is not None:
            node_factory(server, n_nodes, rng)
        else:
            for i in range(n_nodes):
                n = mock.node()
                n.name = f"bench-{i}"
                n.node_resources.cpu_shares = int(rng.choice([4000, 8000, 16000]))
                n.node_resources.memory_mb = int(rng.choice([8192, 16384, 32768]))
                n.compute_class()
                server.raft_apply(NODE_REGISTER, n)

        if expected is None:
            expected = sum(tg.count for job in jobs for tg in job.task_groups)

        from nomad_tpu.server.worker import Worker

        for i in range(workers):
            w = Worker(server, i)
            server.workers.append(w)
            w.start()

        if warmup is not None:
            wjobs = warmup()
            if not isinstance(wjobs, list):
                wjobs = [wjobs]
            for wjob in wjobs:
                server.register_job(wjob)
            deadline = time.perf_counter() + 120
            def warm_done():
                for wjob in wjobs:
                    allocs = server.fsm.state.allocs_by_job(
                        "default", wjob.id, True)
                    if sum(1 for a in allocs if a.desired_status == "run") \
                            < sum(tg.count for tg in wjob.task_groups):
                        return False
                return True
            while time.perf_counter() < deadline and not warm_done():
                time.sleep(0.05)
            for wjob in wjobs:
                server.deregister_job("default", wjob.id, purge=False)
            # wait until the stop evals actually land: lingering warmup
            # allocs would both hold capacity and pollute placed()
            deadline = time.perf_counter() + 60
            def warm_stopped():
                for wjob in wjobs:
                    allocs = server.fsm.state.allocs_by_job(
                        "default", wjob.id, True)
                    if any(a.desired_status == "run" for a in allocs):
                        return False
                return True
            while time.perf_counter() < deadline and not warm_stopped():
                time.sleep(0.05)
            for w in server.workers:
                w.stats["evals_processed"] = 0
            if server.device_batcher is not None:
                # background bucket compiles must not steal device time
                # from the measured window
                server.device_batcher.wait_warm(timeout=120)
                for k in server.device_batcher.stats:
                    server.device_batcher.stats[k] = 0

        from nomad_tpu.trace import attribution
        from nomad_tpu.trace import lifecycle as _lifecycle
        from nomad_tpu.utils import phases

        # attribution covers the MEASURED window: drop boot/warmup spans
        _lifecycle.reset()
        phases.enable()
        p_t0 = phases.now()
        t0 = time.perf_counter()

        def placed():
            # O(table + blocks): never materializes dense allocs — a
            # 50ms poll over state.allocs() would fight the workers for
            # the GIL and depress the number being measured
            return server.fsm.state.count_allocs_desired_run()

        if tranches and tranches > 1:
            # SUSTAINED ingest (the C1M challenge scheduled its million
            # containers as a continuous stream, not one atomic burst):
            # submit the job list in ``tranches`` groups, releasing the
            # next once the previous is ~placed. Keeps optimistic-
            # concurrency collision cohorts at tranche size — a big-bang
            # submission of ~1K evals makes every same-epoch eval replay
            # a near-identical greedy trajectory once score ties thin
            # out, and the rejected fraction cascades into retry storms
            # (measured: >50% of placements at 1M). The registration
            # thread streams during the timed window; the wall clock
            # covers full convergence of every tranche.
            per = (len(jobs) + tranches - 1) // tranches
            groups = [jobs[i:i + per] for i in range(0, len(jobs), per)]

            def feeder():
                cum = 0
                for gi, group in enumerate(groups):
                    with phases.track("register"):
                        for job in group:
                            server.register_job(job)
                    group_count = sum(
                        tg.count for job in group for tg in job.task_groups
                    )
                    cum += group_count
                    # overlap gate: release tranche k+1 once tranche k is
                    # ~half placed, so its snapshot/encode work overlaps
                    # tranche k's device+commit tail. The old ~99% settle
                    # gate serialized tranches — the pipeline drained dry
                    # during every commit tail and the workers sat in the
                    # gather, which is where r05's ~500s untracked idle
                    # came from. Collision cohorts stay tranche-sized:
                    # overlapping halves touch disjoint job sets.
                    gate = cum - max(50, group_count // 2)
                    g_deadline = time.perf_counter() + timeout
                    while (placed() < gate
                           and time.perf_counter() < g_deadline):
                        time.sleep(0.02)

            feeder_t = threading.Thread(target=feeder, daemon=True)
            feeder_t.start()
        else:
            with phases.track("register"):
                for job in jobs:
                    server.register_job(job)

        deadline = time.perf_counter() + timeout
        finished = done if done is not None else (
            lambda srv: placed() >= expected
        )
        completed = False
        next_snap = t0 + 5.0
        while time.perf_counter() < deadline:
            if finished(server) and server.plan_queue.stats()["depth"] == 0:
                completed = True
                break
            if time.perf_counter() >= next_snap:
                # in-flight progress snapshot: if the run dies mid-window
                # (360s headline), the artifact still shows how far it got
                # and where the wall time was going
                next_snap = time.perf_counter() + 5.0
                el = time.perf_counter() - t0
                got_now = placed()
                write_artifact(f"{name}.progress", {
                    "config": name,
                    "placements": got_now,
                    "expected": expected,
                    "elapsed_s": round(el, 2),
                    "placements_per_s": round(got_now / el, 1) if el else 0.0,
                    "phases": phases.wall_shares(p_t0, phases.now()),
                    # in-flight critical-path ledger: a run that dies
                    # mid-window still shows WHERE the wall was going
                    "bottleneck": attribution.bottleneck_report(top_n=5),
                })
            # 5ms poll: the completion check is O(table); at 50ms the poll
            # granularity itself dominates sub-second configs
            time.sleep(0.005)
        elapsed = time.perf_counter() - t0
        phase_shares = phases.wall_shares(p_t0, phases.now())
        phases.disable()
        got = placed()
        evals = sum(w.stats["evals_processed"] for w in server.workers)
        db = server.device_batcher.stats if server.device_batcher else {}
        out = {
            "config": name,
            "nodes": n_nodes,
            "placements": got,
            "expected": expected,
            # "ok" = completion predicate met inside the budget; "timeout"
            # = the window expired first (the artifact still carries
            # whatever was placed). The headline record surfaces this as
            # headline_status so a budget overrun is machine-readable
            # instead of inferable from placements < expected.
            "status": "ok" if completed else "timeout",
            "wall_s": round(elapsed, 2),
            "placements_per_s": round(got / elapsed, 1),
            "evals_per_s": round(evals / elapsed, 1),
            "device_dispatches": db.get("dispatches", 0),
            "device_evals": db.get("evals", 0),
            "max_eval_batch": db.get("max_batch_seen", 0),
            "workers": workers,
            # wave formation: did dispatches actually fill the eval
            # batch? fill_ratio near 1.0 means the broker/gather kept
            # max_eval_batch evals in flight per wave; near 1/batch
            # means the device ran single-eval waves (r05's failure
            # mode: 328 evals over 21 dispatches against a 64 cap).
            "wave_fill": {
                "device_batch": device_batch,
                "gathers": db.get("gathers", 0),
                "full_gathers": db.get("full_gathers", 0),
                "mean_eval_batch": round(
                    db.get("evals", 0) / db["dispatches"], 2
                ) if db.get("dispatches") else 0.0,
                "fill_ratio": round(
                    db.get("evals", 0) / db["dispatches"] / device_batch, 3
                ) if db.get("dispatches") and device_batch else 0.0,
            },
            # wall-clock share (interval UNION across threads, not a
            # thread-sum) each pipeline phase held during the window
            "phases": phase_shares,
            # gather/routing knobs this row ran with, so rows measuring
            # the PRODUCTION ServerConfig defaults are distinguishable
            # from bench-tuned gather windows
            "batcher_config": {
                "device_min_placements": device_min_placements,
                "window_ms": window_ms,
                "idle_ms": idle_ms,
            },
        }
        if server.device_batcher:
            prof = server.device_batcher.dispatch_profile()
            out["dispatch_profile"] = prof
            # roofline companion to the pad_stack/compute/transfer split:
            # modeled [B, N]-plane traffic per step for this config's
            # average dispatch (estimate — n_pad rides close to n_nodes)
            evals_avg = (
                prof.get("evals", 0) / prof["dispatches"]
                if prof.get("dispatches") else 0.0
            )
            bps = step_traffic_bytes("parity", max(evals_avg, 1.0), n_nodes)
            out["roofline"] = {
                "tier": "tpu_binpack (bit-parity, packed-mask step)",
                "passes_eq_per_step": PARITY_PASSES_EQ,
                "bytes_per_step_est": int(bps),
                "evals_per_dispatch_avg": round(evals_avg, 1),
            }
        # chunked-tier sampled-parity tally, when this run exercised it
        from nomad_tpu.tpu.engine import TpuPlacementEngine

        if TpuPlacementEngine._shared is not None:
            stats = TpuPlacementEngine._shared.parity_sample_stats()
            if stats["evals_sampled"]:
                out["parity_sample"] = stats
        report = _flush_flight(name, server)
        _PENDING_FLIGHT.pop(name, None)
        if report is not None:
            # one-line bottleneck verdict rides the config record (the
            # full ranked ledger is the {name}.bottleneck artifact); the
            # ranked component list also rides along so BENCH_r06 can
            # embed it without re-reading artifacts
            out["bottleneck"] = report.get("top")
            out["bottleneck_ranked"] = report.get("entries")
            out["attribution_coverage"] = report.get("coverage")
        log(f"system[{name}]: {json.dumps(out)}")
        write_artifact(name, out)
        return out
    finally:
        # exception/timeout path: flush whatever the recorder has before
        # the server (and its flight thread) goes down
        fn = _PENDING_FLIGHT.pop(name, None)
        if fn is not None:
            fn()
        server.stop()


def c1m_mixed_jobs(total=1_000_000):
    """BASELINE config 5 AS WRITTEN (BASELINE.md line 30): mixed
    service+batch, heterogeneous asks and counts, affinity+spread
    stanzas on a meaningful fraction, 1M ACTUAL placements over 5K
    nodes, the full rank stack (the stack the reference always runs,
    scheduler/stack_oss.go:6-81: job anti-affinity, spread, affinity,
    binpack, limit). 40 job templates — 28 service (10 with
    spread+affinity stanzas, ~25%% of jobs) + 12 batch — instantiated
    round-robin until the placement count is exactly ``total``.
    Capacity is fleet-scale (~30%% util at 1M), matching the C1M
    challenge's 1M-containers-on-5K-hosts shape."""
    from nomad_tpu import mock
    from nomad_tpu.structs import Affinity, Spread, SpreadTarget
    from nomad_tpu.structs.structs import Resources

    cpus = [8, 12, 16, 20]
    mems = [16, 24, 32, 48]
    counts_svc = [900, 950, 1000]   # all pad into the p=1024 scan bucket
    counts_batch = [950, 1000]
    templates = []
    for t in range(28):
        templates.append(dict(
            kind="service", cpu=cpus[t % 4], mem=mems[(t // 4) % 4],
            count=counts_svc[t % 3], stanzas=t < 10,
        ))
    for t in range(12):
        templates.append(dict(
            kind="batch", cpu=cpus[t % 4], mem=mems[t % 4],
            count=counts_batch[t % 2], stanzas=False,
        ))

    def mk_job(tpl, job_id, count):
        j = mock.job() if tpl["kind"] == "service" else mock.batch_job()
        j.id = job_id
        tg = j.task_groups[0]
        tg.count = count
        tg.ephemeral_disk.size_mb = 50
        tg.tasks[0].resources = Resources(cpu=tpl["cpu"], memory_mb=tpl["mem"])
        if tpl["stanzas"]:
            tg.spreads = [Spread(
                attribute="${node.datacenter}", weight=50,
                spread_target=[SpreadTarget(value="dc1", percent=100)],
            )]
            tg.affinities = [Affinity(
                ltarget="${attr.kernel.name}", rtarget="linux",
                operand="=", weight=50,
            )]
        return j

    jobs = []
    placed = 0
    i = 0
    while placed < total:
        tpl = templates[i % len(templates)]
        count = min(tpl["count"], total - placed)
        jobs.append(mk_job(tpl, f"c1m-{i}", count))
        placed += count
        i += 1
    return jobs, templates, mk_job


def bench_c1m_system():
    """The HEADLINE: BASELINE config 5 replayed IN FULL through the real
    system on one chip — 1M actual placements (no extrapolating from a
    smaller run), mixed service+batch with heterogeneous asks/counts and
    spread+affinity stanzas on ~25%% of jobs, over 5K heterogeneous
    nodes; deterministic int-spec scoring with per-eval ring
    decorrelation; ~1K evals ride eval-batched device dispatches (the
    adaptive gather covers the single-flight encode phase); placements
    flow as dense arrays through plan apply and the FSM. The JSON's
    ``phases`` record the measured wall share of every pipeline phase —
    the v5e-8 extrapolation in main() is computed from THOSE, not from
    an assumed per-chip proration.

    NOMAD_BENCH_C1M_TOTAL scales the placement count down for CI/local
    validation of the mechanics (wave fill, coverage, BENCH_r06 shape);
    the default 1M is the measured headline."""
    total = int(os.environ.get("NOMAD_BENCH_C1M_TOTAL", "1000000"))
    jobs, templates, mk_job = c1m_mixed_jobs(total=total)

    def _warm():
        # one warm job per compiled SHAPE the measured run produces:
        # plain evals and spread+affinity evals (whose union shape also
        # covers mixed co-batched dispatches); prewarm compiles their
        # batch-bucket siblings before the timed window
        plain = mk_job(templates[12], "warm-plain", templates[12]["count"])
        stanza = mk_job(templates[0], "warm-stanza", templates[0]["count"])
        return [plain, stanza]

    # Sustained 16-tranche ingest (see bench_system): tranche-sized
    # collision cohorts keep the optimistic-concurrency rejection rate
    # near zero, every dispatch rides the warm (b=64, p=1024) compile
    # bucket, and the wall covers full convergence of all 1M
    # placements. Gather cadence is the PRODUCTION default (demand-aware
    # window, 2s backstop): r05 proved that a bespoke 15s window +
    # 600ms idle gap left workers parked in the gather for ~500s of the
    # 600s wall, so the headline now runs exactly what
    # service-prod-defaults-5K measures — if the defaults can't carry
    # the headline, the defaults are the bug. 128 workers (2x the
    # 64-eval batch) keep a full next wave encoding while the current
    # one is on device. The 360s internal budget is the acceptance bar:
    # overruns surface as headline_status="timeout" in the artifact
    # rather than eating the whole bench wall.
    return bench_system(
        "c1m-mixed-1M", 5000, jobs, workers=128, device_batch=64,
        timeout=360.0, deterministic=True,
        warmup=_warm, tranches=16,
    )


def bench_plan_queue_drain(n_nodes=10_000, n_plans=256, per_plan=100,
                           n_submitters=16):
    """BASELINE metric #2: plan-queue drain time at 10K nodes.

    Floods the leader's plan queue from N submitter threads with dense
    plans (the C1M commit shape) and measures enqueue->commit drain —
    the serialization point the reference instruments at
    nomad/plan_apply.go:185,369,400."""
    import threading

    from nomad_tpu import mock
    from nomad_tpu.server.fsm import NODE_REGISTER
    from nomad_tpu.server.server import Server, ServerConfig
    from nomad_tpu.structs.structs import (
        AllocatedResources,
        AllocatedSharedResources,
        AllocatedTaskResources,
        DenseTGPlacements,
        Plan,
        generate_uuids,
    )

    rng = np.random.default_rng(7)
    server = Server(ServerConfig(
        num_schedulers=0, device_batch=0,
        heartbeat_min_ttl=3600, heartbeat_max_ttl=7200,
    ))
    server.start()
    try:
        node_ids = []
        for i in range(n_nodes):
            n = mock.node()
            n.name = f"drain-{i}"
            n.compute_class()
            server.raft_apply(NODE_REGISTER, n)
            node_ids.append(n.id)

        proto = AllocatedResources(
            tasks={"web": AllocatedTaskResources(cpu_shares=15, memory_mb=30)},
            shared=AllocatedSharedResources(disk_mb=10),
        )

        def mk_plan(k):
            chosen = rng.choice(len(node_ids), size=per_plan, replace=False)
            block = DenseTGPlacements(
                namespace="default", job_id=f"drain-job-{k}",
                task_group="web", eval_id=f"drain-eval-{k}",
                resources_proto=proto, ask_vec=(15.0, 30.0, 10.0, 0.0),
                ids=generate_uuids(per_plan),
                names=[f"drain-job-{k}.web[{i}]" for i in range(per_plan)],
                node_ids=[node_ids[j] for j in chosen],
                node_names=[f"drain-{j}" for j in chosen],
                scores=[1.0] * per_plan,
                nodes_evaluated=[1] * per_plan,
            )
            return Plan(eval_id=f"drain-eval-{k}", dense_placements=[block])

        plans = [mk_plan(k) for k in range(n_plans)]
        futures = []
        fut_lock = threading.Lock()

        def submitter(idx):
            for k in range(idx, n_plans, n_submitters):
                pending = server.plan_queue.enqueue(plans[k])
                with fut_lock:
                    futures.append(pending.future)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=submitter, args=(i,))
            for i in range(n_submitters)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for f in list(futures):
            f.result(timeout=120)
        drain_s = time.perf_counter() - t0
        committed = sum(
            len(b.ids)
            for f in futures
            for b in f.result().dense_placements
        )
        out = {
            "config": "plan-queue-drain",
            "nodes": n_nodes,
            "plans": n_plans,
            "placements_committed": committed,
            "drain_s": round(drain_s, 3),
            "plans_per_s": round(n_plans / drain_s, 1),
            "placements_per_s": round(committed / drain_s, 1),
        }
        log(f"drain[10K nodes]: {json.dumps(out)}")
        write_artifact("plan-queue-drain", out)
        return out
    finally:
        server.stop()


def system_benches():
    from nomad_tpu import mock
    from nomad_tpu.structs import Spread, SpreadTarget

    results = []

    # config 1: service scheduler, 100 task-group instances / 50 nodes
    jobs = []
    for i in range(20):
        j = mock.job()
        j.id = f"svc-{i}"
        j.task_groups[0].count = 5
        j.task_groups[0].tasks[0].resources.cpu = 100
        j.task_groups[0].tasks[0].resources.memory_mb = 128
        jobs.append(j)
    def _svc_warm():
        j = mock.job()
        j.id = "warm-svc"
        j.task_groups[0].count = 2
        j.task_groups[0].tasks[0].resources.cpu = 100
        j.task_groups[0].tasks[0].resources.memory_mb = 128
        return j

    r = _diagnostic(bench_system, "service-100x50", 50, jobs, warmup=_svc_warm)
    if r:
        results.append(r)

    # config 2: batch scheduler, bin-pack only, 1K nodes, 10K short tasks
    jobs = []
    for i in range(10):
        j = mock.batch_job()
        j.id = f"batch-{i}"
        j.task_groups[0].count = 1000
        j.task_groups[0].tasks[0].resources.cpu = 20
        j.task_groups[0].tasks[0].resources.memory_mb = 32
        jobs.append(j)
    def _batch_warm():
        j = mock.batch_job()
        j.id = "warm-batch"
        j.task_groups[0].count = 1000
        j.task_groups[0].tasks[0].resources.cpu = 20
        j.task_groups[0].tasks[0].resources.memory_mb = 32
        return j

    r = _diagnostic(bench_system, "batch-10Kx1K", 1000, jobs, timeout=300.0,
                    warmup=_batch_warm)
    if r:
        results.append(r)

    # config 3: service + affinity/anti-affinity + spread stanzas at 5K
    # nodes (BASELINE.md names all three; job anti-affinity is intrinsic
    # to every multi-count service job via JobAntiAffinityIterator)
    from nomad_tpu.structs import Affinity

    def _spread_job(job_id):
        j = mock.job()
        j.id = job_id
        j.task_groups[0].count = 50
        j.task_groups[0].tasks[0].resources.cpu = 50
        j.task_groups[0].tasks[0].resources.memory_mb = 64
        j.task_groups[0].spreads = [Spread(
            attribute="${node.datacenter}", weight=50,
            spread_target=[SpreadTarget(value="dc1", percent=100)],
        )]
        j.task_groups[0].affinities = [Affinity(
            ltarget="${attr.kernel.name}", rtarget="linux",
            operand="=", weight=50,
        )]
        return j

    jobs = [_spread_job(f"spread-{i}") for i in range(10)]

    def _spread_warm():
        return _spread_job("warm-spread")

    # adaptive idle-gap gather: the 10-eval burst rides 1-2 dispatches;
    # the wall here is dominated by per-dispatch device RTT on the
    # tunneled chip (see phases in the JSON), not host work — the
    # single-flight encode cache collapses the per-eval encode
    r = _diagnostic(bench_system, "service-spread-5K", 5000, jobs, timeout=300.0,
                    idle_ms=100.0, window_ms=2000.0, warmup=_spread_warm)
    if r:
        results.append(r)

    # config 3b: the PRODUCTION batcher defaults at the 5K-node shape —
    # no gather knobs passed, so this row runs exactly what ServerConfig
    # ships (demand-aware gather, 2s backstop window, 3ms idle gap,
    # device_min_placements=24). Since r06 the headline runs these same
    # defaults, so this row is the small-shape control for the headline
    # rather than a what-an-operator-gets footnote.
    def _prod_job(job_id):
        j = mock.job()
        j.id = job_id
        j.task_groups[0].count = 100
        j.task_groups[0].tasks[0].resources.cpu = 50
        j.task_groups[0].tasks[0].resources.memory_mb = 64
        return j

    jobs = [_prod_job(f"prod-{i}") for i in range(10)]

    def _prod_warm():
        return _prod_job("warm-prod")

    r = _diagnostic(bench_system, "service-prod-defaults-5K", 5000, jobs,
                    timeout=300.0, warmup=_prod_warm)
    if r:
        results.append(r)

    # config 4: system scheduler, one-per-node, device constraints +
    # preemption (BASELINE.md list). A low-priority system job saturates
    # the fleet first; the high-priority GPU job then preempts its way on
    # (the engine's forced-node pass handles the clean placements; evals
    # needing preemption fall back to the host stack by design).
    jobs = []
    low = mock.system_job()
    low.id = "sys-low"
    low.priority = 20
    low.task_groups[0].tasks[0].resources.cpu = 900
    low.task_groups[0].tasks[0].resources.memory_mb = 512
    jobs.append(low)
    high = mock.system_job()
    high.id = "sys-high"
    high.priority = 80
    high.task_groups[0].tasks[0].resources.cpu = 600
    high.task_groups[0].tasks[0].resources.memory_mb = 256
    from nomad_tpu.structs.structs import RequestedDevice

    high.task_groups[0].tasks[0].resources.devices = [
        RequestedDevice(name="gpu", count=1)
    ]
    jobs.append(high)

    def _sys_nodes(server, n_nodes, rng):
        # every node dc1/linux so the system jobs cover the fleet; a
        # quarter carry a GPU device group
        from nomad_tpu.server.fsm import NODE_REGISTER

        for i in range(n_nodes):
            n = mock.nvidia_node() if i % 4 == 0 else mock.node()
            n.name = f"sys-{i}"
            n.datacenter = "dc1"
            n.attributes["kernel.name"] = "linux"
            n.node_resources.cpu_shares = 1200
            n.node_resources.memory_mb = 2048
            n.compute_class()
            server.raft_apply(NODE_REGISTER, n)

    sys_nodes_n = 1000
    gpu_nodes = (sys_nodes_n + 3) // 4  # _sys_nodes: every 4th node has GPUs

    def _sys_done(server):
        # done when the high-priority GPU job covers every GPU node (its
        # allocs preempted the low-priority ones there) AND the low-
        # priority job holds the rest of the fleet
        high = server.fsm.state.allocs_by_job("default", "sys-high", True)
        low = server.fsm.state.allocs_by_job("default", "sys-low", True)
        return (
            sum(1 for a in high if a.desired_status == "run") >= gpu_nodes
            and sum(1 for a in low if a.desired_status == "run")
            >= sys_nodes_n - gpu_nodes
        )

    def _sys_warm():
        # one warm job per MEASURED EVAL SHAPE: sys-low encodes without
        # device dims, sys-high with the gpu dims — each is its own
        # forced-kernel compile bucket, and both must load outside the
        # timed window (per-process first-use of a cached executable
        # still costs seconds)
        plain = mock.system_job()
        plain.id = "warm-sys"
        plain.priority = 10
        plain.task_groups[0].tasks[0].resources.cpu = 100
        plain.task_groups[0].tasks[0].resources.memory_mb = 64
        dev = mock.system_job()
        dev.id = "warm-sys-dev"
        dev.priority = 10
        dev.task_groups[0].tasks[0].resources.cpu = 100
        dev.task_groups[0].tasks[0].resources.memory_mb = 64
        dev.task_groups[0].tasks[0].resources.devices = [
            RequestedDevice(name="gpu", count=1)
        ]
        return [plain, dev]

    # steady state: every node holds exactly one alloc (high on the GPU
    # nodes after preempting low, low on the rest)
    r = _diagnostic(bench_system, "system-preempt-1K", sys_nodes_n, jobs,
                    timeout=300.0, node_factory=_sys_nodes,
                    expected=sys_nodes_n, done=_sys_done, warmup=_sys_warm)
    if r:
        results.append(r)

    return results


# ---------------------------------------------------------------------------
# chaos-churn-5K: sustained churn + injected faults + leader kill, with
# pass/fail SLO gates (tail latency, throughput floor, state invariants)
# ---------------------------------------------------------------------------

def _stitched_headline(result):
    """Compact nomad-xtrace summary for the headline record (the full
    stitched block, sample tree included, lives in the artifact)."""
    st = result.get("stitched") or {}
    rep = st.get("report") or {}
    return {
        "processes": st.get("processes"),
        "span_count": st.get("span_count"),
        "trace_count": st.get("trace_count"),
        "coverage": rep.get("coverage"),
        "components": {
            e["component"]: e["seconds"] for e in rep.get("entries") or []
        },
    }


def bench_chaos_churn(name="chaos-churn-5K", seed=0, duration_s=30.0,
                      n_nodes=250, settle_timeout_s=90.0):
    """Replay the default-seed churn trace against a live 3-server
    cluster: ~5K placements created across overlapping registration/stop
    waves, destructive rollouts, drains, heartbeat TTL expiries, armed
    fault windows on every injection point, and a mid-run leader kill.
    The SLO gate turns the run's nomad-trace gauges, throughput, and
    post-run invariant sweep into a recorded pass/fail — tail latency
    under churn, where the BENCH_r* burst configs measure cold-start
    throughput only."""
    from nomad_tpu.chaos import ChurnReplay, SLOGate, SLOThresholds
    from nomad_tpu.chaos.trace import generate_trace, trace_to_jsonable
    from nomad_tpu.server import ServerConfig

    trace = generate_trace(
        seed=seed, duration_s=duration_s, n_nodes=n_nodes,
        n_jobs=60, tg_count=50, stop_frac=0.3, rollout_frac=0.25,
        n_drains=3, n_expiries=2, n_hipri=2, n_fault_windows=4,
        canary_frac=0.25, n_preempt_waves=1,
        leader_kill=True,
    )
    log(f"{name}: {len(trace)} trace events over {duration_s:.0f}s, "
        f"{n_nodes} nodes, seed {seed}")
    replay = ChurnReplay(
        seed=seed, trace=trace, n_servers=3, n_nodes=n_nodes,
        config=ServerConfig(
            num_schedulers=2,
            heartbeat_min_ttl=1.5,
            heartbeat_max_ttl=2.5,
            eval_gc_interval=3600.0,
            watchdog_stall_s=10.0,
            # leader's flight recorder spills chaos-s*.flight.jsonl
            # under the artifact dir alongside the SLO record
            flight_spill_dir=_ARTIFACT_DIR,
        ),
        settle_timeout_s=settle_timeout_s,
        # pre-compile the trace's padded eval shapes (tg counts 50 and
        # the 25-count hipri arrivals) outside the measured window
        warmup_counts=(50, 25),
    )
    t0 = time.monotonic()
    result = replay.run()
    wall = time.monotonic() - t0

    # calibrated against the CPU-backend floor of this config (a tunneled
    # chip's dispatch RTT dominates eval_ms the same way): p99 well under
    # the broker's nack timeout, no in-flight eval older than the
    # pipeline ack bound, and a sustained placement floor that a wedged
    # broker or hot-looping retry path cannot meet
    gate = SLOGate(SLOThresholds(
        eval_ms_p99_max=5_000.0,
        slowest_inflight_ms_max=30_000.0,
        throughput_min_allocs_per_s=25.0,
        # the run's critical-path ledger must account for >=90% of the
        # churn makespan or its bottleneck claim is untrustworthy
        attribution_coverage_min=0.9,
    ))
    slo = gate.evaluate(result)
    record = {
        "config": name,
        "seed": seed,
        "wall_s": round(wall, 2),
        "slo": slo,
        "result": result,
        "trace": trace_to_jsonable(trace),
    }
    write_artifact(name, record)
    status = "PASS" if slo["passed"] else "FAIL"
    bottleneck = (result.get("bottleneck_report") or {}).get("top")
    log(f"{name}: {status} — {result['total_allocs']} allocs "
        f"({result['throughput_allocs_per_s']}/s), p99 "
        f"{result['trace_summary'].get('eval_ms_p99')}ms, "
        f"{result['events_degraded']} degraded events, "
        f"{result['leader_kills']} leader kill(s), faults "
        f"{result['fault_fires']}, bottleneck: {bottleneck}")
    for check in slo["checks"]:
        log(f"  slo[{check['name']}]: observed={check['observed']} "
            f"bound={check['bound']} passed={check['passed']}")
    # headline-record summary (the full result lives in the artifact)
    return {
        "config": name,
        "slo_passed": slo["passed"],
        "total_allocs": result["total_allocs"],
        "throughput_allocs_per_s": result["throughput_allocs_per_s"],
        "eval_ms_p99": result["trace_summary"].get("eval_ms_p99"),
        "slowest_inflight_ms": result["trace_summary"].get(
            "slowest_inflight_ms"),
        "invariants": result["invariants"],
        "fault_fires": result["fault_fires"],
        "leader_kills": result["leader_kills"],
        "events_degraded": result["events_degraded"],
        "bottleneck": bottleneck,
        "attribution_coverage": (
            result.get("bottleneck_report") or {}).get("coverage"),
        "stitched": _stitched_headline(result),
        "rpc_table": ((result.get("rpc") or {}).get("cluster")) or {},
        "wall_s": round(wall, 2),
    }


# ---------------------------------------------------------------------------
# chaos-crash-5K: real-process SIGKILL failover under churn load, with
# MTTR SLO gates (new-leader election, first post-failover commit) and a
# forced snapshot-install rejoin of the killed server
# ---------------------------------------------------------------------------

def bench_chaos_crash(name="chaos-crash-5K", seed=0, duration_s=25.0,
                      n_nodes=120, settle_timeout_s=150.0):
    """Replay a churn trace against three REAL server OS processes (each
    with its own durable data dir), SIGKILL -9 the leader mid-trace, and
    gate on recovery: time to a new leader, time to the first committed
    write through it, and the killed server restarting into a
    snapshot-install rejoin (the leader compacts its log while the node
    is down, so catch-up must take the InstallSnapshot path, not plain
    log replay). The invariant sweep then runs per-replica over RPC —
    identical desired-run counts on all three data dirs is the whole
    point. chaos-churn-5K measures degradation under in-proc faults;
    this config measures process-death recovery with nothing shared."""
    from nomad_tpu.chaos import CrashReplay, SLOGate, SLOThresholds
    from nomad_tpu.chaos.trace import generate_trace, trace_to_jsonable

    # fault windows are per-process (the injector can't reach into the
    # children) and canaried rollouts need the in-proc deployment nurse,
    # so the crash trace runs with both off; the leader kill is the fault
    trace = generate_trace(
        seed=seed, duration_s=duration_s, n_nodes=n_nodes,
        n_jobs=40, tg_count=25, stop_frac=0.25, rollout_frac=0.2,
        n_drains=2, n_expiries=2, n_hipri=2, n_fault_windows=0,
        n_preempt_waves=1, leader_kill=True,
    )
    log(f"{name}: {len(trace)} trace events over {duration_s:.0f}s, "
        f"{n_nodes} nodes, 3 server processes, seed {seed}")
    replay = CrashReplay(
        seed=seed, trace=trace, n_servers=3, n_nodes=n_nodes,
        settle_timeout_s=settle_timeout_s,
    )
    t0 = time.monotonic()
    result = replay.run()
    wall = time.monotonic() - t0

    # recovery bounds: election timeout is 0.5-1.0s per attempt, so 5s of
    # MTTR covers several split-vote rounds before failing; first commit
    # adds RPC retry/forwarding discovery on top. Latency/throughput gates
    # are owned by chaos-churn-5K (in-proc, 250 nodes) — here the only
    # floor is "the cluster still places work through the failover".
    gate = SLOGate(SLOThresholds(
        eval_ms_p99_max=None,
        slowest_inflight_ms_max=None,
        throughput_min_allocs_per_s=5.0,
        failover_new_leader_ms_max=5_000.0,
        failover_first_commit_ms_max=10_000.0,
        require_rejoin=True,
        # the stitched MULTI-PROCESS ledger (spans drained from every
        # replica over Trace.Export, clock-aligned) must account for
        # >=90% of its makespan — the cross-process wire-time claim
        # (rpc_wait / forward_hop) is only trustworthy above this floor
        stitched_attribution_coverage_min=0.9,
    ))
    slo = gate.evaluate(result)
    record = {
        "config": name,
        "seed": seed,
        "wall_s": round(wall, 2),
        "slo": slo,
        "result": result,
        "trace": trace_to_jsonable(trace),
    }
    write_artifact(name, record)
    failover = result.get("failover") or {}
    status = "PASS" if slo["passed"] else "FAIL"
    log(f"{name}: {status} — {result['total_allocs']} allocs "
        f"({result['throughput_allocs_per_s']}/s), new leader in "
        f"{failover.get('time_to_new_leader_ms')}ms, first commit in "
        f"{failover.get('time_to_first_commit_ms')}ms, rejoined="
        f"{failover.get('rejoined')} via {failover.get('snapshot_installs')}"
        f" snapshot install(s)")
    for check in slo["checks"]:
        log(f"  slo[{check['name']}]: observed={check['observed']} "
            f"bound={check['bound']} passed={check['passed']}")
    stitched = _stitched_headline(result)
    log(f"{name}: stitched {stitched['span_count']} spans / "
        f"{stitched['trace_count']} traces across {stitched['processes']}, "
        f"coverage {stitched['coverage']}, components {stitched['components']}")
    return {
        "config": name,
        "slo_passed": slo["passed"],
        "total_allocs": result["total_allocs"],
        "throughput_allocs_per_s": result["throughput_allocs_per_s"],
        "invariants": result["invariants"],
        "leader_kills": result["leader_kills"],
        "time_to_new_leader_ms": failover.get("time_to_new_leader_ms"),
        "time_to_first_commit_ms": failover.get("time_to_first_commit_ms"),
        "restart_catchup_ms": failover.get("restart_catchup_ms"),
        "snapshot_installs": failover.get("snapshot_installs"),
        "rejoined": failover.get("rejoined"),
        "stitched": stitched,
        "rpc_table": ((result.get("rpc") or {}).get("cluster")) or {},
        "wall_s": round(wall, 2),
    }


# ---------------------------------------------------------------------------
# capacity-pressure-5K: saturation waves park evals in BlockedEvals, then
# node-registration bursts storm them back out through the coalesced
# unblock path while the leader's autoscaler covers the remainder — gated
# on unblock-to-place latency, storm flatline, and drain-to-zero
# ---------------------------------------------------------------------------

def bench_capacity_pressure(name="capacity-pressure-5K", seed=0,
                            duration_s=30.0, n_nodes=100,
                            settle_timeout_s=180.0):
    """Replay a trace whose job load starts near the fleet's capacity
    ceiling (~85% cpu-committed), then submit two saturation waves sized
    well past it: those placements fail and their evals park in
    BlockedEvals. Each wave's paired capacity_release registers a burst
    of fresh nodes — every registration fires the capacity-change
    trigger, so the parked evals re-enqueue as an unblock storm through
    the coalesced batch path — and the leader's autoscaler watches
    blocked depth and registers whatever the releases didn't cover. The
    gate reads the saturated-regime surfaces chaos-churn-5K never
    exercises: unblock-to-place p99, placement flatline while blocked,
    batch-size mean (the storm must demonstrably coalesce), and blocked
    depth drained to <=1% of peak by measurement time. Fault windows are
    off — pressure here is capacity, not injected failure; the mid-run
    leader kill stays (parked evals must survive a leadership transfer
    via eval restore on the new leader)."""
    from nomad_tpu.chaos import ChurnReplay, SLOGate, SLOThresholds
    from nomad_tpu.chaos.trace import generate_trace, trace_to_jsonable
    from nomad_tpu.server import ServerConfig

    # sizing: ~1400 background allocs at 250cpu fill ~93% of the fleet's
    # usable slots (15 per node after the reserved share), so each
    # 15-job saturation wave (600 allocs, ~40 nodes' worth) parks well
    # past free capacity; the two 30-node releases cover most of it and
    # the autoscaler's steps close the remainder
    trace = generate_trace(
        seed=seed, duration_s=duration_s, n_nodes=n_nodes,
        n_jobs=35, tg_count=40, stop_frac=0.2, rollout_frac=0.15,
        n_drains=2, n_expiries=2, n_hipri=1, n_fault_windows=0,
        leader_kill=True, cpu=250, memory_mb=128,
        n_saturate_waves=2, saturate_jobs=15, release_nodes=30,
    )
    log(f"{name}: {len(trace)} trace events over {duration_s:.0f}s, "
        f"{n_nodes} nodes, 2 saturation waves, seed {seed}")
    replay = ChurnReplay(
        seed=seed, trace=trace, n_servers=3, n_nodes=n_nodes,
        config=ServerConfig(
            num_schedulers=2,
            heartbeat_min_ttl=1.5,
            heartbeat_max_ttl=2.5,
            eval_gc_interval=3600.0,
            watchdog_stall_s=10.0,
            flight_spill_dir=_ARTIFACT_DIR,
            # storm path: coalesce per-trigger unblocks for 50ms, cap
            # each batched enqueue (the spike bound under test)
            unblock_coalesce_window_s=0.05,
            unblock_max_batch=256,
            # leader-side autoscaler: tick at 2Hz, add up to 8 nodes per
            # 1s cooldown while evals stay parked (each saturate job
            # spans ~2.6 nodes, so evals_per_node=1 under-provisions per
            # step and the releases + repeated steps share the work)
            autoscaler_interval_s=0.5,
            autoscaler_cooldown_s=1.0,
            autoscaler_max_step=8,
            autoscaler_evals_per_node=1,
        ),
        settle_timeout_s=settle_timeout_s,
        autoscale=True,
        warmup_counts=(40, 20),
    )
    t0 = time.monotonic()
    result = replay.run()
    wall = time.monotonic() - t0

    # eval-latency gates are owned by chaos-churn-5K and deliberately OFF
    # here: a parked eval's lifecycle spans its whole blocked wait, so
    # eval_ms p99 in a saturated run measures time-to-capacity, which
    # unblock_to_place_ms_p99 bounds directly. The saturated regime's
    # gates: evals must actually have parked (else the config measured
    # nothing), placement must follow capacity within 10s at p99, the
    # storm must never starve the pipeline for >5s while work is parked,
    # and the blocked ledger must be drained by the time the gate reads it
    gate = SLOGate(SLOThresholds(
        eval_ms_p99_max=None,
        slowest_inflight_ms_max=None,
        throughput_min_allocs_per_s=20.0,
        attribution_coverage_min=0.9,
        blocked_peak_min=4,
        unblock_to_place_p99_ms_max=10_000.0,
        storm_flatline_s_max=5.0,
        blocked_drain_frac_max=0.01,
        unblock_batch_mean_min=1.5,
    ))
    slo = gate.evaluate(result)
    record = {
        "config": name,
        "seed": seed,
        "wall_s": round(wall, 2),
        "slo": slo,
        "result": result,
        "trace": trace_to_jsonable(trace),
    }
    write_artifact(name, record)
    cap = result.get("capacity") or {}
    status = "PASS" if slo["passed"] else "FAIL"
    bottleneck = (result.get("bottleneck_report") or {}).get("top")
    log(f"{name}: {status} — {result['total_allocs']} allocs "
        f"({result['throughput_allocs_per_s']}/s), blocked peak "
        f"{cap.get('peak_blocked')}, unblock->place p99 "
        f"{cap.get('unblock_to_place_ms_p99')}ms, batch mean "
        f"{cap.get('unblock_batch_size_mean')}, flatline "
        f"{cap.get('max_flatline_s_while_blocked')}s, drain frac "
        f"{cap.get('blocked_drain_frac')}, autoscaled "
        f"{cap.get('autoscaled_nodes')} node(s), bottleneck: {bottleneck}")
    for check in slo["checks"]:
        log(f"  slo[{check['name']}]: observed={check['observed']} "
            f"bound={check['bound']} passed={check['passed']}")
    return {
        "config": name,
        "slo_passed": slo["passed"],
        "total_allocs": result["total_allocs"],
        "throughput_allocs_per_s": result["throughput_allocs_per_s"],
        "eval_ms_p99": result["trace_summary"].get("eval_ms_p99"),
        "blocked_peak": cap.get("peak_blocked"),
        "unblock_to_place_ms_p99": cap.get("unblock_to_place_ms_p99"),
        "unblock_batch_size_mean": cap.get("unblock_batch_size_mean"),
        "unblock_batches": cap.get("unblock_batches"),
        "blocked_drain_frac": cap.get("blocked_drain_frac"),
        "max_flatline_s_while_blocked": cap.get(
            "max_flatline_s_while_blocked"),
        "autoscaled_nodes": cap.get("autoscaled_nodes"),
        "invariants": result["invariants"],
        "leader_kills": result["leader_kills"],
        "bottleneck": bottleneck,
        "attribution_coverage": (
            result.get("bottleneck_report") or {}).get("coverage"),
        "stitched": _stitched_headline(result),
        "rpc_table": ((result.get("rpc") or {}).get("cluster")) or {},
        "wall_s": round(wall, 2),
    }


# ---------------------------------------------------------------------------
# serve-100Kwatch: the read-serving config — a 5K-thread blocking-watcher
# army over real RPC against the 3-process cluster while the churn trace
# runs, gated on wakeup tail latency, zero lost wakeups, and followers
# carrying the majority of the read traffic as allow_stale local serves
# ---------------------------------------------------------------------------

def bench_serve_watch(name="serve-100Kwatch", seed=0, duration_s=22.0,
                      n_nodes=60, n_watchers=5120, settle_timeout_s=240.0):
    """Park >=5K concurrent blocking queries (``Eval.GetEval`` with
    ``min_query_index``) across three real server processes — two thirds
    pinned to FOLLOWERS as ``allow_stale`` reads served by the
    follower's own FSM and watch hub — and drive churn underneath. A
    beacon writer commits rotating key groups through ``Eval.Update``
    (which returns the raft index) into a ledger; every watch return is
    judged against it: covered commit -> wakeup (latency = return -
    max(park, commit)), deadline-shaped return sitting on an old covered
    commit -> LOST (gate: zero). Concurrency is sampled from per-replica
    ``Watch.Stats`` each tick, not assumed from thread count. The name
    is the 100K-capacity claim (hub registry bound per replica); the
    seed-0 config proves the serving path at 5K real parked threads,
    which is where this container's core count stops lying."""
    from nomad_tpu.chaos import SLOGate, SLOThresholds
    from nomad_tpu.chaos.trace import generate_trace, trace_to_jsonable
    from nomad_tpu.watch.serve import ServeReplay

    # no leader kill (watchers pin replicas by role) and no fault
    # windows (per-process injector); churn here is load, not failure
    # churn here is background load, not the product under test (the
    # placement SLOs live in chaos-churn-5K): sized so the replica
    # schedulers converge on one core while the serving army eats a
    # fixed ~220 RPCs/s of the same GIL
    trace = generate_trace(
        seed=seed, duration_s=duration_s, n_nodes=n_nodes,
        n_jobs=16, tg_count=16, stop_frac=0.2, rollout_frac=0.15,
        n_drains=1, n_expiries=1, n_hipri=1, n_fault_windows=0,
        leader_kill=False,
    )
    log(f"{name}: {len(trace)} trace events over {duration_s:.0f}s, "
        f"{n_nodes} nodes, 3 server processes, {n_watchers} watchers, "
        f"seed {seed}")
    replay = ServeReplay(
        seed=seed, trace=trace, n_servers=3, n_nodes=n_nodes,
        settle_timeout_s=settle_timeout_s, n_watchers=n_watchers,
    )
    t0 = time.monotonic()
    result = replay.run()
    wall = time.monotonic() - t0

    serve = result.get("serve") or {}
    # base gate: the cluster must still place work under the army (the
    # latency/throughput bars live in chaos-churn-5K; serving is the
    # product under test here)
    gate = SLOGate(SLOThresholds(
        eval_ms_p99_max=None,
        slowest_inflight_ms_max=None,
        throughput_min_allocs_per_s=1.0,
    ))
    slo = gate.evaluate(result)
    wake = serve.get("wakeup_ms") or {}
    serve_checks = [
        {"name": "concurrent_watchers",
         "observed": serve.get("peak_concurrent_watchers", 0),
         "bound": ">= 5000",
         "passed": serve.get("peak_concurrent_watchers", 0) >= 5000},
        {"name": "lost_wakeups",
         "observed": serve.get("lost_wakeups", -1),
         "bound": "== 0",
         "passed": serve.get("lost_wakeups", -1) == 0},
        {"name": "wakeup_p99_ms",
         "observed": wake.get("p99"),
         "bound": "<= 2000",
         "passed": (wake.get("p99") is not None
                    and wake.get("p99") <= 2000.0)},
        {"name": "follower_read_share",
         "observed": serve.get("follower_read_share", 0.0),
         "bound": ">= 0.5",
         "passed": serve.get("follower_read_share", 0.0) >= 0.5},
        {"name": "stragglers",
         "observed": serve.get("stragglers", -1),
         "bound": "== 0",
         "passed": serve.get("stragglers", -1) == 0},
    ]
    passed = slo["passed"] and all(c["passed"] for c in serve_checks)
    record = {
        "config": name,
        "seed": seed,
        "wall_s": round(wall, 2),
        "passed": passed,
        "slo": slo,
        "serve_checks": serve_checks,
        "result": result,
        "trace": trace_to_jsonable(trace),
    }
    write_artifact(name, record)
    stitched = _stitched_headline(result)
    rpc_wait_share = None
    for e in ((result.get("stitched") or {}).get("report") or {}).get(
            "entries") or []:
        if e.get("component") == "rpc_wait":
            rpc_wait_share = e.get("share")
    status = "PASS" if passed else "FAIL"
    log(f"{name}: {status} — peak {serve.get('peak_concurrent_watchers')} "
        f"parked watchers, {serve.get('wakeups')} wakeups "
        f"(p99 {wake.get('p99')}ms, max {wake.get('max')}ms), "
        f"{serve.get('lost_wakeups')} lost, coalesce ratio "
        f"{serve.get('coalesce_ratio')}, follower read share "
        f"{serve.get('follower_read_share')}, rpc_wait share "
        f"{rpc_wait_share}")
    for check in serve_checks + slo["checks"]:
        log(f"  check[{check['name']}]: observed={check['observed']} "
            f"bound={check['bound']} passed={check['passed']}")
    headline = {
        "config": name,
        "passed": passed,
        "slo_passed": slo["passed"],
        "serve_checks": serve_checks,
        "n_watchers": serve.get("n_watchers"),
        "peak_concurrent_watchers": serve.get("peak_concurrent_watchers"),
        "wakeups": serve.get("wakeups"),
        "lost_wakeups": serve.get("lost_wakeups"),
        "spurious_wakeups": serve.get("spurious_wakeups"),
        "wakeup_ms": wake,
        "coalesce_ratio": serve.get("coalesce_ratio"),
        "reads_total": serve.get("reads_total"),
        "reads_by_role": serve.get("reads_by_role"),
        "follower_read_share": serve.get("follower_read_share"),
        "beacon_commits": serve.get("beacon_commits"),
        "total_allocs": result.get("total_allocs"),
        "throughput_allocs_per_s": result.get("throughput_allocs_per_s"),
        "invariants": result.get("invariants"),
        "rpc_wait_share": rpc_wait_share,
        "stitched": stitched,
        "wall_s": round(wall, 2),
    }
    # round record at the repo root, written atomically by the bench
    # itself (same lesson as BENCH_r06: the run's own data must survive
    # an outer-harness timeout)
    try:
        root = os.path.dirname(os.path.abspath(__file__))
        tmp = os.path.join(root, ".SERVE_r01.json.tmp")
        with open(tmp, "w") as f:
            json.dump(dict(headline, round="r01"), f, indent=2,
                      sort_keys=True)
            f.write("\n")
        os.replace(tmp, os.path.join(root, "SERVE_r01.json"))
    except OSError as e:
        log(f"SERVE_r01.json write failed: {e}")
    return headline


def _diagnostic(fn, *args, **kwargs):
    """Run one diagnostic bench in isolation: a failure is reported but
    never skips later diagnostics or breaks the headline JSON line. The
    failure itself becomes an artifact, so a crashed config is diagnosable
    from disk even when stderr is lost."""
    try:
        return fn(*args, **kwargs)
    except Exception as e:
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"diagnostic bench {fn.__name__} failed: {e}")
        write_artifact(f"{fn.__name__}.error", {
            "bench": fn.__name__,
            "error": repr(e),
            "traceback": traceback.format_exc(),
        })
        return None


def main():
    # Cheap, bounded diagnostics run FIRST — kernel microbench, plan-queue
    # drain, chunked/single-scan modes, the small system configs — so a
    # crash or overrun inside the expensive headline window can never
    # erase them (they are already on disk as artifacts by the time the
    # headline starts). The headline runs LAST with its own 360s internal
    # budget and reports headline_status instead of hanging the run.
    kernel_rate = _diagnostic(bench_batched_parity_c1m, budget_s=40.0)
    if kernel_rate:
        write_artifact("kernel-rate",
                       {"placements_per_s": round(kernel_rate, 1)})
    drain = _diagnostic(bench_plan_queue_drain)
    chunked = _diagnostic(bench_c1m_chunked) or {}
    chunked_rate = chunked.get("placements_per_s", 0.0)
    _diagnostic(bench_parity_scan_single)
    _diagnostic(bench_kernel_roofline)
    sys_results = _diagnostic(system_benches) or []
    # churn/chaos SLO config rides the diagnostics tier: a chaos
    # regression (gate FAIL or crash) still yields its own artifact and a
    # complete headline record
    chaos_churn = _diagnostic(bench_chaos_churn)
    # crash-recovery config: real server processes, SIGKILL failover,
    # snapshot-install rejoin — gated on MTTR instead of tail latency
    chaos_crash = _diagnostic(bench_chaos_crash)
    # saturated-regime config: blocked-eval storms + autoscaler drain —
    # gated on unblock-to-place latency and drain-to-zero
    capacity_pressure = _diagnostic(bench_capacity_pressure)
    # read-serving config: 5K parked blocking watchers + follower stale
    # reads under churn — gated on wakeup tail, zero lost wakeups, and
    # follower read share; writes SERVE_r01.json at the repo root itself
    serve_watch = _diagnostic(bench_serve_watch)

    # HEADLINE: end-to-end system C1M replay (jobs -> broker -> workers ->
    # eval-batched engine -> plan queue -> raft/FSM), one chip.
    headline = _diagnostic(bench_c1m_system)

    if headline is None:
        # never lose the bench record: fall back to the kernel rate at
        # the per-chip bar (the r3 headline form)
        headline = {"placements_per_s": kernel_rate or 0.0,
                    "config": "kernel-fallback", "status": "timeout"}
    rate = headline["placements_per_s"] or 1e-9
    if kernel_rate:
        log(f"kernel-rate / system-rate gap: {kernel_rate / rate:,.1f}x")

    # The BASELINE bar is 1M placements in <10s on TPU v5e-8. The
    # headline above ran the FULL 1M on ONE chip; extrapolate to 8 chips
    # from the MEASURED phase wall-shares (VERDICT r4 ask #1), not an
    # assumed per-chip proration: the device phase (eval-batched scan —
    # the eval axis shards across chips with zero cross-chip traffic;
    # dryrun_multichip executes that sharding) divides by 8, every
    # host-side second (GIL-serialized encode/plan/FSM plus untracked
    # wall) is conservatively kept AS IS. vs_baseline = 10s / t_v5e8.
    phases = headline.get("phases", {})
    wall = headline.get("wall_s", 0.0) or 0.0
    placements = headline.get("placements", 0)
    dev_share = min(phases.get("device", 0.0), wall)
    if wall > 0 and placements > 0:
        t1m_single = wall * (1_000_000 / placements)
        dev_1m = dev_share * (1_000_000 / placements)
        t_v5e8 = (t1m_single - dev_1m) + dev_1m / 8.0
        vs_baseline = 10.0 / t_v5e8
    else:
        t_v5e8 = None
        vs_baseline = 0.0
    if t_v5e8 is not None:
        log(
            f"v5e-8 extrapolation from measured phases: 1M in {t_v5e8:.2f}s "
            f"(host {t1m_single - dev_1m:.2f}s held serial + device "
            f"{dev_1m:.2f}s / 8) -> vs_baseline {vs_baseline:.3f} against "
            "the <10s bar"
        )
    record = {
        "metric": (
            "BASELINE config 5 AS WRITTEN, end-to-end: 1M actual "
            "placements, mixed service+batch, heterogeneous asks/"
            "counts, spread+affinity stanzas on ~25% of jobs, full "
            "rank stack, 5K nodes, exact int-spec scoring, single "
            "chip; vs_baseline = 10s bar / v5e-8 time extrapolated "
            "from MEASURED phases (device/8, host kept serial)"
        ),
        "value": round(rate, 1),
        "unit": "placements/s",
        "vs_baseline": round(vs_baseline, 4),
        "headline_status": headline.get("status", "timeout"),
        # one-line critical-path verdict from the flight recorder: a DNF
        # ("timeout") names its own bottleneck stage right here
        "bottleneck": headline.get("bottleneck"),
        "extra": {
            "headline_config": headline,
            "v5e8_extrapolation_s": (
                round(t_v5e8, 2) if t_v5e8 is not None else None
            ),
            "extrapolation_model": (
                "t = host_wall(serial, measured) + device_wall/8"
            ),
            "kernel_placements_per_s": round(kernel_rate or 0.0, 1),
            "chunked_tier_placements_per_s": round(chunked_rate or 0.0, 1),
            # sampled-parity divergence of the throughput tier, stamped
            # next to its rate: the tier is only quotable WITH its
            # measured drift from the host oracle
            "chunked_tier_parity_sample": chunked.get("parity_sample"),
            "plan_queue_drain_10k_nodes": drain,
            "system_configs": sys_results,
            "chaos_churn": chaos_churn,
            "chaos_crash": chaos_crash,
            "capacity_pressure": capacity_pressure,
            "serve_100kwatch": serve_watch,
        },
    }
    write_artifact("headline", record)

    # Round record at the repo root, written by bench.py itself (r05's
    # lesson: the outer harness timed out and its wrapper recorded
    # parsed=null — the run's own data survived only in a stderr tail).
    # Everything the acceptance gate reads is top-level here.
    r06 = {
        "round": "r06",
        "headline_config": headline.get("config"),
        "headline_status": headline.get("status", "timeout"),
        "placements_per_s": round(rate, 1),
        "placements": placements,
        # expected != 1M marks a NOMAD_BENCH_C1M_TOTAL-scaled dry run —
        # never quote such a file as the round's measured number
        "expected": headline.get("expected"),
        "wall_s": round(wall, 2),
        "vs_baseline": round(vs_baseline, 4),
        "workers": headline.get("workers"),
        "wave_fill": headline.get("wave_fill"),
        "bottleneck": headline.get("bottleneck"),
        "bottleneck_ranked": headline.get("bottleneck_ranked"),
        "attribution_coverage": headline.get("attribution_coverage"),
        "phases": phases,
        "chunked_tier_placements_per_s": round(chunked_rate or 0.0, 1),
        "chunked_tier_parity_sample": chunked.get("parity_sample"),
        "headline_parity_sample": headline.get("parity_sample"),
        "v5e8_extrapolation_s": (
            round(t_v5e8, 2) if t_v5e8 is not None else None
        ),
    }
    try:
        root = os.path.dirname(os.path.abspath(__file__))
        tmp = os.path.join(root, ".BENCH_r06.json.tmp")
        with open(tmp, "w") as f:
            json.dump(r06, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, os.path.join(root, "BENCH_r06.json"))
    except OSError as e:
        log(f"BENCH_r06.json write failed: {e}")

    print(json.dumps(record))


if __name__ == "__main__":
    main()
