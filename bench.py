"""Benchmark: tpu_binpack placement throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline: the C1M replay — 1M containers placed across 5K nodes with the
full rank scan (bin-pack + anti-affinity + spread scoring active). The
reference's C1M challenge (hashicorp.com/c1m) targets 1M containers / 5K
nodes; BASELINE.md sets <10s on TPU v5e as the bar, i.e. 100K placements/s
(vs_baseline = measured / 100_000).

Extra diagnostics (exact-parity scan rate, host-path comparison) on stderr.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*args):
    print(*args, file=sys.stderr, flush=True)


def c1m_inputs(n_nodes=5000, total=1_000_000, n_tgs=8, seed=0):
    """1M tiny containers over 5K nodes, every score term active.
    Scores run in float32: the throughput scan's top-K ordering doesn't
    need the parity path's float64 bit-exactness, and f64 is emulated on
    TPU vector units."""
    from nomad_tpu.tpu.engine import DIM_CPU, DIM_MEM, NUM_DIMS, example_scan_inputs

    n_pad, static, carry, _ = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=n_tgs, n_placements=64, seed=seed
    )
    static = list(static)
    asks = np.zeros((n_tgs, NUM_DIMS), static[2].dtype)
    asks[:, DIM_CPU] = 15  # 5K nodes x ~3900 free MHz / 15 ≈ 1.3M capacity
    asks[:, DIM_MEM] = 30
    static[2] = asks
    static[3] = np.ones_like(static[3])  # no constraint filtering in C1M

    def f32(t):
        return tuple(
            np.asarray(a).astype(np.float32)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a)
            for a in t
        )

    return n_pad, f32(static), f32(carry), None


BULK_K = 1024  # big chunks clear ~88% of the load in few device steps
TAIL_K = 256  # small chunks + deficit retries place the exact remainder


def c1m_schedules(total=1_000_000, n_tgs=8, bulk_frac=0.88):
    from nomad_tpu.tpu.engine import chunk_schedule

    per_tg = total // n_tgs
    bulk = int(per_tg * bulk_frac)
    xs_bulk = chunk_schedule([(g, bulk) for g in range(n_tgs)], chunk=BULK_K)
    xs_tail = chunk_schedule(
        [(g, per_tg - bulk) for g in range(n_tgs)], chunk=TAIL_K, retry_rounds=12
    )
    return xs_bulk, xs_tail


def bench_c1m():
    """Hybrid two-phase scan: bulk top-1024 chunks, then top-256 chunks
    with deficit-absorbing retries for the capacity-constrained tail."""
    from nomad_tpu.tpu.engine import _build_chunk_scan

    scan_bulk = _build_chunk_scan(BULK_K)
    scan_tail = _build_chunk_scan(TAIL_K)
    total = 1_000_000
    xs_bulk, xs_tail = c1m_schedules(total)

    def run(seed):
        n_pad, static, carry, _ = c1m_inputs(seed=seed)
        t0 = time.perf_counter()
        mid_carry, deficit, out_b = scan_bulk(n_pad, static, carry, xs_bulk)
        _, _, out_t = scan_tail(n_pad, static, mid_carry, xs_tail, deficit)
        placed = int(np.asarray(out_b[3]).sum() + np.asarray(out_t[3]).sum())
        return time.perf_counter() - t0, placed

    t, placed = run(seed=0)
    log(f"C1M compile+first run: {t:.1f}s placed={placed}")

    best = float("inf")
    min_placed = placed
    for r in range(3):
        t, placed = run(seed=100 + r)
        best = min(best, t)
        min_placed = min(min_placed, placed)
    placed = min_placed
    rate = total / best
    log(
        f"C1M replay: {total:,} placements / 5K nodes in {best:.2f}s -> "
        f"{rate:,.0f} placements/s ({placed:,} placed)"
    )
    if placed != total:
        log(f"WARNING: placed {placed:,} != {total:,}")
    return rate, placed


def bench_parity_scan(n_nodes=5000, n_placements=10_000):
    """Exact-parity (1-per-step) scan rate, for the record."""
    from nomad_tpu.tpu.engine import _build_place_scan, example_scan_inputs

    scan = _build_place_scan()
    n_pad, static, carry, xs = example_scan_inputs(
        n_nodes=n_nodes, n_tgs=8, n_placements=n_placements, seed=0
    )
    np.asarray(scan(n_pad, static, carry, xs)[1][0])  # warm
    best = float("inf")
    for r in range(2):
        n_pad, static, carry, xs = example_scan_inputs(
            n_nodes=n_nodes, n_tgs=8, n_placements=n_placements, seed=100 + r
        )
        t0 = time.perf_counter()
        np.asarray(scan(n_pad, static, carry, xs)[1][0])
        best = min(best, time.perf_counter() - t0)
    log(
        f"exact-parity scan: {n_placements:,} placements / {n_nodes} nodes in "
        f"{best*1000:.0f}ms -> {n_placements/best:,.0f} placements/s"
    )


def bench_host_end_to_end(n_nodes=200, count=500):
    """Full scheduler path (harness) for context."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.testing import Harness
    from nomad_tpu.structs.structs import (
        EVAL_TRIGGER_JOB_REGISTER,
        Evaluation,
        SchedulerConfiguration,
    )

    h = Harness()
    h.state.scheduler_set_config(
        h.next_index(), SchedulerConfiguration(scheduler_algorithm="binpack")
    )
    for i in range(n_nodes):
        n = mock.node()
        n.name = f"n{i}"
        h.state.upsert_node(h.next_index(), n)
    job = mock.batch_job()
    job.task_groups[0].count = count
    job.task_groups[0].tasks[0].resources.cpu = 20
    job.task_groups[0].tasks[0].resources.memory_mb = 32
    h.state.upsert_job(h.next_index(), job)
    ev = Evaluation(
        priority=job.priority,
        type=job.type,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
        job_id=job.id,
        namespace=job.namespace,
    )
    t0 = time.perf_counter()
    h.process("batch", ev)
    dt = time.perf_counter() - t0
    placed = sum(len(v) for v in h.plans[-1].node_allocation.values())
    log(
        f"host end-to-end (stock iterator semantics): {placed} placements / "
        f"{n_nodes} nodes in {dt:.2f}s -> {placed/dt:,.0f} placements/s"
    )


def main():
    rate, placed = bench_c1m()
    try:
        bench_parity_scan()
        bench_host_end_to_end()
    except Exception as e:  # diagnostics only; never break the headline line
        log(f"diagnostic bench failed: {e}")

    baseline = 100_000.0  # C1M bar: 1M containers in <10s
    print(
        json.dumps(
            {
                "metric": "C1M replay: 1M containers / 5K nodes, full rank scan (tpu_binpack)",
                "value": round(rate, 1),
                "unit": "placements/s",
                "vs_baseline": round(rate / baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
