// nomad-executor: out-of-process task executor.
//
// Fills the role of the reference's shared executor
// (drivers/shared/executor/executor.go UniversalExecutor and the
// libcontainer-based executor_linux.go:50): the driver fork-execs THIS
// binary, which sets up isolation and supervises the real task so the task
// survives a driver/client restart (re-attach by pid, the reference's
// reattach config). Isolation applied before exec:
//   - new session (setsid) => own process group for group signalling
//   - rlimits (cpu seconds, address space, nofile) when requested
//   - working directory, cleared/supplied environment
//   - optional chroot (--chroot, needs privilege; skipped gracefully)
// Status protocol: writes "<exit_code> <signal>\n" to --status-file when the
// task exits (the driver's reaper tails it), and forwards SIGTERM/SIGINT to
// the task group with a --kill-timeout escalation to SIGKILL.
//
// Build: g++ -O2 -std=c++17 nomad_executor.cpp -o nomad-executor

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

namespace {

pid_t child_pid = -1;
double kill_timeout_s = 5.0;

void forward_signal(int sig) {
  if (child_pid <= 0) return;
  kill(-child_pid, sig == SIGINT ? SIGTERM : sig);
  if (sig == SIGTERM || sig == SIGINT) {
    // escalation alarm: SIGKILL the group after the timeout
    alarm((unsigned)(kill_timeout_s < 1 ? 1 : kill_timeout_s));
  }
}

void on_alarm(int) {
  if (child_pid > 0) kill(-child_pid, SIGKILL);
}

void write_status(const char* path, int exit_code, int sig) {
  if (!path || !*path) return;
  std::string tmp = std::string(path) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) return;
  fprintf(f, "%d %d\n", exit_code, sig);
  fflush(f);
  fsync(fileno(f));
  fclose(f);
  rename(tmp.c_str(), path);
}

void write_pid_file(const char* path, pid_t pid) {
  if (!path || !*path) return;
  FILE* f = fopen(path, "w");
  if (!f) return;
  fprintf(f, "%d", (int)pid);
  fclose(f);
}

void usage() {
  fprintf(stderr,
          "usage: nomad-executor [--status-file F] [--pid-file F] [--stdout F]\n"
          "  [--stderr F] [--cwd D] [--chroot D] [--kill-timeout S] [--rlimit-cpu S]\n"
          "  [--rlimit-as BYTES] [--rlimit-nofile N] [--env K=V]... -- cmd args...\n");
}

}  // namespace

int main(int argc, char** argv) {
  const char* status_file = nullptr;
  const char* pid_file = nullptr;
  const char* stdout_path = nullptr;
  const char* stderr_path = nullptr;
  const char* cwd = nullptr;
  const char* chroot_dir = nullptr;
  long rlimit_cpu = 0, rlimit_as = 0, rlimit_nofile = 0;
  std::vector<std::string> env;
  int cmd_start = -1;

  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&](const char* flag) -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "%s needs a value\n", flag);
        exit(2);
      }
      return argv[++i];
    };
    if (a == "--status-file") status_file = next("--status-file");
    else if (a == "--pid-file") pid_file = next("--pid-file");
    else if (a == "--stdout") stdout_path = next("--stdout");
    else if (a == "--stderr") stderr_path = next("--stderr");
    else if (a == "--cwd") cwd = next("--cwd");
    else if (a == "--chroot") chroot_dir = next("--chroot");
    else if (a == "--kill-timeout") kill_timeout_s = atof(next("--kill-timeout"));
    else if (a == "--rlimit-cpu") rlimit_cpu = atol(next("--rlimit-cpu"));
    else if (a == "--rlimit-as") rlimit_as = atol(next("--rlimit-as"));
    else if (a == "--rlimit-nofile") rlimit_nofile = atol(next("--rlimit-nofile"));
    else if (a == "--env") env.push_back(next("--env"));
    else if (a == "--") { cmd_start = i + 1; break; }
    else { usage(); return 2; }
  }
  if (cmd_start < 0 || cmd_start >= argc) {
    usage();
    return 2;
  }

  child_pid = fork();
  if (child_pid < 0) {
    perror("fork");
    return 1;
  }
  if (child_pid == 0) {
    // -- child: isolate, then exec the task --
    setsid();
    if (chroot_dir && *chroot_dir) {
      if (chroot(chroot_dir) != 0 || chdir("/") != 0) {
        // unprivileged: run unchrooted rather than fail the task
        fprintf(stderr, "nomad-executor: chroot skipped: %s\n", strerror(errno));
      }
    }
    if (cwd && chdir(cwd) != 0) {
      fprintf(stderr, "nomad-executor: chdir(%s): %s\n", cwd, strerror(errno));
      _exit(127);
    }
    auto set_rlim = [](int res, long v) {
      if (v > 0) {
        struct rlimit rl {(rlim_t)v, (rlim_t)v};
        setrlimit(res, &rl);
      }
    };
    set_rlim(RLIMIT_CPU, rlimit_cpu);
    set_rlim(RLIMIT_AS, rlimit_as);
    set_rlim(RLIMIT_NOFILE, rlimit_nofile);
    // Output paths may be logmon FIFOs: a plain open(O_WRONLY) on a FIFO
    // with no reader blocks forever, wedging the task before exec. Retry
    // non-blocking (ENXIO = no reader yet) with a deadline, then restore
    // blocking semantics for the task's own writes.
    auto open_output = [](const char* path) -> int {
      struct stat st;
      bool fifo = stat(path, &st) == 0 && S_ISFIFO(st.st_mode);
      if (!fifo) return open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
      for (int i = 0; i < 500; i++) {  // ~10s at 20ms
        int fd = open(path, O_WRONLY | O_NONBLOCK);
        if (fd >= 0) {
          int flags = fcntl(fd, F_GETFL);
          fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
          return fd;
        }
        if (errno != ENXIO) return -1;
        usleep(20 * 1000);
      }
      return -1;
    };
    if (stdout_path) {
      int fd = open_output(stdout_path);
      if (fd >= 0) { dup2(fd, 1); close(fd); }
    }
    if (stderr_path) {
      int fd = open_output(stderr_path);
      if (fd >= 0) { dup2(fd, 2); close(fd); }
    }
    if (!env.empty()) {
      std::vector<char*> envp;
      for (auto& e : env) envp.push_back(const_cast<char*>(e.c_str()));
      envp.push_back(nullptr);
      execvpe(argv[cmd_start], &argv[cmd_start], envp.data());
    } else {
      execvp(argv[cmd_start], &argv[cmd_start]);
    }
    fprintf(stderr, "nomad-executor: exec %s: %s\n", argv[cmd_start],
            strerror(errno));
    _exit(127);
  }

  // -- parent: supervise --
  write_pid_file(pid_file, child_pid);  // task pgid, for external group kill
  signal(SIGTERM, forward_signal);
  signal(SIGINT, forward_signal);
  signal(SIGALRM, on_alarm);

  int status = 0;
  while (waitpid(child_pid, &status, 0) < 0) {
    if (errno != EINTR) {
      write_status(status_file, 127, 0);
      return 127;
    }
  }
  int exit_code = WIFEXITED(status) ? WEXITSTATUS(status) : 0;
  int sig = WIFSIGNALED(status) ? WTERMSIG(status) : 0;
  // reap any stragglers in the group
  kill(-child_pid, SIGKILL);
  write_status(status_file, exit_code, sig);
  return sig ? 128 + sig : exit_code;
}
