// nomadlog: durable append-only segmented log for the replicated-log layer.
//
// Fills the role of the reference's vendored raft-boltdb log store
// (nomad/server.go:1079 setupRaft wires hashicorp/raft to BoltDB). Design:
// fixed-size segments of [u64 index][u32 len][u32 crc32c][payload] records,
// an in-memory offset index rebuilt on open, torn-write recovery (scan stops
// at the first record whose CRC fails and truncates the tail), and
// prefix/suffix truncation for snapshot compaction and conflict repair.
// Exposed as a C ABI consumed over ctypes.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC nomadlog.cpp -o libnomadlog.so

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

// CRC-32C (Castagnoli), table-driven.
uint32_t crc_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      crc_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32c(const uint8_t* data, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct RecordLoc {
  int segment;       // index into Log::segments
  uint64_t offset;   // record start offset in that segment file
  uint32_t len;      // payload length
};

struct Segment {
  std::string path;
  uint64_t first_index;  // first record index (0 = empty)
  int fd;
  uint64_t size;
};

constexpr uint64_t kHeaderSize = 8 + 4 + 4;

struct Log {
  std::string dir;
  uint64_t segment_bytes;
  std::vector<Segment> segments;
  std::map<uint64_t, RecordLoc> index;  // log index -> location
  uint64_t first = 0, last = 0;
  std::mutex mu;

  ~Log() {
    for (auto& s : segments)
      if (s.fd >= 0) close(s.fd);
  }
};

std::string segment_name(const std::string& dir, uint64_t first_index) {
  char buf[64];
  snprintf(buf, sizeof(buf), "%020llu.log", (unsigned long long)first_index);
  return dir + "/" + buf;
}

// The compaction floor persists in <dir>/FIRST so records below it in a
// still-active segment don't resurrect on reopen.
uint64_t read_first_marker(const std::string& dir) {
  FILE* f = fopen((dir + "/FIRST").c_str(), "r");
  if (!f) return 0;
  unsigned long long v = 0;
  if (fscanf(f, "%llu", &v) != 1) v = 0;
  fclose(f);
  return v;
}

void write_first_marker(const std::string& dir, uint64_t v) {
  std::string tmp = dir + "/FIRST.tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) return;
  fprintf(f, "%llu", (unsigned long long)v);
  fflush(f);
  fsync(fileno(f));
  fclose(f);
  rename(tmp.c_str(), (dir + "/FIRST").c_str());
}

// Scan one segment, appending valid records to the in-memory index.
// Returns the offset of the first invalid byte (for tail truncation).
uint64_t scan_segment(Log* log, int seg_idx) {
  Segment& seg = log->segments[seg_idx];
  uint64_t off = 0;
  uint8_t header[kHeaderSize];
  std::vector<uint8_t> payload;
  while (off + kHeaderSize <= seg.size) {
    if (pread(seg.fd, header, kHeaderSize, off) != (ssize_t)kHeaderSize) break;
    uint64_t idx;
    uint32_t len, crc;
    memcpy(&idx, header, 8);
    memcpy(&len, header + 8, 4);
    memcpy(&crc, header + 12, 4);
    if (len > (1u << 30) || off + kHeaderSize + len > seg.size) break;
    payload.resize(len);
    if (len && pread(seg.fd, payload.data(), len, off + kHeaderSize) != (ssize_t)len)
      break;
    if (crc32c(payload.data(), len) != crc) break;  // torn write: stop
    log->index[idx] = RecordLoc{seg_idx, off, len};
    if (log->first == 0 || idx < log->first) log->first = idx;
    if (idx > log->last) log->last = idx;
    off += kHeaderSize + len;
  }
  return off;
}

int open_segment(Log* log, uint64_t first_index) {
  Segment seg;
  seg.path = segment_name(log->dir, first_index);
  seg.first_index = first_index;
  seg.fd = open(seg.path.c_str(), O_RDWR | O_CREAT, 0644);
  if (seg.fd < 0) return -1;
  struct stat st;
  fstat(seg.fd, &st);
  seg.size = (uint64_t)st.st_size;
  log->segments.push_back(seg);
  return (int)log->segments.size() - 1;
}

}  // namespace

extern "C" {

void* nomadlog_open(const char* dir, uint64_t segment_bytes) {
  Log* log = new Log();
  log->dir = dir;
  log->segment_bytes = segment_bytes ? segment_bytes : (64u << 20);
  mkdir(dir, 0755);

  std::vector<std::string> names;
  if (DIR* d = opendir(dir)) {
    while (dirent* e = readdir(d)) {
      std::string n = e->d_name;
      if (n.size() > 4 && n.substr(n.size() - 4) == ".log") names.push_back(n);
    }
    closedir(d);
  }
  std::sort(names.begin(), names.end());
  for (auto& n : names) {
    Segment seg;
    seg.path = log->dir + "/" + n;
    seg.first_index = strtoull(n.c_str(), nullptr, 10);
    seg.fd = open(seg.path.c_str(), O_RDWR, 0644);
    if (seg.fd < 0) continue;
    struct stat st;
    fstat(seg.fd, &st);
    seg.size = (uint64_t)st.st_size;
    log->segments.push_back(seg);
  }
  // rebuild the index; truncate a torn tail on the last segment
  for (size_t i = 0; i < log->segments.size(); i++) {
    uint64_t valid = scan_segment(log, (int)i);
    if (i == log->segments.size() - 1 && valid < log->segments[i].size) {
      if (ftruncate(log->segments[i].fd, (off_t)valid) == 0)
        log->segments[i].size = valid;
    }
  }
  // apply the persisted compaction floor
  uint64_t floor = read_first_marker(log->dir);
  if (floor > 0) {
    for (auto it = log->index.begin();
         it != log->index.end() && it->first < floor;)
      it = log->index.erase(it);
    log->first = log->index.empty() ? 0 : log->index.begin()->first;
    if (log->index.empty()) log->last = 0;
  }
  return log;
}

uint64_t nomadlog_first_index(void* h) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  return log->first;
}

uint64_t nomadlog_last_index(void* h) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  return log->last;
}

int nomadlog_append(void* h, uint64_t index, const uint8_t* data, uint32_t len) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  int seg_idx;
  if (log->segments.empty() ||
      log->segments.back().size + kHeaderSize + len > log->segment_bytes) {
    seg_idx = open_segment(log, index);
    if (seg_idx < 0) return -1;
  } else {
    seg_idx = (int)log->segments.size() - 1;
  }
  Segment& seg = log->segments[seg_idx];
  uint8_t header[kHeaderSize];
  uint32_t crc = crc32c(data, len);
  memcpy(header, &index, 8);
  memcpy(header + 8, &len, 4);
  memcpy(header + 12, &crc, 4);
  uint64_t off = seg.size;
  if (pwrite(seg.fd, header, kHeaderSize, off) != (ssize_t)kHeaderSize) return -1;
  if (len && pwrite(seg.fd, data, len, off + kHeaderSize) != (ssize_t)len) return -1;
  seg.size += kHeaderSize + len;
  log->index[index] = RecordLoc{seg_idx, off, len};
  if (log->first == 0 || index < log->first) log->first = index;
  if (index > log->last) log->last = index;
  return 0;
}

int nomadlog_sync(void* h) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  if (log->segments.empty()) return 0;
  return fdatasync(log->segments.back().fd);
}

// Caller frees via nomadlog_free. Returns 0 on success, -1 if absent.
int nomadlog_get(void* h, uint64_t index, uint8_t** out, uint32_t* out_len) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  auto it = log->index.find(index);
  if (it == log->index.end()) return -1;
  const RecordLoc& loc = it->second;
  uint8_t* buf = (uint8_t*)malloc(loc.len);
  if (loc.len &&
      pread(log->segments[loc.segment].fd, buf, loc.len,
            loc.offset + kHeaderSize) != (ssize_t)loc.len) {
    free(buf);
    return -1;
  }
  *out = buf;
  *out_len = loc.len;
  return 0;
}

void nomadlog_free(uint8_t* p) { free(p); }

// Drop entries with index < upto (snapshot compaction): deletes whole
// segments whose records are all below the cutoff.
int nomadlog_truncate_before(void* h, uint64_t upto) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  std::vector<bool> keep(log->segments.size(), false);
  for (auto& [idx, loc] : log->index)
    if (idx >= upto) keep[loc.segment] = true;
  std::vector<Segment> remaining;
  std::vector<int> remap(log->segments.size(), -1);
  for (size_t i = 0; i < log->segments.size(); i++) {
    if (keep[i] || i == log->segments.size() - 1) {  // keep active segment
      remap[i] = (int)remaining.size();
      remaining.push_back(log->segments[i]);
    } else {
      close(log->segments[i].fd);
      unlink(log->segments[i].path.c_str());
    }
  }
  log->segments = std::move(remaining);
  for (auto it = log->index.begin(); it != log->index.end();) {
    if (it->first < upto) {
      it = log->index.erase(it);
    } else {
      it->second.segment = remap[it->second.segment];
      ++it;
    }
  }
  log->first = log->index.empty() ? 0 : log->index.begin()->first;
  if (log->index.empty()) log->last = 0;
  write_first_marker(log->dir, upto);
  return 0;
}

// Drop entries with index > from (conflict repair on raft divergence).
// Raft only truncates a suffix of the append order, so the physical cut is
// at the earliest removed record's position; everything after it goes.
int nomadlog_truncate_after(void* h, uint64_t from) {
  Log* log = (Log*)h;
  std::lock_guard<std::mutex> g(log->mu);
  int cut_seg = -1;
  uint64_t cut_off = 0;
  for (auto it = log->index.begin(); it != log->index.end();) {
    if (it->first > from) {
      if (cut_seg == -1 || it->second.segment < cut_seg ||
          (it->second.segment == cut_seg && it->second.offset < cut_off)) {
        cut_seg = it->second.segment;
        cut_off = it->second.offset;
      }
      it = log->index.erase(it);
    } else {
      ++it;
    }
  }
  if (cut_seg >= 0) {
    for (size_t i = cut_seg + 1; i < log->segments.size(); i++) {
      close(log->segments[i].fd);
      unlink(log->segments[i].path.c_str());
    }
    log->segments.resize(cut_seg + 1);
    if (ftruncate(log->segments[cut_seg].fd, (off_t)cut_off) == 0)
      log->segments[cut_seg].size = cut_off;
  }
  log->last = log->index.empty() ? 0 : log->index.rbegin()->first;
  if (log->index.empty()) log->first = 0;
  return 0;
}

void nomadlog_close(void* h) { delete (Log*)h; }

}  // extern "C"
