#!/usr/bin/env python3
"""Launcher for the nomad_tpu CLI (reference: the single `nomad` binary)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from nomad_tpu.cli import main

sys.exit(main())
