"""nomad_tpu — a TPU-native cluster workload orchestrator.

A from-scratch rebuild of the capabilities of HashiCorp Nomad v0.10.2
(reference at /root/reference), with the placement hot path implemented as a
batched, vectorized JAX engine (`tpu_binpack`) instead of the reference's
per-node Go iterator chain.
"""

__version__ = "0.1.0"
