"""ACL policy engine (reference acl/ package + nomad/acl.go)."""

from .acl import (
    ACL,
    HostVolumePolicy,
    NamespacePolicy,
    Policy,
    management_acl,
    new_acl,
    parse_policy,
)
from .resolver import ACLResolver, PermissionDenied, TokenError

__all__ = [
    "ACL",
    "ACLResolver",
    "HostVolumePolicy",
    "NamespacePolicy",
    "PermissionDenied",
    "Policy",
    "TokenError",
    "management_acl",
    "new_acl",
    "parse_policy",
]
