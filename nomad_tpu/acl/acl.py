"""ACL policy language and capability engine (reference acl/policy.go,
acl/acl.go:43 ACL / :83 NewACL).

Policies are HCL documents:

    namespace "default" {
      policy       = "read"
      capabilities = ["submit-job"]
    }
    node     { policy = "write" }
    agent    { policy = "read" }
    operator { policy = "write" }
    quota    { policy = "read" }
    host_volume "prod-*" {
      policy = "read"
    }

``policy`` shorthands expand to capability sets
(acl/policy.go expandNamespacePolicy); explicit ``capabilities`` merge in.
An :class:`ACL` merges many parsed policies; "deny" always wins
(acl/acl.go:118).  Namespace and host-volume rules support a trailing-``*``
glob, longest-prefix match winning (the reference uses exact radix lookups in
0.10 plus the implicit ``default`` namespace; globs are a superset kept for
convenience).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from ..jobspec.hcl import HCLError, HCLObject, parse as parse_hcl

# Namespace capabilities (reference acl/policy.go:26-40)
NS_CAP_DENY = "deny"
NS_CAP_LIST_JOBS = "list-jobs"
NS_CAP_READ_JOB = "read-job"
NS_CAP_SUBMIT_JOB = "submit-job"
NS_CAP_DISPATCH_JOB = "dispatch-job"
NS_CAP_READ_LOGS = "read-logs"
NS_CAP_READ_FS = "read-fs"
NS_CAP_ALLOC_EXEC = "alloc-exec"
NS_CAP_ALLOC_NODE_EXEC = "alloc-node-exec"
NS_CAP_ALLOC_LIFECYCLE = "alloc-lifecycle"
NS_CAP_SENTINEL_OVERRIDE = "sentinel-override"

_VALID_NS_CAPS = {
    NS_CAP_DENY,
    NS_CAP_LIST_JOBS,
    NS_CAP_READ_JOB,
    NS_CAP_SUBMIT_JOB,
    NS_CAP_DISPATCH_JOB,
    NS_CAP_READ_LOGS,
    NS_CAP_READ_FS,
    NS_CAP_ALLOC_EXEC,
    NS_CAP_ALLOC_NODE_EXEC,
    NS_CAP_ALLOC_LIFECYCLE,
    NS_CAP_SENTINEL_OVERRIDE,
}

HOST_VOLUME_CAP_DENY = "deny"
HOST_VOLUME_CAP_MOUNT_READONLY = "mount-readonly"
HOST_VOLUME_CAP_MOUNT_READWRITE = "mount-readwrite"

POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_WRITE = "write"
POLICY_SCALE = "scale"

_VALID_POLICIES = {POLICY_DENY, POLICY_READ, POLICY_WRITE}


def _expand_namespace_policy(policy: str) -> List[str]:
    if policy == POLICY_DENY:
        return [NS_CAP_DENY]
    if policy == POLICY_READ:
        return [NS_CAP_LIST_JOBS, NS_CAP_READ_JOB]
    if policy == POLICY_WRITE:
        return [
            NS_CAP_LIST_JOBS,
            NS_CAP_READ_JOB,
            NS_CAP_SUBMIT_JOB,
            NS_CAP_DISPATCH_JOB,
            NS_CAP_READ_LOGS,
            NS_CAP_READ_FS,
            NS_CAP_ALLOC_EXEC,
            NS_CAP_ALLOC_LIFECYCLE,
        ]
    raise HCLError(f"invalid namespace policy {policy!r}", 0)


def _expand_host_volume_policy(policy: str) -> List[str]:
    if policy == POLICY_DENY:
        return [HOST_VOLUME_CAP_DENY]
    if policy == POLICY_READ:
        return [HOST_VOLUME_CAP_MOUNT_READONLY]
    if policy == POLICY_WRITE:
        return [HOST_VOLUME_CAP_MOUNT_READONLY, HOST_VOLUME_CAP_MOUNT_READWRITE]
    raise HCLError(f"invalid host_volume policy {policy!r}", 0)


@dataclass
class NamespacePolicy:
    name: str = ""
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)


@dataclass
class HostVolumePolicy:
    name: str = ""
    policy: str = ""
    capabilities: List[str] = field(default_factory=list)


@dataclass
class Policy:
    """A parsed policy document (reference acl/policy.go:111 Policy)."""

    namespaces: List[NamespacePolicy] = field(default_factory=list)
    host_volumes: List[HostVolumePolicy] = field(default_factory=list)
    agent: str = ""
    node: str = ""
    operator: str = ""
    quota: str = ""

    def is_empty(self) -> bool:
        return (
            not self.namespaces
            and not self.host_volumes
            and not self.agent
            and not self.node
            and not self.operator
            and not self.quota
        )


def _coarse(o: HCLObject, what: str) -> str:
    p = o.get("policy", "")
    if p not in _VALID_POLICIES:
        raise HCLError(f"invalid {what} policy {p!r}", 0)
    return p


def parse_policy(rules: str) -> Policy:
    """Parse a policy HCL document (reference acl/policy.go:253 Parse)."""
    root = parse_hcl(rules)
    pol = Policy()
    for key, body in root:
        if key == "namespace":
            if not isinstance(body, HCLObject) or len(body) != 1:
                raise HCLError("namespace block requires a name label", 0)
            name, inner = body.items[0]
            if not isinstance(inner, HCLObject):
                raise HCLError("namespace block requires a body", 0)
            np = NamespacePolicy(name=name)
            if "policy" in inner:
                np.policy = inner.get("policy")
                np.capabilities.extend(_expand_namespace_policy(np.policy))
            for cap in inner.get("capabilities") or []:
                if cap not in _VALID_NS_CAPS:
                    raise HCLError(f"invalid namespace capability {cap!r}", 0)
                if cap not in np.capabilities:
                    np.capabilities.append(cap)
            if not np.capabilities:
                raise HCLError(f"namespace {name!r} grants nothing", 0)
            pol.namespaces.append(np)
        elif key == "host_volume":
            if not isinstance(body, HCLObject) or len(body) != 1:
                raise HCLError("host_volume block requires a name label", 0)
            name, inner = body.items[0]
            hv = HostVolumePolicy(name=name)
            if "policy" in inner:
                hv.policy = inner.get("policy")
                hv.capabilities.extend(_expand_host_volume_policy(hv.policy))
            for cap in inner.get("capabilities") or []:
                if cap not in hv.capabilities:
                    hv.capabilities.append(cap)
            pol.host_volumes.append(hv)
        elif key in ("agent", "node", "operator", "quota"):
            if not isinstance(body, HCLObject):
                raise HCLError(f"{key} must be a block", 0)
            setattr(pol, key, _coarse(body, key))
        else:
            raise HCLError(f"unknown policy block {key!r}", 0)
    return pol


# ---------------------------------------------------------------------------
# Merged ACL object
# ---------------------------------------------------------------------------


def _match_rule(rules: Dict[str, frozenset], name: str) -> Optional[frozenset]:
    """Exact match, else longest trailing-* glob match."""
    if name in rules:
        return rules[name]
    best: Tuple[int, Optional[frozenset]] = (-1, None)
    for pattern, caps in rules.items():
        if pattern.endswith("*") and name.startswith(pattern[:-1]):
            if len(pattern) > best[0]:
                best = (len(pattern), caps)
    return best[1]


_COARSE_RANK = {POLICY_DENY: 3, POLICY_WRITE: 2, POLICY_READ: 1, "": 0}


class ACL:
    """Capability check object compiled from policies (acl/acl.go:43)."""

    def __init__(self, management: bool = False) -> None:
        self.management = management
        self._namespaces: Dict[str, frozenset] = {}
        self._host_volumes: Dict[str, frozenset] = {}
        self.agent = ""
        self.node = ""
        self.operator = ""
        self.quota = ""

    # -- namespace ---------------------------------------------------------

    def allow_namespace_operation(self, ns: str, op: str) -> bool:
        if self.management:
            return True
        caps = _match_rule(self._namespaces, ns or "default")
        if caps is None or NS_CAP_DENY in caps:
            return False
        return op in caps

    def allow_namespace(self, ns: str) -> bool:
        if self.management:
            return True
        caps = _match_rule(self._namespaces, ns or "default")
        return bool(caps) and NS_CAP_DENY not in caps

    def allow_host_volume_operation(self, name: str, op: str) -> bool:
        if self.management:
            return True
        caps = _match_rule(self._host_volumes, name)
        if caps is None or HOST_VOLUME_CAP_DENY in caps:
            return False
        return op in caps

    # -- coarse-grained ------------------------------------------------------

    def _coarse_allows(self, level: str, write: bool) -> bool:
        if self.management:
            return True
        if level == POLICY_DENY:
            return False
        if write:
            return level == POLICY_WRITE
        return level in (POLICY_READ, POLICY_WRITE)

    def allow_agent_read(self) -> bool:
        return self._coarse_allows(self.agent, write=False)

    def allow_agent_write(self) -> bool:
        return self._coarse_allows(self.agent, write=True)

    def allow_node_read(self) -> bool:
        return self._coarse_allows(self.node, write=False)

    def allow_node_write(self) -> bool:
        return self._coarse_allows(self.node, write=True)

    def allow_operator_read(self) -> bool:
        return self._coarse_allows(self.operator, write=False)

    def allow_operator_write(self) -> bool:
        return self._coarse_allows(self.operator, write=True)

    def allow_quota_read(self) -> bool:
        return self._coarse_allows(self.quota, write=False)

    def allow_quota_write(self) -> bool:
        return self._coarse_allows(self.quota, write=True)

    def is_management(self) -> bool:
        return self.management


#: ACL that allows everything (management token / ACLs disabled)
def management_acl() -> ACL:
    return ACL(management=True)


def new_acl(policies: Iterable[Policy]) -> ACL:
    """Merge policies into an ACL; deny wins (acl/acl.go:83 NewACL)."""
    acl = ACL()
    ns_caps: Dict[str, set] = {}
    ns_denied: Dict[str, set] = {}
    hv_caps: Dict[str, set] = {}
    hv_denied: Dict[str, set] = {}
    for pol in policies:
        for np in pol.namespaces:
            bucket = ns_caps.setdefault(np.name, set())
            denied = ns_denied.setdefault(np.name, set())
            if NS_CAP_DENY in np.capabilities:
                # a blanket deny wipes previously granted caps for the name
                denied.update(_VALID_NS_CAPS)
            for cap in np.capabilities:
                bucket.add(cap)
        for hv in pol.host_volumes:
            bucket = hv_caps.setdefault(hv.name, set())
            denied = hv_denied.setdefault(hv.name, set())
            if HOST_VOLUME_CAP_DENY in hv.capabilities:
                denied.update(
                    {
                        HOST_VOLUME_CAP_DENY,
                        HOST_VOLUME_CAP_MOUNT_READONLY,
                        HOST_VOLUME_CAP_MOUNT_READWRITE,
                    }
                )
            bucket.update(hv.capabilities)
        for attr in ("agent", "node", "operator", "quota"):
            level = getattr(pol, attr)
            if _COARSE_RANK[level] > _COARSE_RANK[getattr(acl, attr)]:
                setattr(acl, attr, level)
    for name, caps in ns_caps.items():
        if ns_denied.get(name):
            caps = {NS_CAP_DENY}
        acl._namespaces[name] = frozenset(caps)
    for name, caps in hv_caps.items():
        if hv_denied.get(name):
            caps = {HOST_VOLUME_CAP_DENY}
        acl._host_volumes[name] = frozenset(caps)
    return acl
