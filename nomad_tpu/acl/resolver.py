"""Token → ACL resolution with policy caching (reference nomad/acl.go
ResolveToken and the server's parsed-ACL LRU at server.go:212)."""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from ..structs.acl import ACLToken
from .acl import ACL, Policy, management_acl, new_acl, parse_policy


class TokenError(PermissionError):
    """Presented secret does not resolve to a token (HTTP 403)."""


class PermissionDenied(PermissionError):
    """Token resolved but lacks the capability (HTTP 403)."""


class ACLResolver:
    """Resolves secret IDs against the replicated ACL tables.

    Parsed policies are cached keyed by (name, modify_index) and compiled
    ACLs by the sorted policy-name/index tuple, mirroring the reference's
    two-level cache (nomad/acl.go:37 resolveTokenFromSnapshotCache).
    """

    def __init__(self, state_fn: Callable[[], object], enabled: bool = True) -> None:
        self._state_fn = state_fn
        self.enabled = enabled
        self._lock = threading.Lock()
        self._policy_cache: Dict[Tuple[str, int], Policy] = {}
        self._acl_cache: Dict[Tuple, ACL] = {}

    def resolve_secret(self, secret: str) -> Optional[ACL]:
        """Secret → compiled ACL. ``None`` means "ACLs disabled, allow all"."""
        if not self.enabled:
            return None
        state = self._state_fn()
        if not secret:
            token = ACLToken(accessor_id="anonymous", policies=["anonymous"])
        else:
            token = state.acl_token_by_secret(secret)
            if token is None:
                raise TokenError("ACL token not found")
        if token.is_management():
            return management_acl()
        policies = []
        key = []
        for name in sorted(token.policies):
            pol = state.acl_policy_by_name(name)
            if pol is None:
                continue  # dangling policy reference: grants nothing
            key.append((name, pol.modify_index))
            policies.append(self._parse_cached(pol))
        cache_key = tuple(key)
        with self._lock:
            acl = self._acl_cache.get(cache_key)
        if acl is None:
            acl = new_acl(policies)
            with self._lock:
                self._acl_cache[cache_key] = acl
        return acl

    def _parse_cached(self, pol) -> Policy:
        key = (pol.name, pol.modify_index)
        with self._lock:
            parsed = self._policy_cache.get(key)
        if parsed is None:
            parsed = parse_policy(pol.rules) if pol.rules else Policy()
            with self._lock:
                self._policy_cache[key] = parsed
        return parsed

    # -- HTTP enforcement ---------------------------------------------------

    def check_http(self, req, capabilities, namespace: str) -> None:
        """Enforce capability strings from the route table.

        Namespace capabilities are plain names ("submit-job"); coarse-grained
        checks use "<scope>:<read|write>" ("node:write", "operator:read").
        """
        acl = self.resolve_secret(req.options.auth_token)
        if acl is None:
            return
        for cap in capabilities:
            if ":" in cap:
                scope, op = cap.split(":", 1)
                ok = getattr(acl, f"allow_{scope}_{op}")()
            else:
                ok = acl.allow_namespace_operation(namespace or "default", cap)
            if not ok:
                raise PermissionDenied("Permission denied")
