"""HTTP agent: embeds a server and/or client and serves the /v1 API.

Fills the role of the reference's ``command/agent`` package (agent.go:90
NewAgent, http.go:150 registerHandlers).
"""

from .agent import Agent, AgentConfig
from .http import HTTPServer

__all__ = ["Agent", "AgentConfig", "HTTPServer"]
