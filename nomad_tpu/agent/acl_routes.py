"""/v1/acl/* HTTP surface (reference command/agent/acl_endpoint.go →
nomad/acl_endpoint.go)."""

from __future__ import annotations

from typing import List

from ..structs.acl import ACLPolicy, ACLToken
from . import jsonapi
from .http import HTTPError, Request


class ACLRoutes:
    def __init__(self, agent) -> None:
        self.agent = agent

    @property
    def server(self):
        if self.agent.server is None:
            raise HTTPError(501, "server is not enabled on this agent")
        return self.agent.server

    @property
    def state(self):
        return self.server.fsm.state

    def register_all(self, mux) -> None:
        r = mux.register
        r("/v1/acl/bootstrap", self.bootstrap)
        r("/v1/acl/policies", self.policies_index)
        r("/v1/acl/policy/", self.policy_specific)
        r("/v1/acl/tokens", self.tokens_index)
        r("/v1/acl/token", self.token_create)
        r("/v1/acl/token/", self.token_specific)

    # -- helpers ----------------------------------------------------------

    def _require_management(self, req: Request) -> None:
        resolver = self.agent.acl_resolver
        if resolver is None:
            raise HTTPError(400, "ACL support disabled")
        acl = resolver.resolve_secret(req.options.auth_token)
        if acl is None or not acl.is_management():
            raise PermissionError("Permission denied")

    def _enabled(self) -> None:
        if self.agent.acl_resolver is None:
            raise HTTPError(400, "ACL support disabled")

    # -- handlers ---------------------------------------------------------

    def bootstrap(self, req: Request):
        self._enabled()
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        try:
            token = self.server.bootstrap_acl()
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return token

    def policies_index(self, req: Request):
        self._enabled()
        self._require_management(req)
        req.response_index = self.state.latest_index
        return [
            {
                "Name": p.name,
                "Description": p.description,
                "CreateIndex": p.create_index,
                "ModifyIndex": p.modify_index,
            }
            for p in self.state.acl_policies()
        ]

    def policy_specific(self, req: Request):
        self._enabled()
        name = req.path[len("/v1/acl/policy/") :]
        if not name:
            raise HTTPError(400, "missing policy name")
        self._require_management(req)
        if req.method == "GET":
            pol = self.state.acl_policy_by_name(name)
            if pol is None:
                raise HTTPError(404, f"policy {name!r} not found")
            req.response_index = pol.modify_index
            return pol
        if req.method in ("PUT", "POST"):
            pol = req.json(ACLPolicy)
            if pol.name and pol.name != name:
                raise HTTPError(400, "policy name does not match request path")
            pol.name = name
            try:
                self.server.upsert_acl_policies([pol])
            except ValueError as e:
                raise HTTPError(400, str(e))
            req.response_index = self.state.latest_index
            return None
        if req.method == "DELETE":
            self.server.delete_acl_policies([name])
            req.response_index = self.state.latest_index
            return None
        raise HTTPError(405, "method not allowed")

    def tokens_index(self, req: Request):
        self._enabled()
        self._require_management(req)
        req.response_index = self.state.latest_index
        return [t.public_stub() for t in self.state.acl_tokens()]

    def token_create(self, req: Request):
        self._enabled()
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._require_management(req)
        tok = req.json(ACLToken)
        try:
            created: List[ACLToken] = self.server.upsert_acl_tokens([tok])
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return created[0]

    def token_specific(self, req: Request):
        self._enabled()
        accessor = req.path[len("/v1/acl/token/") :]
        if accessor == "self":
            return self._token_self(req)
        if not accessor:
            # the longest-prefix mux routes bare /v1/acl/token here too
            return self.token_create(req)
        self._require_management(req)
        if req.method == "GET":
            tok = self.state.acl_token_by_accessor(accessor)
            if tok is None:
                raise HTTPError(404, f"token {accessor!r} not found")
            req.response_index = tok.modify_index
            return tok
        if req.method in ("PUT", "POST"):
            tok = req.json(ACLToken)
            if tok.accessor_id and tok.accessor_id != accessor:
                raise HTTPError(400, "token accessor does not match request path")
            existing = self.state.acl_token_by_accessor(accessor)
            if existing is None:
                raise HTTPError(404, f"token {accessor!r} not found")
            tok.accessor_id = accessor
            tok.secret_id = existing.secret_id  # secrets are immutable
            try:
                created = self.server.upsert_acl_tokens([tok])
            except ValueError as e:
                raise HTTPError(400, str(e))
            req.response_index = self.state.latest_index
            return created[0]
        if req.method == "DELETE":
            self.server.delete_acl_tokens([accessor])
            req.response_index = self.state.latest_index
            return None
        raise HTTPError(405, "method not allowed")

    def _token_self(self, req: Request):
        secret = req.options.auth_token
        if not secret:
            raise HTTPError(400, "no token supplied")
        tok = self.state.acl_token_by_secret(secret)
        if tok is None:
            raise PermissionError("ACL token not found")
        req.response_index = tok.modify_index
        return tok
