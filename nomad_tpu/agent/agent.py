"""The agent: embeds a server and/or client plus the HTTP front-end.

Fills the role of the reference's ``command/agent/agent.go`` (NewAgent
:90, setupServer :560, setupClient :735): one process that can be a
server, a client, or both (dev mode), serving /v1 over HTTP. The
in-process wiring (client dials the embedded server directly) matches
the reference's dev-mode agent; distributed wiring rides the RPC
transport (nomad_tpu.rpc).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.client import Client, ClientConfig, ServerProxy
from ..server.server import Server, ServerConfig
from .http import HTTPServer, Request
from .routes import Routes


@dataclass
class AgentConfig:
    name: str = "agent-1"
    region: str = "global"
    datacenter: str = "dc1"
    server_enabled: bool = True
    client_enabled: bool = False
    dev_mode: bool = False
    http_bind: str = "127.0.0.1"
    http_port: int = 0  # 0 = ephemeral; reference default 4646
    num_schedulers: int = 2
    scheduler_algorithm: str = "tpu_binpack"
    acl_enabled: bool = False
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)


class Agent:
    def __init__(
        self,
        config: Optional[AgentConfig] = None,
        server: Optional[Server] = None,
        client: Optional[Client] = None,
    ) -> None:
        self.config = config or AgentConfig()
        if self.config.dev_mode:
            self.config.server_enabled = True
            self.config.client_enabled = True

        self.server: Optional[Server] = server
        self.client: Optional[Client] = client
        if self.server is None and self.config.server_enabled:
            self.server = Server(
                ServerConfig(
                    num_schedulers=self.config.num_schedulers,
                    scheduler_algorithm=self.config.scheduler_algorithm,
                ),
                name=self.config.name,
            )
        if self.client is None and self.config.client_enabled:
            if self.server is None:
                raise ValueError(
                    "client-only agents need a server to dial; pass client="
                )
            self.client = Client(
                ServerProxy(self.server),
                ClientConfig(
                    datacenter=self.config.datacenter,
                    node_class=self.config.node_class,
                    meta=dict(self.config.meta),
                ),
            )

        self.http = HTTPServer(self.config.http_bind, self.config.http_port)
        self.routes = Routes(self)
        self.routes.register_all(self.http)
        self.acl_resolver = None
        if self.config.acl_enabled:
            if self.server is None:
                raise ValueError("ACLs require a server-mode agent")
            from ..acl import ACLResolver

            self.acl_resolver = ACLResolver(lambda: self.server.fsm.state)
        from .acl_routes import ACLRoutes

        self.acl_routes = ACLRoutes(self)
        self.acl_routes.register_all(self.http)
        self._started = False
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Agent":
        with self._lock:
            if self._started:
                return self
            if self.server is not None:
                self.server.start()
            if self.client is not None:
                self.client.start()
            self.http.start()
            self._started = True
        return self

    def shutdown(self) -> None:
        with self._lock:
            if not self._started:
                return
            self.http.stop()
            if self.client is not None:
                self.client.shutdown()
            if self.server is not None:
                self.server.stop()
            self._started = False

    @property
    def http_addr(self) -> str:
        host, port = self.http.addr
        return f"http://{host}:{port}"

    # -- surface used by routes ------------------------------------------

    def authorize(self, req: Request, capabilities, namespace: str) -> None:
        """ACL choke point: every handler passes through here. A no-op
        until ACLs are enabled (reference: aclObj checks in every
        endpoint, e.g. job_endpoint.go:100)."""
        if self.acl_resolver is not None:
            self.acl_resolver.check_http(req, capabilities, namespace)

    def peer_names(self) -> List[str]:
        if self.server is None:
            return []
        return [f"{self.config.name}"]

    def raft_servers(self) -> List[Tuple[str, str, bool]]:
        if self.server is None:
            return []
        return [(self.config.name, self.http_addr, self.server.is_leader)]

    def known_servers(self) -> List[str]:
        return [self.http_addr] if self.server is not None else []

    def members(self) -> List[dict]:
        if self.server is None:
            return []
        return [
            {
                "Name": f"{self.config.name}.{self.config.region}",
                "Addr": self.http.addr[0],
                "Port": self.http.addr[1],
                "Status": "alive",
                "Leader": self.server.is_leader,
                "Tags": {
                    "region": self.config.region,
                    "dc": self.config.datacenter,
                    "role": "nomad",
                },
            }
        ]

    def regions(self) -> List[str]:
        return [self.config.region]

    def self_info(self) -> dict:
        stats = {}
        if self.server is not None:
            stats["nomad"] = {
                "server": "true",
                "leader": str(self.server.is_leader).lower(),
            }
        if self.client is not None:
            stats["client"] = {
                "node_id": self.client.node.id,
                "known_servers": ",".join(self.known_servers()),
            }
        return {
            "config": {
                "Region": self.config.region,
                "Datacenter": self.config.datacenter,
                "NodeName": self.config.name,
                "Server": {"Enabled": self.config.server_enabled},
                "Client": {"Enabled": self.config.client_enabled},
                "ACL": {"Enabled": self.config.acl_enabled},
                "Version": {"Version": "0.10.2-tpu"},
            },
            "stats": stats,
            "member": (self.members() or [{}])[0],
        }
