"""The agent: embeds a server and/or client plus the HTTP front-end.

Fills the role of the reference's ``command/agent/agent.go`` (NewAgent
:90, setupServer :560, setupClient :735): one process that can be a
server, a client, or both (dev mode), serving /v1 over HTTP. The
in-process wiring (client dials the embedded server directly) matches
the reference's dev-mode agent; distributed wiring rides the RPC
transport (nomad_tpu.rpc).
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.client import Client, ClientConfig, ServerProxy
from ..server.server import Server, ServerConfig
from .http import HTTPServer, Request
from .routes import Routes


@dataclass
class AgentConfig:
    name: str = "agent-1"
    region: str = "global"
    datacenter: str = "dc1"
    server_enabled: bool = True
    client_enabled: bool = False
    dev_mode: bool = False
    http_bind: str = "127.0.0.1"
    http_port: int = 0  # 0 = ephemeral; reference default 4646
    rpc_bind: str = "127.0.0.1"
    rpc_port: int = 0  # reference default 4647
    serf_bind: str = "127.0.0.1"
    serf_port: int = 0  # reference default 4648
    advertise_addr: str = ""  # routable host gossiped to peers; required with 0.0.0.0 binds
    gossip_enabled: bool = True
    retry_join: List[str] = field(default_factory=list)  # "host:port" gossip addrs
    retry_join_interval: float = 3.0
    bootstrap_expect: int = 1
    num_schedulers: int = 2
    scheduler_algorithm: str = "tpu_binpack"
    # chunked-tier knobs (default_scheduler_config stanza); only read
    # when scheduler_algorithm = "tpu_binpack_chunked"
    chunk_k: int = 128
    parity_sample_rate: float = 0.05
    acl_enabled: bool = False
    # gossip encryption key (reference agent `encrypt` option): base64 of
    # 16/24/32 bytes; all servers must share it — plaintext packets drop
    encrypt: str = ""
    # federation: non-authoritative regions mirror ACL policies + global
    # tokens from here (reference authoritative_region + replication_token)
    authoritative_region: str = ""
    replication_token: str = ""
    acl_replication_interval: float = 30.0
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    # client host_volume stanzas: name -> host path (reference client
    # config host_volume blocks)
    host_volumes: Dict[str, str] = field(default_factory=dict)
    # telemetry push sinks (reference command/agent/command.go:976-1018:
    # statsite/statsd/DataDog fan-out next to the inmem sink).
    # "host:port" UDP addresses; statsite speaks the statsd line protocol
    telemetry_statsd_address: str = ""
    telemetry_datadog_address: str = ""
    telemetry_datadog_tags: Dict[str, str] = field(default_factory=dict)
    telemetry_prefix: str = ""
    # flight recorder (telemetry stanza): leader-owned ~250ms sampler
    # behind GET /v1/flight; <= 0 interval disables the thread entirely
    flight_interval_s: float = 0.25
    flight_retain: int = 1024
    flight_spill_dir: str = ""
    # multi-process consensus: real raft over the RPC transport instead of
    # the in-proc shared log. Requires gossip; with bootstrap_expect > 1
    # the raft holds elections only once that many servers are known
    # (reference server.go bootstrap_expect semantics).
    wire_raft: bool = False
    data_dir: str = ""  # durable raft log + snapshots (and client state)
    enable_debug: bool = False  # /v1/agent/pprof dumps (http.go:220)
    # client-only agents dial these server RPC addrs ("host:port") —
    # reference client config `servers` list
    servers: List[str] = field(default_factory=list)
    # mutual TLS for the RPC plane (reference agent `tls` stanza +
    # helper/tlsutil): all three paths required to enable
    tls_ca_file: str = ""
    tls_cert_file: str = ""
    tls_key_file: str = ""
    tls_http: bool = False  # also serve the /v1 API over HTTPS (mTLS)
    # verify the dialed server's cert SAN is "server.<region>.nomad" so a
    # client-cert holder can't pose as a server (verify_server_hostname);
    # requires role-named certs — disable for legacy address-named certs
    tls_verify_server_hostname: bool = True


class _LeaderFailoverProxy:
    """Client⇆server surface for a client colocated with a wire-raft
    server: local calls first; writes rejected with NotLeader retry over
    RPC against the gossip-learned leader (the reference client always
    RPCs and the transport forwards — this keeps the fast local path for
    reads and leader-mode)."""

    def __init__(self, agent: "Agent", local) -> None:
        self._agent = agent
        self._local = local
        self._remote = None
        self._remote_lock = threading.Lock()

    def _leader_remote(self):
        from ..rpc.endpoints import RemoteServerProxy

        addr = self._agent.rpc.leader_addr if self._agent.rpc else None
        if addr is None:
            raise RuntimeError("no known leader")
        addr = tuple(addr)
        # locked check-close-create: heartbeat/sync/vault threads all come
        # through here concurrently, and a leader flap must not leak conns
        with self._remote_lock:
            if self._remote is not None and self._remote.rpc.addr != addr:
                self._remote.close()
                self._remote = None
            if self._remote is None:
                self._remote = RemoteServerProxy(
                    *addr, tls=self._agent.tls
                )
            return self._remote

    def close(self) -> None:
        with self._remote_lock:
            if self._remote is not None:
                self._remote.close()
                self._remote = None

    def _call(self, name, *args):
        # writes carry leader-side effects (heartbeat TTL timers live on
        # the leader): route them there whenever we aren't it
        if self._agent.server is not None and self._agent.server.is_leader:
            return getattr(self._local, name)(*args)
        return getattr(self._leader_remote(), name)(*args)

    def register_node(self, node):
        return self._call("register_node", node)

    def heartbeat(self, node_id):
        return self._call("heartbeat", node_id)

    def pull_allocs(self, node_id, min_index, timeout):
        return self._local.pull_allocs(node_id, min_index, timeout)  # local read

    def update_allocs(self, allocs):
        return self._call("update_allocs", allocs)

    def alloc_info(self, alloc_id):
        return self._local.alloc_info(alloc_id)

    def derive_vault_token(self, alloc_id, task_name, node_id="", node_secret=""):
        return self._call(
            "derive_vault_token", alloc_id, task_name, node_id, node_secret
        )


class Agent:
    def __init__(
        self,
        config: Optional[AgentConfig] = None,
        server: Optional[Server] = None,
        client: Optional[Client] = None,
    ) -> None:
        self.config = config or AgentConfig()
        if self.config.dev_mode:
            self.config.server_enabled = True
            self.config.client_enabled = True

        self.server: Optional[Server] = server
        self.client: Optional[Client] = client
        self.wire_raft = None
        self.tls = None
        tls_parts = (self.config.tls_ca_file, self.config.tls_cert_file,
                     self.config.tls_key_file)
        if any(tls_parts):
            if not all(tls_parts):
                # a half-configured stanza silently serving plaintext is
                # the worst failure mode mTLS can have
                raise ValueError(
                    "TLS requires all of tls_ca_file, tls_cert_file and "
                    "tls_key_file (got a partial set)"
                )
            from ..rpc.transport import TLSConfig

            self.tls = TLSConfig(
                *tls_parts,
                server_name=f"server.{self.config.region}.nomad",
                verify_server_hostname=self.config.tls_verify_server_hostname,
            )
        if self.config.tls_http and self.tls is None:
            raise ValueError(
                "tls_http requires tls_ca_file/tls_cert_file/tls_key_file"
            )
        # the RPC listener binds before the server exists: wire raft needs
        # its address to register handlers, and peers need it to dial us
        self.rpc = None
        if self.config.server_enabled or self.server is not None:
            from ..rpc.transport import RPCServer

            self.rpc = RPCServer(
                self.config.rpc_bind, self.config.rpc_port,
                region=self.config.region, tls=self.tls,
            )
        if self.server is None and self.config.server_enabled:
            raft = None
            if self.config.wire_raft:
                from ..server.wire_raft import WireRaft, WireRaftConfig

                data_dir = self.config.data_dir or None
                self.wire_raft = WireRaft(
                    self.rpc,
                    peers={},  # filled from gossip before election starts
                    # raft ids match gossip member names ("<name>.<region>")
                    # so serf→raft reconciliation is a straight map
                    config=WireRaftConfig(
                        node_id=f"{self.config.name}.{self.config.region}"
                    ),
                    data_dir=data_dir,
                )
                raft = self.wire_raft
            elif self.config.data_dir:
                # single-server durability: the in-proc raft persists its
                # log/snapshots so a restarted agent replays server state
                import os as _os

                from ..server.raft import InProcRaft

                raft = InProcRaft(
                    data_dir=_os.path.join(self.config.data_dir, "raft")
                )
            self.server = Server(
                ServerConfig(
                    num_schedulers=self.config.num_schedulers,
                    scheduler_algorithm=self.config.scheduler_algorithm,
                    chunk_k=self.config.chunk_k,
                    parity_sample_rate=self.config.parity_sample_rate,
                    region=self.config.region,
                    authoritative_region=self.config.authoritative_region,
                    replication_token=self.config.replication_token,
                    replication_interval=self.config.acl_replication_interval,
                    flight_interval_s=self.config.flight_interval_s,
                    flight_retain=self.config.flight_retain,
                    flight_spill_dir=self.config.flight_spill_dir,
                ),
                raft=raft,
                name=self.config.name,
            )
        if self.client is None and self.config.client_enabled:
            if self.server is not None:
                proxy = ServerProxy(self.server)
                if self.config.wire_raft:
                    # a colocated client on a FOLLOWER can't write through
                    # the in-process server; wrap with leader-RPC failover
                    proxy = _LeaderFailoverProxy(self, proxy)
            elif self.config.servers:
                from ..client.servers import FailoverServerProxy, ServersManager

                addrs = []
                for a in self.config.servers:
                    host, sep, port = a.rpartition(":")
                    if not sep or not port.isdigit():
                        raise ValueError(
                            f"server address {a!r} must be host:port"
                        )
                    addrs.append((host, int(port)))
                # per-call failover over the full candidate list (the
                # reference's client/servers manager): every RPC uses the
                # current best server; a failed call rotates and retries
                proxy = FailoverServerProxy(ServersManager(addrs), tls=self.tls)
            else:
                raise ValueError(
                    "client-only agents need -servers addresses or a server"
                )
            client_cfg = ClientConfig(
                datacenter=self.config.datacenter,
                node_class=self.config.node_class,
                meta=dict(self.config.meta),
                host_volumes=dict(self.config.host_volumes),
                tls=self.tls,
            )
            if self.config.data_dir:
                import os as _os

                client_cfg.state_dir = _os.path.join(self.config.data_dir, "client")
                client_cfg.persist_state = True
            self.client = Client(proxy, client_cfg)

        self.http = HTTPServer(
            self.config.http_bind, self.config.http_port,
            tls=self.tls if self.config.tls_http else None,
        )
        self.routes = Routes(self)
        self.routes.register_all(self.http)
        self.acl_resolver = None
        if self.config.acl_enabled:
            if self.server is None:
                raise ValueError("ACLs require a server-mode agent")
            from ..acl import ACLResolver

            self.acl_resolver = ACLResolver(lambda: self.server.fsm.state)
        from .acl_routes import ACLRoutes
        from .fs_routes import FSRoutes

        self.acl_routes = ACLRoutes(self)
        self.acl_routes.register_all(self.http)
        self.fs_routes = FSRoutes(self)
        self.fs_routes.register_all(self.http)
        from .ui import register_ui

        register_ui(self.http, self)

        # distributed wiring: RPC endpoints + gossip membership
        # (reference agent.go:560 setupServer → nomad.NewServer → setupRPC/Serf)
        self.membership = None
        if self.server is not None:
            from ..rpc.endpoints import bind_server
            from ..server.membership import ServerMembership

            bind_server(self.server, self.rpc)
            self.rpc.register("Region.List", self.regions)
            self.rpc.is_leader = lambda: self.server.is_leader
            # follower workers dequeue from the leader through this
            # (worker.go:161 Eval.Dequeue; address learned via gossip)
            self.server.get_leader_rpc_addr = lambda: self.rpc.leader_addr
            self.server.rpc_tls = self.tls
            if self.config.gossip_enabled:
                from ..gossip.memberlist import resolve_advertise_host

                rpc_host = resolve_advertise_host(
                    self.config.advertise_addr or self.rpc.addr[0]
                )
                self.membership = ServerMembership(
                    name=self.config.name,
                    region=self.config.region,
                    datacenter=self.config.datacenter,
                    rpc_addr=(rpc_host, self.rpc.addr[1]),
                    bind_host=self.config.serf_bind,
                    bind_port=self.config.serf_port,
                    advertise_host=self.config.advertise_addr,
                    expect=self.config.bootstrap_expect,
                    encrypt_key=self.config.encrypt.encode()
                    if self.config.encrypt else b"",
                )
                self.rpc.region_servers = lambda region: [
                    s.rpc_addr for s in self.membership.servers_in_region(region)
                ]
                # cross-region RPC for the server's leader loops (ACL
                # replication): rides the transport's region forwarding
                self.server.region_rpc = (
                    lambda method, region, *args:
                    self.rpc._forward_region(region, method, args)
                )
                self.membership.on_server_change = self._on_server_change
                self.server.raft.leadership_observers.append(self._on_raft_leadership)
        # monitor + autopilot (reference command/agent/monitor, autopilot.go)
        from .monitor import AgentMonitor

        self.monitor = AgentMonitor().attach()
        self.autopilot = None
        if self.server is not None:
            from ..server.autopilot import Autopilot

            self.autopilot = Autopilot(
                self.server, membership=self.membership, wire_raft=self.wire_raft
            )

        self._started = False
        self._join_done = None
        self._raft_started = False
        self._raft_boot_lock = threading.Lock()
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Agent":
        with self._lock:
            if self._started:
                return self
            self._setup_telemetry_sinks()
            if self.rpc is not None:
                self.rpc.start()
            if self.server is not None:
                self.server.start()
            if self.membership is not None:
                self.membership.start()
                if self.server.is_leader:
                    self.membership.set_leader(True)
                if self.config.retry_join:
                    self._start_retry_join()
            self._maybe_bootstrap_raft()
            if self.autopilot is not None:
                self.autopilot.start()
            # HTTP before the client: the node registration advertises this
            # agent's HTTP address for cross-node fs/logs proxying
            self.http.start()
            if self.client is not None:
                from ..gossip.memberlist import resolve_advertise_host

                http_host = resolve_advertise_host(
                    self.config.advertise_addr or self.http.addr[0]
                )
                addr = f"{http_host}:{self.http.addr[1]}"
                if self.http.tls is not None:
                    addr = f"https://{addr}"
                self.client.node.http_addr = addr
                self.client.start()
            # register telemetry sinks LAST: a failure anywhere above
            # leaves nothing process-global behind (shutdown only runs
            # once _started is set)
            from ..utils import metrics as _metrics

            for sink in getattr(self, "_telemetry_sinks", []):
                _metrics.register_sink(sink)
            self._started = True
        return self

    def _maybe_bootstrap_raft(self) -> None:
        if self.wire_raft is None:
            return
        with self._raft_boot_lock:
            self._maybe_bootstrap_raft_locked()

    def _maybe_bootstrap_raft_locked(self) -> None:
        """Start wire-raft elections once bootstrap_expect servers are
        known via gossip (reference: serf handler bootstraps the raft peer
        set at expect, nomad/serf.go nodeJoin → maybeBootstrap). Caller
        holds _raft_boot_lock."""
        if self._raft_started:
            return
        if self.membership is None:
            self.wire_raft.start()  # no gossip: solo (dev) raft
            self._raft_started = True
            return
        known = self.membership.servers_in_region()
        if len(known) < self.config.bootstrap_expect:
            return
        for meta in known:
            self.wire_raft.add_peer(meta.name, meta.rpc_addr)
        self.wire_raft.start()
        self._raft_started = True

    @staticmethod
    def _parse_addr(addr: str) -> Tuple[str, int]:
        host, port = addr.rsplit(":", 1)
        return (host, int(port))

    def _start_retry_join(self) -> None:
        """Join the gossip pool, retrying until at least one seed responds
        (the reference's retry_join loop, command/agent/command.go
        retryJoin). Runs in the background so startup isn't blocked by
        seeds that boot later."""
        seeds = [self._parse_addr(a) for a in self.config.retry_join]
        self._join_done = threading.Event()

        def loop() -> None:
            while not self._join_done.is_set():
                if self.membership.join(seeds) > 0:
                    self._join_done.set()
                    return
                self._join_done.wait(self.config.retry_join_interval)

        t = threading.Thread(target=loop, name="retry-join", daemon=True)
        t.start()

    def _setup_telemetry_sinks(self) -> None:
        """Fan metrics out to the configured push sinks (the reference's
        setupTelemetry, command.go:976-1018)."""
        from ..utils import metrics as _metrics

        # construct everything FIRST: a bad address raises before any
        # sink registers, so a failed start leaks nothing process-global
        sinks = []
        if self.config.telemetry_statsd_address:
            sinks.append(_metrics.StatsdSink(
                self.config.telemetry_statsd_address,
                prefix=self.config.telemetry_prefix,
            ))
        if self.config.telemetry_datadog_address:
            sinks.append(_metrics.StatsdSink(
                self.config.telemetry_datadog_address,
                prefix=self.config.telemetry_prefix,
                datadog=True, tags=self.config.telemetry_datadog_tags,
            ))
        self._telemetry_sinks = sinks

    def shutdown(self) -> None:
        with self._lock:
            if not self._started:
                return
            from ..utils import metrics as _metrics

            for sink in getattr(self, "_telemetry_sinks", []):
                _metrics.deregister_sink(sink)
            self._telemetry_sinks = []
            self.http.stop()
            if self.client is not None:
                self.client.shutdown()
            if self.autopilot is not None:
                self.autopilot.stop()
            self.monitor.detach()
            if getattr(self, "_join_done", None) is not None:
                self._join_done.set()  # stop an unfinished retry-join loop
            if self.membership is not None:
                self.membership.leave()
            if self.rpc is not None:
                self.rpc.stop()
            if self.server is not None:
                self.server.stop()
            if self.wire_raft is not None:
                self.wire_raft.close()
            self._started = False

    # -- membership hooks ------------------------------------------------

    def _on_raft_leadership(self, peer: int, is_leader: bool) -> None:
        if self.server is not None and peer == self.server.peer:
            if self.membership is not None:
                self.membership.set_leader(is_leader)
        # a NEW leader reconciles gossip membership into the replicated
        # configuration (leader.go:836 reconcile): members that joined
        # while there was no leader (or during a partition) get their
        # staged add now
        if is_leader and self.wire_raft is not None and self.membership is not None:
            for meta in self.membership.servers_in_region():
                if meta.name != self.config.name:
                    self.wire_raft.add_peer_staged(meta.name, meta.rpc_addr)

    def _on_server_change(self, meta, status: str) -> None:
        """Track the local region's leader for RPC forwarding
        (reference serf.go → leader forwarding via raft; here the leader
        tag gossips the address)."""
        if meta.region != self.config.region or self.rpc is None:
            return
        alive = status == "alive"
        if alive and meta.is_leader:
            self.rpc.leader_addr = meta.rpc_addr
        elif self.rpc.leader_addr == meta.rpc_addr:
            # the leader died, or stepped down while staying alive — either
            # way, stop forwarding writes to it
            self.rpc.leader_addr = None
        # serf → raft peer reconciliation (leader.go:859/:952). The boot
        # lock serializes against an in-flight bootstrap so a server whose
        # join races it still lands in the peer set. Only a graceful LEAVE
        # shrinks the voter set — removing peers on failure suspicion would
        # let a partitioned minority elect itself (split-brain); a failed
        # peer stays a voter and simply doesn't ack (reference: serf
        # Leave/Reap remove peers, failures don't).
        if self.wire_raft is not None:
            if alive:
                with self._raft_boot_lock:
                    if self._raft_started:
                        # post-bootstrap additions are LOG-REPLICATED: the
                        # leader stages the peer nonvoter -> voter; other
                        # nodes only retarget addresses of known peers and
                        # learn new ones from the committed config entries
                        # — a minority partition can never grow its own
                        # voter set
                        if not self.wire_raft.add_peer_staged(
                            meta.name, meta.rpc_addr
                        ):
                            self.wire_raft.note_peer_address(
                                meta.name, meta.rpc_addr
                            )
                    else:
                        self._maybe_bootstrap_raft_locked()
            elif status == "left":
                self.wire_raft.remove_peer(meta.name)

    @property
    def http_scheme(self) -> str:
        return "https" if self.http.tls is not None else "http"

    @property
    def http_addr(self) -> str:
        host, port = self.http.addr
        return f"{self.http_scheme}://{host}:{port}"

    # -- surface used by routes ------------------------------------------

    def authorize(self, req: Request, capabilities, namespace: str) -> None:
        """ACL choke point: every handler passes through here. A no-op
        until ACLs are enabled (reference: aclObj checks in every
        endpoint, e.g. job_endpoint.go:100)."""
        if self.acl_resolver is not None:
            self.acl_resolver.check_http(req, capabilities, namespace)

    def peer_names(self) -> List[str]:
        if self.server is None:
            return []
        if self.membership is not None:
            return [s.name for s in self.membership.servers_in_region()]
        return [f"{self.config.name}"]

    def remove_raft_peer(self, peer_id: str) -> None:
        """Replicated removal of a consensus peer (reference
        operator_endpoint.go RaftRemovePeerByID). Wire-raft only; the
        in-proc dev raft has no membership to mutate."""
        if self.wire_raft is None:
            raise ValueError("raft peer removal requires wire raft (-raft)")
        if peer_id == self.wire_raft.node_id:
            raise ValueError("refusing to remove self; run on another server")
        if peer_id not in self.wire_raft.peers:
            raise ValueError(f"unknown raft peer {peer_id!r}")
        self.wire_raft.remove_peer_replicated(peer_id)

    def raft_servers(self) -> List[Tuple[str, str, bool]]:
        if self.server is None:
            return []
        if self.wire_raft is not None:
            # the actual consensus configuration — this is what autopilot's
            # dead-server cleanup mutates
            out = [(
                self.wire_raft.node_id,
                "{}:{}".format(*self.rpc.addr),
                self.server.is_leader,
            )]
            leader_id = self.wire_raft.leader_id
            # snapshot: autopilot prunes peers concurrently
            for peer_id, addr in dict(self.wire_raft.peers).items():
                out.append((peer_id, "{}:{}".format(*addr), peer_id == leader_id))
            return out
        if self.membership is not None:
            return [
                (s.name, f"{s.rpc_host}:{s.rpc_port}", s.is_leader)
                for s in self.membership.servers_in_region()
            ]
        addr = (
            "{}:{}".format(*self.rpc.addr) if self.rpc is not None else self.http_addr
        )
        return [(self.config.name, addr, self.server.is_leader)]

    def _memberlist(self):
        if self.membership is None:
            raise ValueError("gossip is not enabled on this agent")
        return self.membership.memberlist

    def join(self, addrs: List[str]) -> int:
        """Runtime gossip join (reference agent Join): 'host:port' list,
        returns how many seeds responded."""
        seeds = []
        for a in addrs:
            host, _, port = a.rpartition(":")
            if not host or not port.isdigit():
                raise ValueError(f"join address {a!r} must be host:port")
            seeds.append((host, int(port)))
        return self._memberlist().join(seeds)

    def force_leave(self, name: str) -> bool:
        """Evict a (failed) gossip member (serf RemoveFailedNode)."""
        return self._memberlist().force_leave(name)

    def keyring(self, op: str, key: str):
        """Gossip keyring ops: list/install/use/remove. Mutations
        propagate cluster-wide over sealed gossip (serf's keyring ops
        are cluster queries)."""
        ml = self._memberlist()
        if op == "list":
            return ml.keyring_list()
        ml.keyring_broadcast(op, key)
        return None

    def known_servers(self) -> List[str]:
        if self.membership is not None:
            return [
                f"{s.rpc_host}:{s.rpc_port}"
                for s in self.membership.servers_in_region()
            ]
        return [self.http_addr] if self.server is not None else []

    def members(self) -> List[dict]:
        if self.server is None:
            return []
        if self.membership is not None:
            return [
                {
                    "Name": m.name,
                    "Addr": m.host,
                    "Port": m.port,
                    "Status": m.status,
                    "Leader": m.tags.get("leader") == "1",
                    "Tags": dict(m.tags),
                }
                for m in self.membership.members()
            ]
        return [
            {
                "Name": f"{self.config.name}.{self.config.region}",
                "Addr": self.http.addr[0],
                "Port": self.http.addr[1],
                "Status": "alive",
                "Leader": self.server.is_leader,
                "Tags": {
                    "region": self.config.region,
                    "dc": self.config.datacenter,
                    "role": "nomad",
                },
            }
        ]

    def regions(self) -> List[str]:
        if self.membership is not None:
            return self.membership.regions()
        return [self.config.region]

    def self_info(self) -> dict:
        stats = {}
        if self.server is not None:
            stats["nomad"] = {
                "server": "true",
                "leader": str(self.server.is_leader).lower(),
            }
        if self.client is not None:
            stats["client"] = {
                "node_id": self.client.node.id,
                "known_servers": ",".join(self.known_servers()),
            }
        return {
            "config": {
                "Region": self.config.region,
                "Datacenter": self.config.datacenter,
                "NodeName": self.config.name,
                "Server": {"Enabled": self.config.server_enabled},
                "Client": {"Enabled": self.config.client_enabled},
                "ACL": {"Enabled": self.config.acl_enabled},
                "Version": {"Version": "0.10.2-tpu"},
            },
            "stats": stats,
            "member": (self.members() or [{}])[0],
        }
