"""Agent configuration files: HCL/JSON load + defaults merging.

Fills the role of reference ``command/agent/config.go`` +
``config_parse.go``: the agent is driven by config FILES with the CLI
flags as overrides. ``-config`` takes a file or a directory (repeatable);
directories load every ``*.hcl``/``*.json`` in lexical order; later
sources merge over earlier ones key-by-key (reference Config.Merge,
config.go:1); key names match the reference's HCL schema so existing
Nomad config files map over:

    region / datacenter / name / data_dir / bind_addr / enable_debug
    ports { http rpc serf }
    advertise { rpc }
    server { enabled bootstrap_expect num_schedulers encrypt
             authoritative_region raft_protocol(ignored)
             default_scheduler_config { scheduler_algorithm chunk_k
                                        parity_sample_rate } }
    client { enabled node_class servers meta {} host_volume "n" { path } }
    acl { enabled replication_token }
    telemetry { statsd_address statsite_address datadog_address
                datadog_tags prefix flight_interval_s flight_retain
                flight_spill_dir }
    tls { http ca_file cert_file key_file verify_server_hostname }

The file model intentionally covers the knobs this agent implements; an
unknown key is an ERROR (reference config parsing is strict via
hcl.DecodeObject) so typos fail loudly at boot instead of silently
running defaults.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List

from ..jobspec import HCLError, parse_hcl
from .agent import AgentConfig


class ConfigError(ValueError):
    pass


def load_config_sources(paths: List[str]) -> Dict[str, Any]:
    """Load + merge every ``-config`` source in order."""
    merged: Dict[str, Any] = {}
    for path in paths:
        for f in _expand(path):
            merged = merge_config(merged, _load_one(f))
    return merged


def _expand(path: str) -> List[str]:
    if os.path.isdir(path):
        out = [
            os.path.join(path, name)
            for name in sorted(os.listdir(path))
            if name.endswith((".hcl", ".json")) and not name.startswith(".")
        ]
        return out
    if not os.path.exists(path):
        raise ConfigError(f"config path {path!r} does not exist")
    return [path]


def _load_one(path: str) -> Dict[str, Any]:
    with open(path) as f:
        src = f.read()
    try:
        if path.endswith(".json"):
            data = json.loads(src or "{}")
        else:
            data = parse_hcl(src).to_plain()
    except (HCLError, ValueError) as e:
        raise ConfigError(f"{path}: {e}") from e
    if not isinstance(data, dict):
        raise ConfigError(f"{path}: top level must be an object")
    return data


def merge_config(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    """Recursive key-wise merge; scalars and lists in the overlay replace,
    objects merge (reference Config.Merge semantics)."""
    out = dict(base)
    for k, v in overlay.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = merge_config(out[k], v)
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# file model -> AgentConfig
# ---------------------------------------------------------------------------

_TOP_KEYS = {
    "region", "datacenter", "name", "data_dir", "bind_addr", "enable_debug",
    "ports", "advertise", "server", "client", "acl", "telemetry", "tls",
    "log_level", "disable_update_check", "leave_on_interrupt",
    "leave_on_terminate",
}
_PORT_KEYS = {"http", "rpc", "serf"}
_SERVER_KEYS = {
    "enabled", "bootstrap_expect", "num_schedulers", "encrypt",
    "authoritative_region", "retry_join", "wire_raft", "raft_protocol",
    "default_scheduler_config",
}
_CLIENT_KEYS = {
    "enabled", "node_class", "servers", "meta", "host_volume",
}
_ACL_KEYS = {"enabled", "replication_token", "token_ttl", "policy_ttl"}
_TELEMETRY_KEYS = {
    "statsd_address", "statsite_address", "datadog_address", "datadog_tags",
    "prefix", "prometheus_metrics", "collection_interval",
    "flight_interval_s", "flight_retain", "flight_spill_dir",
}
_TLS_KEYS = {
    "http", "rpc", "ca_file", "cert_file", "key_file",
    "verify_server_hostname",
}


def _check_keys(obj: Dict[str, Any], allowed: set, where: str) -> None:
    unknown = set(obj) - allowed
    if unknown:
        raise ConfigError(
            f"unknown {where} config key(s): {', '.join(sorted(unknown))}"
        )


def _as_bool(v: Any, where: str) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    raise ConfigError(f"{where}: expected bool, got {v!r}")


def _as_list(v: Any) -> List[str]:
    if v is None:
        return []
    if isinstance(v, str):
        return [v]
    return [str(x) for x in v]


def apply_file_config(cfg: AgentConfig, data: Dict[str, Any]) -> AgentConfig:
    """Overlay a merged config-file dict onto an AgentConfig (which
    carries the defaults). Returns a NEW AgentConfig; ``cfg`` is not
    mutated. CLI flags are applied by the caller AFTER this, giving the
    reference's defaults < files < flags precedence."""
    cfg = dataclasses.replace(cfg)
    _check_keys(data, _TOP_KEYS, "top-level")

    if "region" in data:
        cfg.region = str(data["region"])
    if "datacenter" in data:
        cfg.datacenter = str(data["datacenter"])
    if "name" in data:
        cfg.name = str(data["name"])
    if "data_dir" in data:
        cfg.data_dir = str(data["data_dir"])
    if "bind_addr" in data:
        cfg.http_bind = cfg.rpc_bind = cfg.serf_bind = str(data["bind_addr"])
    if "enable_debug" in data:
        cfg.enable_debug = _as_bool(data["enable_debug"], "enable_debug")

    ports = data.get("ports") or {}
    _check_keys(ports, _PORT_KEYS, "ports")
    if "http" in ports:
        cfg.http_port = int(ports["http"])
    if "rpc" in ports:
        cfg.rpc_port = int(ports["rpc"])
    if "serf" in ports:
        cfg.serf_port = int(ports["serf"])

    adv = data.get("advertise") or {}
    _check_keys(adv, {"http", "rpc", "serf"}, "advertise")
    if "rpc" in adv:
        cfg.advertise_addr = str(adv["rpc"])

    srv = data.get("server") or {}
    _check_keys(srv, _SERVER_KEYS, "server")
    if "enabled" in srv:
        cfg.server_enabled = _as_bool(srv["enabled"], "server.enabled")
    if "bootstrap_expect" in srv:
        cfg.bootstrap_expect = int(srv["bootstrap_expect"])
    if "num_schedulers" in srv:
        cfg.num_schedulers = int(srv["num_schedulers"])
    if "encrypt" in srv:
        cfg.encrypt = str(srv["encrypt"])
    if "authoritative_region" in srv:
        cfg.authoritative_region = str(srv["authoritative_region"])
    if "retry_join" in srv:
        cfg.retry_join = _as_list(srv["retry_join"])
    if "wire_raft" in srv:
        cfg.wire_raft = _as_bool(srv["wire_raft"], "server.wire_raft")
    dsc = srv.get("default_scheduler_config") or {}
    if "scheduler_algorithm" in dsc:
        cfg.scheduler_algorithm = str(dsc["scheduler_algorithm"])
    if "chunk_k" in dsc:
        cfg.chunk_k = int(dsc["chunk_k"])
    if "parity_sample_rate" in dsc:
        cfg.parity_sample_rate = float(dsc["parity_sample_rate"])

    cli = data.get("client") or {}
    _check_keys(cli, _CLIENT_KEYS, "client")
    if "enabled" in cli:
        cfg.client_enabled = _as_bool(cli["enabled"], "client.enabled")
    if "node_class" in cli:
        cfg.node_class = str(cli["node_class"])
    if "servers" in cli:
        cfg.servers = _as_list(cli["servers"])
    if "meta" in cli:
        cfg.meta = {str(k): str(v) for k, v in (cli["meta"] or {}).items()}
    if "host_volume" in cli:
        vols: Dict[str, str] = {}
        for vname, spec in (cli["host_volume"] or {}).items():
            if not isinstance(spec, dict) or "path" not in spec:
                raise ConfigError(
                    f"client.host_volume.{vname}: needs a path attribute"
                )
            vols[str(vname)] = str(spec["path"])
        cfg.host_volumes = vols

    acl = data.get("acl") or {}
    _check_keys(acl, _ACL_KEYS, "acl")
    if "enabled" in acl:
        cfg.acl_enabled = _as_bool(acl["enabled"], "acl.enabled")
    if "replication_token" in acl:
        cfg.replication_token = str(acl["replication_token"])

    tel = data.get("telemetry") or {}
    _check_keys(tel, _TELEMETRY_KEYS, "telemetry")
    # statsite speaks the statsd line protocol; both map onto the
    # statsd push sink (command/agent/command.go:976-1018)
    if "statsd_address" in tel:
        cfg.telemetry_statsd_address = str(tel["statsd_address"])
    elif "statsite_address" in tel:
        cfg.telemetry_statsd_address = str(tel["statsite_address"])
    if "datadog_address" in tel:
        cfg.telemetry_datadog_address = str(tel["datadog_address"])
    if "datadog_tags" in tel:
        cfg.telemetry_datadog_tags = {
            str(k): str(v) for k, v in (tel["datadog_tags"] or {}).items()
        }
    if "prefix" in tel:
        cfg.telemetry_prefix = str(tel["prefix"])
    if "flight_interval_s" in tel:
        cfg.flight_interval_s = float(tel["flight_interval_s"])
    if "flight_retain" in tel:
        cfg.flight_retain = int(tel["flight_retain"])
    if "flight_spill_dir" in tel:
        cfg.flight_spill_dir = str(tel["flight_spill_dir"])

    tls = data.get("tls") or {}
    _check_keys(tls, _TLS_KEYS, "tls")
    if "ca_file" in tls:
        cfg.tls_ca_file = str(tls["ca_file"])
    if "cert_file" in tls:
        cfg.tls_cert_file = str(tls["cert_file"])
    if "key_file" in tls:
        cfg.tls_key_file = str(tls["key_file"])
    if "http" in tls:
        cfg.tls_http = _as_bool(tls["http"], "tls.http")
    if "verify_server_hostname" in tls:
        cfg.tls_verify_server_hostname = _as_bool(
            tls["verify_server_hostname"], "tls.verify_server_hostname"
        )

    return cfg


def load_agent_config(paths: List[str],
                      base: AgentConfig | None = None) -> AgentConfig:
    """defaults -> files (in order) -> returned AgentConfig."""
    return apply_file_config(base or AgentConfig(), load_config_sources(paths))
