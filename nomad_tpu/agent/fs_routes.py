"""Alloc filesystem + logs HTTP endpoints.

Fills the role of reference ``client/fs_endpoint.go`` (FileSystem.List/
Stat/Stream/Logs) + ``command/agent/fs_endpoint.go`` (/v1/client/fs/*)
+ the server→client proxy (``nomad/client_fs_endpoint.go``): an agent
serves requests for allocs on its own client directly from the alloc dir;
for remote allocs a server-mode agent forwards the HTTP request to the
owning node's advertised HTTP address (the reference proxies over
streaming RPC — same hop, this transport is HTTP).
"""
from __future__ import annotations

import os
import urllib.parse
import urllib.request
from typing import Optional

from .http import Hijacker, HTTPError, HTTPServer, Request, StreamingResponse
from .routes import _tail


# Follow-mode streams end after this long with no data AND no way to
# observe the peer (disconnects only surface on write); bounds the threads
# abandoned followers can pin.
MAX_STREAM_IDLE_S = 600.0


class FSRoutes:
    def __init__(self, agent) -> None:
        self.agent = agent

    def register_all(self, mux: HTTPServer) -> None:
        mux.register("/v1/client/fs/ls/", self.ls)
        mux.register("/v1/client/fs/stat/", self.stat)
        mux.register("/v1/client/fs/cat/", self.cat)
        mux.register("/v1/client/fs/readat/", self.readat)
        mux.register("/v1/client/fs/logs/", self.logs)
        mux.register("/v1/client/stats", self.host_stats)
        mux.register("/v1/client/allocation/", self.alloc_stats)

    # -- helpers ---------------------------------------------------------

    def _authorize(self, req: Request, alloc_id: str, capability: str) -> None:
        """Enforce namespace fs/logs capabilities (reference
        fs_endpoint.go:~40 aclObj.AllowNsOp(ns, readFS/readLogs))."""
        namespace = "default"
        server = self.agent.server
        if server is not None:
            alloc = server.fsm.state.alloc_by_id(alloc_id)
            if alloc is not None:
                namespace = alloc.namespace
        elif self.agent.client is not None:
            ar = self.agent.client.allocrunners.get(alloc_id)
            if ar is not None:
                namespace = ar.alloc.namespace
        self.agent.authorize(req, (capability,), namespace)

    def _alloc_root(self, alloc_id: str) -> Optional[str]:
        """The alloc's directory if it lives on this agent's client."""
        client = self.agent.client
        if client is None:
            return None
        root = os.path.join(client.alloc_dir_base, alloc_id)
        return root if os.path.isdir(root) else None

    def _safe_path(self, root: str, rel: str) -> str:
        """Resolve ``rel`` inside ``root``; reject escapes
        (fs_endpoint.go uses filepath.Clean + prefix check)."""
        candidate = os.path.realpath(os.path.join(root, rel.lstrip("/")))
        real_root = os.path.realpath(root)
        if candidate != real_root and not candidate.startswith(real_root + os.sep):
            raise HTTPError(403, "path escapes allocation directory")
        return candidate

    def _forward(self, req: Request, http_addr: str, path: str,
                 method: str = "GET", body: bytes = b"") -> bytes:
        """One node-addressed HTTP hop with token + query passthrough."""
        query = urllib.parse.urlencode(
            {k: v[0] for k, v in req.query.items()}, safe="/"
        )
        base = http_addr if "://" in http_addr else f"http://{http_addr}"
        url = f"{base}{path}"
        if query:
            url += f"?{query}"
        preq = urllib.request.Request(url, method=method, data=body or None)
        token = req.options.auth_token
        if token:
            preq.add_header("X-Nomad-Token", token)
        ctx = None
        if url.startswith("https://") and self.agent.tls is not None:
            ctx = self.agent.tls.http_client_context()
        try:
            with urllib.request.urlopen(preq, timeout=30, context=ctx) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            raise HTTPError(e.code, e.read().decode(errors="replace"))
        except OSError as e:
            raise HTTPError(502, f"proxy to {http_addr} failed: {e}")

    def _resolve_remote_node(self, alloc_id: str):
        """The node owning the alloc, for server→client forwarding.
        Raises 404 when the node is unknown, unreachable, or IS this very
        agent (a self-proxy would recurse until fd exhaustion)."""
        server = self.agent.server
        if server is None:
            raise HTTPError(404, f"alloc {alloc_id} not on this node")
        alloc = server.fsm.state.alloc_by_id(alloc_id)
        if alloc is None:
            raise HTTPError(404, f"unknown allocation {alloc_id}")
        node = server.fsm.state.node_by_id(alloc.node_id)
        if node is None or not node.http_addr:
            raise HTTPError(
                404, f"node for alloc {alloc_id} has no reachable HTTP address"
            )
        if node.http_addr.split("://")[-1] == "{}:{}".format(*self.agent.http.addr):
            raise HTTPError(404, f"alloc {alloc_id} directory not found")
        return node

    def _proxy(self, req: Request, alloc_id: str, method: str = "GET",
               body: bytes = b"") -> bytes:
        """Forward to the node that owns the alloc (client_fs_endpoint.go
        server→client hop)."""
        node = self._resolve_remote_node(alloc_id)
        return self._forward(req, node.http_addr, req.path, method, body)

    # -- handlers --------------------------------------------------------

    def ls(self, req: Request):
        alloc_id = _tail(req, "/v1/client/fs/ls/")
        self._authorize(req, alloc_id, "read-fs")
        root = self._alloc_root(alloc_id)
        if root is None:
            import json

            return json.loads(self._proxy(req, alloc_id) or b"[]")
        path = self._safe_path(root, req.param("path", "/"))
        if not os.path.exists(path):
            raise HTTPError(404, f"path {req.param('path', '/')} not found")
        entries = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append({
                "Name": name,
                "IsDir": os.path.isdir(full),
                "Size": st.st_size,
                "FileMode": oct(st.st_mode & 0o777),
                "ModTime": int(st.st_mtime),
            })
        return entries

    def stat(self, req: Request):
        alloc_id = _tail(req, "/v1/client/fs/stat/")
        self._authorize(req, alloc_id, "read-fs")
        root = self._alloc_root(alloc_id)
        if root is None:
            import json

            return json.loads(self._proxy(req, alloc_id) or b"{}")
        path = self._safe_path(root, req.param("path", "/"))
        if not os.path.exists(path):
            raise HTTPError(404, f"path {req.param('path', '/')} not found")
        st = os.stat(path)
        return {
            "Name": os.path.basename(path) or "/",
            "IsDir": os.path.isdir(path),
            "Size": st.st_size,
            "FileMode": oct(st.st_mode & 0o777),
            "ModTime": int(st.st_mtime),
        }

    def cat(self, req: Request) -> bytes:
        alloc_id = _tail(req, "/v1/client/fs/cat/")
        self._authorize(req, alloc_id, "read-fs")
        root = self._alloc_root(alloc_id)
        if root is None:
            return self._proxy(req, alloc_id)
        path = self._safe_path(root, req.param("path", "/"))
        if not os.path.isfile(path):
            raise HTTPError(404, f"file {req.param('path', '/')} not found")
        with open(path, "rb") as f:
            return f.read()

    def readat(self, req: Request) -> bytes:
        alloc_id = _tail(req, "/v1/client/fs/readat/")
        self._authorize(req, alloc_id, "read-fs")
        root = self._alloc_root(alloc_id)
        if root is None:
            return self._proxy(req, alloc_id)
        path = self._safe_path(root, req.param("path", "/"))
        if not os.path.isfile(path):
            raise HTTPError(404, f"file {req.param('path', '/')} not found")
        try:
            offset = int(req.param("offset", "0"))
            limit = int(req.param("limit", str(1 << 20)))
        except ValueError:
            raise HTTPError(400, "offset/limit must be integers")
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(max(0, limit))

    def host_stats(self, req: Request):
        """/v1/client/stats (reference command/agent/stats_endpoint.go →
        ClientStats.Stats, ACL NodeRead): host CPU/memory/disk/uptime. On a
        server agent, ?node_id= proxies to that node
        (client_stats_endpoint.go rpcHandlerForNode)."""
        self.agent.authorize(req, ("node:read",), "default")
        node_id = req.param("node_id")
        local = self.agent.client
        if node_id and (local is None or local.node.id != node_id):
            # not (or not only) this node: hop to the target's agent
            server = self.agent.server
            if server is None:
                raise HTTPError(404, f"node {node_id} is not this client")
            node = server.fsm.state.node_by_id(node_id)
            if node is None or not node.http_addr:
                raise HTTPError(404, f"node {node_id} has no reachable HTTP address")
            import json as json_mod

            return json_mod.loads(self._forward(
                req, node.http_addr, "/v1/client/stats") or b"{}")
        if local is None:
            raise HTTPError(404, "not a client node (pass ?node_id= on servers)")
        import os as os_mod
        import shutil as shutil_mod
        import time as time_mod

        mem = {}
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, v = line.partition(":")
                    mem[k.strip()] = int(v.split()[0]) * 1024
        except (OSError, ValueError, IndexError):
            pass
        try:
            load1, load5, load15 = os_mod.getloadavg()
        except OSError:
            load1 = load5 = load15 = 0.0
        try:
            du = shutil_mod.disk_usage(self.agent.client.config.state_dir)
            disk = {"Size": du.total, "Used": du.used, "Available": du.free,
                    "UsedPercent": du.used / du.total * 100.0 if du.total else 0.0,
                    "Device": self.agent.client.config.state_dir}
        except OSError:
            disk = {}
        uptime = 0
        try:
            with open("/proc/uptime") as f:
                uptime = int(float(f.read().split()[0]))
        except (OSError, ValueError, IndexError):
            pass
        return {
            "Timestamp": time_mod.time_ns(),
            "Uptime": uptime,
            "CPUTicksConsumed": 0.0,
            "CPU": [{"CPU": f"cpu{i}"} for i in range(os_mod.cpu_count() or 1)],
            "LoadAvg": {"Load1": load1, "Load5": load5, "Load15": load15},
            "Memory": {
                "Total": mem.get("MemTotal", 0),
                "Available": mem.get("MemAvailable", 0),
                "Used": max(0, mem.get("MemTotal", 0) - mem.get("MemAvailable", 0)),
                "Free": mem.get("MemFree", 0),
            },
            "DiskStats": [disk] if disk else [],
        }

    def alloc_stats(self, req: Request):
        """/v1/client/allocation/<id>/{stats,restart,signal,exec}
        (reference client_allocations_endpoint.go + alloc_endpoint.go):
        stats aggregation plus task lifecycle verbs."""
        rest = _tail(req, "/v1/client/allocation/")
        alloc_id, _, verb = rest.partition("/")
        if verb in ("restart", "signal", "exec"):
            return self._alloc_lifecycle(req, alloc_id, verb)
        if verb != "stats":
            raise HTTPError(404, f"no handler for {req.path}")
        self._authorize(req, alloc_id, "read-job")
        client = self.agent.client
        runner = client.allocrunners.get(alloc_id) if client is not None else None
        if runner is None:
            import json

            return json.loads(self._proxy(req, alloc_id) or b"{}")
        import time as time_mod

        tasks = {}
        total_cpu = 0.0
        total_rss = 0
        for name, tr in runner.task_runners.items():
            try:
                st = tr.driver.task_stats(tr.task_id)
            except Exception:  # noqa: BLE001 — dead tasks have no stats
                continue
            tasks[name] = {
                "ResourceUsage": {
                    "CpuStats": {"Percent": st.cpu_percent},
                    "MemoryStats": {"RSS": st.memory_rss_bytes},
                },
                "Timestamp": st.timestamp_ns,
            }
            total_cpu += st.cpu_percent
            total_rss += st.memory_rss_bytes
        return {
            "ResourceUsage": {
                "CpuStats": {"Percent": total_cpu},
                "MemoryStats": {"RSS": total_rss},
            },
            "Tasks": tasks,
            "Timestamp": time_mod.time_ns(),
        }

    def _alloc_lifecycle(self, req: Request, alloc_id: str, verb: str):
        """restart/signal: alloc-lifecycle capability; exec: alloc-exec
        (reference acl.NamespaceCapabilityAllocLifecycle / AllocExec)."""
        cap = "alloc-exec" if verb == "exec" else "alloc-lifecycle"
        self._authorize(req, alloc_id, cap)
        client = self.agent.client
        runner = client.allocrunners.get(alloc_id) if client is not None else None
        if verb == "exec" and (req.headers.get("Upgrade") or "").lower() == "websocket":
            # INTERACTIVE exec (alloc_endpoint.go execStream): upgrade to a
            # websocket and bridge json-framed stdio to the task
            if runner is None:
                return self._exec_ws_bridge(req, alloc_id)
            return self._exec_ws_local(req, runner)
        if runner is None:
            import json

            return json.loads(self._proxy(req, alloc_id, method=req.method,
                                          body=req.body) or b"{}")
        body = {}
        if req.body:
            import json

            try:
                body = json.loads(req.body)
            except ValueError:
                raise HTTPError(400, "bad request body")
        task = body.get("Task", "") or req.param("task", "")
        if verb == "restart":
            runner.restart_task(task)
            return {"Index": 0}
        if verb == "signal":
            sig = body.get("Signal", "") or req.param("signal", "SIGTERM")
            try:
                runner.signal_task(task, sig)
            except KeyError:
                raise HTTPError(404, f"unknown task {task!r}")
            except Exception as e:  # noqa: BLE001 — bad signal names are 400s
                raise HTTPError(400, str(e))
            return {"Index": 0}
        # exec (one-shot, non-interactive)
        cmd = body.get("Cmd") or []
        if not task or not cmd:
            raise HTTPError(400, "exec requires Task and Cmd")
        try:
            timeout_s = float(req.param("timeout", "30"))
        except ValueError:
            raise HTTPError(400, "timeout must be a number")
        try:
            output, code = runner.exec_task(task, cmd, timeout_s)
        except KeyError:
            raise HTTPError(404, f"unknown task {task!r}")
        except Exception as e:  # noqa: BLE001 — driver may not support exec
            raise HTTPError(400, str(e))
        return {"Output": output.decode(errors="replace"), "ExitCode": code}

    def _exec_ws_local(self, req: Request, runner):
        """Serve an interactive exec session over a websocket upgrade.
        Frames are json, reference exec protocol shape:
          client -> {"stdin": {"data": b64}} | {"stdin": {"close": true}}
          server -> {"stdout": {"data": b64}} ... {"exit_code": N}
        """
        import base64
        import json
        import threading

        from . import websocket as ws

        task = req.param("task", "")
        try:
            cmd = json.loads(req.param("command", "[]"))
        except ValueError:
            raise HTTPError(400, "command must be a json array")
        if not task or not cmd:
            raise HTTPError(400, "exec requires task and command parameters")
        try:
            session = runner.exec_task_streaming(task, cmd)
        except KeyError:
            raise HTTPError(404, f"unknown task {task!r}")
        except Exception as e:  # noqa: BLE001 — driver may not support it
            raise HTTPError(400, str(e))

        def serve(handler) -> None:
            if not ws.server_handshake(handler):
                session.kill()
                return
            stop = threading.Event()

            def pump_stdin() -> None:
                try:
                    while not stop.is_set():
                        opcode, payload = ws.read_frame(handler.rfile)
                        if opcode == ws.OP_CLOSE:
                            session.stdin_close()
                            return
                        if opcode == ws.OP_PING:
                            ws.write_frame(handler.wfile, payload, ws.OP_PONG)
                            continue
                        try:
                            frame = json.loads(payload or b"{}")
                        except ValueError:
                            continue
                        stdin = frame.get("stdin") or {}
                        if stdin.get("close"):
                            session.stdin_close()
                        elif stdin.get("data"):
                            session.stdin_write(base64.b64decode(stdin["data"]))
                except (ConnectionError, OSError):
                    session.kill()

            t = threading.Thread(target=pump_stdin, daemon=True)
            t.start()
            try:
                while True:
                    chunk = session.read_output(timeout=0.25)
                    if chunk is None:
                        break
                    if chunk:
                        frame = json.dumps({
                            "stdout": {"data": base64.b64encode(chunk).decode()}
                        }).encode()
                        ws.write_frame(handler.wfile, frame, ws.OP_TEXT)
                code = session.exit_code()
                ws.write_frame(
                    handler.wfile,
                    json.dumps({"exit_code": 0 if code is None else code}).encode(),
                    ws.OP_TEXT,
                )
                ws.write_frame(handler.wfile, b"", ws.OP_CLOSE)
            except (BrokenPipeError, ConnectionResetError, OSError):
                session.kill()
            finally:
                stop.set()

        return Hijacker(serve)

    def _exec_ws_bridge(self, req: Request, alloc_id: str):
        """Server-mode agent: bridge the websocket to the owning node
        (the reference's server->client streaming-RPC hop)."""
        node = self._resolve_remote_node(alloc_id)
        addr = node.http_addr.split("://")[-1]
        host, _, port = addr.rpartition(":")
        query = urllib.parse.urlencode(
            {k: v[0] for k, v in req.query.items()}, safe="/"
        )
        path = req.path + (f"?{query}" if query else "")
        headers = {}
        if req.options.auth_token:
            headers["X-Nomad-Token"] = req.options.auth_token
        tls_ctx = None
        if node.http_addr.startswith("https://") and self.agent.tls is not None:
            tls_ctx = self.agent.tls.http_client_context()

        from . import websocket as ws

        def serve(handler) -> None:
            import threading

            try:
                upstream = ws.WebSocketClient(
                    host, int(port), path, headers=headers, tls_context=tls_ctx
                )
            except (OSError, ConnectionError) as e:
                handler.wfile.write(
                    f"HTTP/1.1 502 Bad Gateway\r\nContent-Length: 0\r\n\r\n".encode()
                )
                return
            if not ws.server_handshake(handler):
                upstream.close()
                return

            def downstream_to_upstream() -> None:
                try:
                    while True:
                        opcode, payload = ws.read_frame(handler.rfile)
                        if opcode == ws.OP_CLOSE:
                            upstream.close()
                            return
                        upstream.send(payload, opcode)
                except (ConnectionError, OSError):
                    upstream.close()

            t = threading.Thread(target=downstream_to_upstream, daemon=True)
            t.start()
            try:
                while True:
                    opcode, payload = upstream.recv()
                    if opcode == ws.OP_CLOSE:
                        ws.write_frame(handler.wfile, b"", ws.OP_CLOSE)
                        return
                    ws.write_frame(handler.wfile, payload, opcode)
            except (ConnectionError, OSError):
                pass

        return Hijacker(serve)

    def logs(self, req: Request):
        """Log read across the rotated sequence (fs_endpoint.go logs).
        ``follow=true`` switches to SERVER-PUSH streaming: the agent keeps
        the response open and pushes new bytes as the task writes them
        (the reference's streaming-RPC log frames; chunked here)."""
        alloc_id = _tail(req, "/v1/client/fs/logs/")
        self._authorize(req, alloc_id, "read-logs")
        follow = req.param("follow", "") in ("true", "1")
        root = self._alloc_root(alloc_id)
        if root is None:
            if follow:
                return self._proxy_stream(req, alloc_id)
            return self._proxy(req, alloc_id)
        task = req.param("task", "")
        if not task:
            raise HTTPError(400, "task parameter required")
        kind = req.param("type", "stdout")
        if kind not in ("stdout", "stderr"):
            raise HTTPError(400, "type must be stdout or stderr")
        try:
            offset = int(req.param("offset", "0"))
        except ValueError:
            raise HTTPError(400, "offset must be an integer")
        origin = req.param("origin", "start")
        from ..client.logmon import read_logs

        log_dir = os.path.join(root, "alloc", "logs")
        if not follow:
            data, next_offset = read_logs(
                log_dir, task, kind, offset=offset, origin=origin
            )
            req.response_index = next_offset
            return data

        runner = (self.agent.client.allocrunners.get(alloc_id)
                  if self.agent.client is not None else None)

        def task_dead() -> bool:
            if runner is None:
                return True
            tr = runner.task_runners.get(task)
            return tr is None or tr.done.is_set()

        def stream():
            import time as time_mod

            pos = offset
            first_origin = origin
            idle_deadline = time_mod.monotonic() + MAX_STREAM_IDLE_S
            while True:
                data, pos = read_logs(
                    log_dir, task, kind, offset=pos, origin=first_origin
                )
                first_origin = "start"  # offsets are absolute afterwards
                if data:
                    idle_deadline = time_mod.monotonic() + MAX_STREAM_IDLE_S
                    yield data
                    continue
                # the reference's frame stream ends at task completion;
                # the idle cap bounds abandoned followers (a disconnect
                # is only detectable on write)
                if task_dead() or time_mod.monotonic() > idle_deadline:
                    return
                time_mod.sleep(0.2)

        return StreamingResponse(stream())

    def _proxy_stream(self, req: Request, alloc_id: str):
        """Streaming pass-through to the owning node (server→client hop
        for follow-mode logs)."""
        node = self._resolve_remote_node(alloc_id)
        query = urllib.parse.urlencode(
            {k: v[0] for k, v in req.query.items()}, safe="/"
        )
        base = node.http_addr if "://" in node.http_addr else f"http://{node.http_addr}"
        url = f"{base}{req.path}"
        if query:
            url += f"?{query}"
        preq = urllib.request.Request(url)
        if req.options.auth_token:
            preq.add_header("X-Nomad-Token", req.options.auth_token)
        ctx = None
        if url.startswith("https://") and self.agent.tls is not None:
            ctx = self.agent.tls.http_client_context()
        try:
            resp = urllib.request.urlopen(preq, timeout=3600, context=ctx)
        except urllib.error.HTTPError as e:
            raise HTTPError(e.code, e.read().decode(errors="replace"))
        except OSError as e:
            raise HTTPError(502, f"proxy to {node.http_addr} failed: {e}")

        def stream():
            try:
                while True:
                    chunk = resp.read1(8192) if hasattr(resp, "read1") else resp.read(8192)
                    if not chunk:
                        return
                    yield chunk
            finally:
                resp.close()

        return StreamingResponse(stream())
