"""HTTP transport for the /v1 API.

Fills the role of the reference's ``command/agent/http.go``: a mux of
prefix-registered handlers (registerHandlers :150–224) behind a ``wrap``
that does JSON encoding, blocking-query parameters (index/wait), the
pretty flag, the ACL token header, and the X-Nomad-Index response
headers. Built on the stdlib threading HTTP server — one thread per
in-flight request, which is what blocking queries need.
"""
from __future__ import annotations

import json
import re
import threading
import traceback
import urllib.parse
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import jsonapi


class HTTPError(Exception):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code
        self.message = message


_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DUR_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(s: str) -> float:
    """Go-style duration string ("5s", "1m30s", "150ms") -> seconds."""
    if not s:
        return 0.0
    try:
        return float(s)  # bare number = seconds
    except ValueError:
        pass
    total, pos = 0.0, 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise HTTPError(400, f"invalid duration {s!r}")
        total += float(m.group(1)) * _DUR_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise HTTPError(400, f"invalid duration {s!r}")
    return total


@dataclass
class QueryOptions:
    """Parsed blocking-query / common request params (api QueryOptions)."""

    min_index: int = 0
    wait: float = 0.0
    namespace: str = "default"
    region: str = ""
    prefix: str = ""
    auth_token: str = ""
    stale: bool = False


@dataclass
class Request:
    method: str
    path: str
    query: Dict[str, List[str]]
    body: bytes
    headers: Dict[str, str]
    options: QueryOptions = field(default_factory=QueryOptions)
    # handlers set this to stamp X-Nomad-Index
    response_index: Optional[int] = None
    # handlers returning bytes may override the content type (UI assets)
    response_content_type: Optional[str] = None

    def param(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def json(self, cls=None):
        try:
            return jsonapi.loads(cls, self.body.decode("utf-8") if self.body else "")
        except (ValueError, TypeError) as e:
            raise HTTPError(400, f"bad request body: {e}")


Handler = Callable[[Request], Any]


class StreamingResponse:
    """Handler return value that streams chunks to the client
    (Transfer-Encoding: chunked) — the HTTP realization of the
    reference's streaming RPC frames (structs/streaming_rpc.go,
    command/agent/http.go:187). ``gen`` yields bytes; the stream ends
    when it returns or the client disconnects."""

    def __init__(self, gen, content_type: str = "application/octet-stream") -> None:
        self.gen = gen
        self.content_type = content_type


class Hijacker:
    """Handler return value that takes over the raw connection (the
    reference's WebSocket upgrade path for interactive exec,
    alloc_endpoint.go execStream). ``fn`` receives the
    BaseHTTPRequestHandler; it owns the socket afterwards."""

    def __init__(self, fn) -> None:
        self.fn = fn


class HTTPServer:
    """Prefix-matching mux + JSON wrap, mirroring http.go's mux semantics."""

    def __init__(self, bind: str = "127.0.0.1", port: int = 0,
                 tls=None) -> None:
        self._routes: List[Tuple[str, Handler]] = []
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._bind = bind
        self._port = port
        self.tls = tls  # rpc.transport.TLSConfig: serve HTTPS with mTLS

    def register(self, prefix: str, handler: Handler) -> None:
        self._routes.append((prefix, handler))
        # longest prefix wins, like Go's ServeMux
        self._routes.sort(key=lambda r: len(r[0]), reverse=True)

    def lookup(self, path: str) -> Optional[Handler]:
        for prefix, handler in self._routes:
            if prefix.endswith("/"):
                if path.startswith(prefix) or path == prefix[:-1]:
                    return handler
            elif path == prefix:
                return handler
        return None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        mux = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _handle(self):
                parsed = urllib.parse.urlsplit(self.path)
                path = parsed.path
                query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req = Request(
                    method=self.command,
                    path=path,
                    query=query,
                    body=body,
                    headers={k: v for k, v in self.headers.items()},
                )
                opts = req.options
                if "index" in query:
                    try:
                        opts.min_index = int(query["index"][0])
                    except ValueError:
                        return self._send_err(400, "invalid index")
                if "wait" in query:
                    try:
                        opts.wait = parse_duration(query["wait"][0])
                    except HTTPError as e:
                        return self._send_err(e.code, e.message)
                opts.namespace = req.param("namespace", "default")
                opts.region = req.param("region", "")
                opts.prefix = req.param("prefix", "")
                opts.stale = "stale" in query
                opts.auth_token = (
                    self.headers.get("X-Nomad-Token") or req.param("token", "")
                )

                handler = mux.lookup(path)
                if handler is None:
                    return self._send_err(404, f"no handler for {path}")
                try:
                    result = handler(req)
                except HTTPError as e:
                    return self._send_err(e.code, e.message)
                except PermissionError as e:
                    return self._send_err(403, str(e) or "Permission denied")
                except KeyError as e:
                    return self._send_err(404, str(e))
                except Exception as e:  # 500 with message, like wrap()
                    traceback.print_exc()
                    return self._send_err(500, f"{type(e).__name__}: {e}")
                if isinstance(result, Hijacker):
                    self.close_connection = True
                    result.fn(self)
                    return
                if isinstance(result, StreamingResponse):
                    return self._send_stream(result, req)
                self._send_json(result, req)

            def _send_stream(self, stream: "StreamingResponse", req: Request):
                self.send_response(200)
                self.send_header("Content-Type", stream.content_type)
                self.send_header("Transfer-Encoding", "chunked")
                if req.response_index is not None:
                    self.send_header("X-Nomad-Index", str(req.response_index))
                self.end_headers()
                self.close_connection = True
                try:
                    for chunk in stream.gen:
                        if not chunk:
                            continue
                        self.wfile.write(b"%x\r\n" % len(chunk))
                        self.wfile.write(chunk)
                        self.wfile.write(b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away — generator GC closes sources
                finally:
                    close = getattr(stream.gen, "close", None)
                    if close is not None:
                        close()

            def _send_json(self, obj, req: Request):
                if isinstance(obj, bytes):
                    payload = obj
                    ctype = req.response_content_type or "application/octet-stream"
                else:
                    pretty = "pretty" in req.query
                    payload = jsonapi.dumps(obj, pretty=pretty).encode("utf-8")
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                if req.response_index is not None:
                    self.send_header("X-Nomad-Index", str(req.response_index))
                    self.send_header("X-Nomad-KnownLeader", "true")
                    self.send_header("X-Nomad-LastContact", "0")
                self.end_headers()
                self.wfile.write(payload)

            def _send_err(self, code: int, message: str):
                payload = message.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            do_GET = _handle
            do_POST = _handle
            do_PUT = _handle
            do_DELETE = _handle

        tls_cfg = self.tls

        class _Server(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                # disconnects mid-stream (follow-mode consumers hitting
                # Ctrl-C) are peer-side events; anything else escaped the
                # route wrapper and keeps its traceback via logging
                import logging as logging_mod
                import sys as sys_mod

                exc = sys_mod.exc_info()[1]
                log = logging_mod.getLogger("nomad_tpu.http")
                if isinstance(exc, (ConnectionError, TimeoutError,
                                    BrokenPipeError)):
                    log.debug("connection from %s dropped: %s",
                              client_address, exc)
                else:
                    log.warning("request from %s crashed", client_address,
                                exc_info=True)

            def finish_request(self, request, client_address):
                # handshake in the per-connection thread: wrapping the
                # LISTENER would run handshakes in the accept loop, where
                # one stalled client freezes the whole API
                if tls_cfg is not None:
                    import ssl as ssl_mod

                    try:
                        request.settimeout(30)
                        request = tls_cfg.server_context().wrap_socket(
                            request, server_side=True
                        )
                        request.settimeout(None)
                    except (OSError, ssl_mod.SSLError):
                        try:
                            request.close()
                        except OSError:
                            pass
                        return
                super().finish_request(request, client_address)

        self._server = _Server((self._bind, self._port), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="http", daemon=True
        )
        self._thread.start()

    @property
    def addr(self) -> Tuple[str, int]:
        assert self._server is not None
        return self._server.server_address[:2]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
