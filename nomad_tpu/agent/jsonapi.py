"""JSON ⇆ dataclass codec for the HTTP API.

The reference serves Go structs whose JSON keys are the exported Go field
names ("ID", "JobID", "MemoryMB", "TaskGroups"...). Our structs are
snake_case Python dataclasses; this module maps between the two so the
HTTP surface looks like the reference's /v1 API (command/agent/http.go
``wrap`` encodes responses with the stdlib JSON encoder over those
structs). Decoding is type-hint driven: given a target dataclass we
rebuild nested structs, lists, dicts, optionals — never arbitrary types.
"""
from __future__ import annotations

import base64
import dataclasses
import json
import typing
from typing import Any, Dict, Optional, Type

# Word fragments rendered as acronyms in Go field names.
_ACRONYMS = {
    "id": "ID",
    "cpu": "CPU",
    "mb": "MB",
    "mbits": "MBits",
    "ttl": "TTL",
    "acl": "ACL",
    "url": "URL",
    "ip": "IP",
    "iops": "IOPS",
    "gc": "GC",
    "dns": "DNS",
    "ns": "Ns",
    "hcl": "HCL",
    # Go API: FailedTGAllocs, DesiredTGUpdates (api/evaluations.go,
    # api/jobs.go plan annotations)
    "tg": "TG",
}

# Whole-field overrides where fragment-by-fragment casing is not enough.
_FIELD_OVERRIDES = {
    "ids": "IDs",
    "eval_ids": "EvalIDs",
    "alloc_ids": "AllocIDs",
    "node_ids": "NodeIDs",
    # Go API name differs from the struct field (api/jobs.go
    # ParameterizedJob *ParameterizedJobConfig)
    "parameterized": "ParameterizedJob",
}


def camel(name: str) -> str:
    """snake_case field name -> reference-style Go JSON key."""
    if name in _FIELD_OVERRIDES:
        return _FIELD_OVERRIDES[name]
    parts = name.split("_")
    out = []
    for p in parts:
        if not p:
            continue
        out.append(_ACRONYMS.get(p, p[0].upper() + p[1:]))
    return "".join(out)


def to_json_obj(obj: Any) -> Any:
    """Dataclass tree -> plain JSON-serializable tree with Go-style keys."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            out[camel(f.name)] = to_json_obj(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {str(k): to_json_obj(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [to_json_obj(v) for v in obj]
    if isinstance(obj, bytes):
        return base64.b64encode(obj).decode("ascii")
    if isinstance(obj, float) and obj != obj:  # NaN -> null
        return None
    return obj


def dumps(obj: Any, pretty: bool = False) -> str:
    data = to_json_obj(obj)
    if pretty:
        return json.dumps(data, indent=4, sort_keys=False)
    return json.dumps(data, separators=(",", ":"))


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------

_hints_cache: Dict[Type, Dict[str, Any]] = {}


def _type_hints(cls: Type) -> Dict[str, Any]:
    hints = _hints_cache.get(cls)
    if hints is None:
        hints = typing.get_type_hints(cls)
        _hints_cache[cls] = hints
    return hints


_keymap_cache: Dict[Type, Dict[str, str]] = {}


def _key_map(cls: Type) -> Dict[str, str]:
    """Accepted JSON key (camel or snake, lowercased) -> field name."""
    m = _keymap_cache.get(cls)
    if m is None:
        m = {}
        for f in dataclasses.fields(cls):
            m[f.name.lower()] = f.name
            m[camel(f.name).lower()] = f.name
        _keymap_cache[cls] = m
    return m


def from_json_obj(cls: Type, data: Any) -> Any:
    """Build an instance of ``cls`` (honoring type hints) from JSON data."""
    return _convert(cls, data)


def _convert(hint: Any, data: Any) -> Any:
    if data is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:  # Optional[X] and unions
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _convert(args[0], data)
        return data
    if origin in (list, typing.List):
        (item,) = typing.get_args(hint) or (Any,)
        return [_convert(item, v) for v in data]
    if origin in (set, typing.Set):
        (item,) = typing.get_args(hint) or (Any,)
        return set(_convert(item, v) for v in data)
    if origin in (dict, typing.Dict):
        args = typing.get_args(hint)
        vt = args[1] if len(args) == 2 else Any
        return {k: _convert(vt, v) for k, v in data.items()}
    if origin in (tuple, typing.Tuple):
        args = typing.get_args(hint)
        if args and args[-1] is not Ellipsis:
            return tuple(_convert(a, v) for a, v in zip(args, data))
        return tuple(data)
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        if not isinstance(data, dict):
            raise ValueError(f"expected object for {hint.__name__}, got {type(data).__name__}")
        keymap = _key_map(hint)
        hints = _type_hints(hint)
        kwargs = {}
        for k, v in data.items():
            fname = keymap.get(str(k).lower())
            if fname is None:
                continue  # tolerate unknown keys like the reference's API does
            kwargs[fname] = _convert(hints.get(fname, Any), v)
        return hint(**kwargs)
    if hint is bytes:
        if isinstance(data, str):
            return base64.b64decode(data)
        return bytes(data)
    if hint is float and isinstance(data, int):
        return float(data)
    if hint is int and isinstance(data, float) and data.is_integer():
        return int(data)
    return data


def loads(cls: Optional[Type], body: str) -> Any:
    data = json.loads(body) if body else None
    if cls is None:
        return data
    return from_json_obj(cls, data)
