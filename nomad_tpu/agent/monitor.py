"""Agent monitor + debug introspection.

Fills the role of reference ``command/agent/monitor/`` (live log
streaming over /v1/agent/monitor) and the pprof endpoints gated on
``enable_debug`` (command/agent/http.go:220). The monitor attaches a
ring-buffer handler to the framework's logger tree; requests drain the
buffer from an offset, so a polling client gets a live tail (the
reference streams frames — same data, poll transport). Debug dumps are
the Python equivalents of goroutine/heap profiles: per-thread stacks and
object census.
"""
from __future__ import annotations

import logging
import sys
import threading
import traceback
from collections import deque
from typing import List, Tuple

LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
}


class RingBufferHandler(logging.Handler):
    """Bounded in-memory log capture with monotonically increasing
    sequence numbers so pollers can resume where they left off."""

    def __init__(self, capacity: int = 2048) -> None:
        super().__init__(level=logging.DEBUG)
        self.capacity = capacity
        self._lock2 = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._seq = 0
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
        ))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:  # noqa: BLE001
            return
        with self._lock2:
            self._seq += 1
            self._buf.append((self._seq, record.levelno, line))

    def since(self, seq: int, min_level: int = logging.DEBUG) -> Tuple[List[str], int]:
        """Lines after ``seq`` at/above ``min_level``; returns (lines,
        newest_seq)."""
        with self._lock2:
            lines = [l for s, lvl, l in self._buf if s > seq and lvl >= min_level]
            newest = self._seq
        return lines, newest


class AgentMonitor:
    def __init__(self, logger_name: str = "nomad_tpu", capacity: int = 2048) -> None:
        self.handler = RingBufferHandler(capacity)
        self.logger = logging.getLogger(logger_name)
        self._attached = False

    def attach(self) -> "AgentMonitor":
        """Attach the capture handler WITHOUT changing the logger's level:
        forcing DEBUG here would flood the operator's own console handler.
        The buffer captures whatever verbosity the process runs at."""
        if not self._attached:
            self.logger.addHandler(self.handler)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            self.logger.removeHandler(self.handler)
            self._attached = False

    def tail(self, seq: int = 0, level: str = "info") -> dict:
        lines, newest = self.handler.since(
            seq, LEVELS.get(level.lower(), logging.INFO)
        )
        return {"Lines": lines, "Seq": newest}


def thread_dump() -> str:
    """Per-thread stack dump (the goroutine-profile analog)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out)


def heap_dump(top: int = 30) -> dict:
    """Object census (the heap-profile analog)."""
    import gc
    from collections import Counter

    counts = Counter(type(o).__name__ for o in gc.get_objects())
    return {
        "TotalObjects": sum(counts.values()),
        "TopTypes": dict(counts.most_common(top)),
        "GCStats": gc.get_stats(),
    }
