"""/v1 HTTP endpoint handlers.

One section per noun, mirroring the reference's handler registry
(command/agent/http.go:151–224 → command/agent/*_endpoint.go). Handlers
take the parsed :class:`~nomad_tpu.agent.http.Request` and return plain
structs; the transport JSON-encodes them with reference-style keys.
Blocking queries ride the state store's ``blocking_query`` and stamp
``X-Nomad-Index`` via ``req.response_index``.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ..structs.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    SchedulerConfiguration,
)
from . import jsonapi
from .http import HTTPError, HTTPServer, Request

MAX_BLOCKING_WAIT = 300.0  # cap like the reference's 5m default wait ceiling


def _blocking(req: Request, state, run: Callable[[Any], Any]):
    """Run a (possibly blocking) query and stamp the response index."""
    opts = req.options
    if opts.min_index > 0:
        result, index = state.blocking_query(
            run, opts.min_index, timeout=min(opts.wait or 5.0, MAX_BLOCKING_WAIT)
        )
    else:
        snap = state.snapshot()
        result, index = run(snap), snap.latest_index
    req.response_index = index
    return result


def _prefix_filter(items: List[Any], prefix: str, key=lambda o: o.id):
    if not prefix:
        return items
    return [o for o in items if key(o).startswith(prefix)]


def _require(obj, what: str):
    if obj is None:
        raise HTTPError(404, f"{what} not found")
    return obj


def _tail(req: Request, prefix: str) -> str:
    if not req.path.startswith(prefix):
        raise HTTPError(404, f"no handler for {req.path}")
    return req.path[len(prefix):]


class Routes:
    """Binds an Agent's server/client to an HTTPServer mux."""

    def __init__(self, agent) -> None:
        self.agent = agent

    # -- helpers ---------------------------------------------------------

    @property
    def server(self):
        if self.agent.server is None:
            raise HTTPError(501, "server is not enabled on this agent")
        return self.agent.server

    @property
    def state(self):
        return self.server.fsm.state

    @property
    def client(self):
        if self.agent.client is None:
            raise HTTPError(501, "client is not enabled on this agent")
        return self.agent.client

    def _authorize(self, req: Request, *capabilities: str, ns: str = "") -> None:
        """ACL enforcement choke point; no-op until ACLs are enabled."""
        self.agent.authorize(req, capabilities, ns or req.options.namespace)

    def register_all(self, mux: HTTPServer) -> None:
        r = mux.register
        r("/v1/jobs", self.jobs_index)
        r("/v1/jobs/parse", self.jobs_parse)
        r("/v1/job/", self.job_specific)
        r("/v1/nodes", self.nodes_index)
        r("/v1/node/", self.node_specific)
        r("/v1/allocations", self.allocs_index)
        r("/v1/allocation/", self.alloc_specific)
        r("/v1/evaluations", self.evals_index)
        r("/v1/evaluation/", self.eval_specific)
        r("/v1/deployments", self.deployments_index)
        r("/v1/deployment/", self.deployment_specific)
        r("/v1/status/leader", self.status_leader)
        r("/v1/status/peers", self.status_peers)
        r("/v1/operator/scheduler/configuration", self.operator_scheduler_config)
        r("/v1/operator/raft/configuration", self.operator_raft_config)
        r("/v1/operator/raft/peer", self.operator_raft_peer)
        r("/v1/operator/autopilot/configuration", self.operator_autopilot_config)
        r("/v1/operator/autopilot/health", self.operator_autopilot_health)
        r("/v1/agent/monitor", self.agent_monitor)
        r("/v1/agent/pprof", self.agent_pprof)
        r("/v1/system/gc", self.system_gc)
        r("/v1/system/reconcile/summaries", self.system_reconcile)
        r("/v1/agent/self", self.agent_self)
        r("/v1/agent/join", self.agent_join)
        r("/v1/agent/force-leave", self.agent_force_leave)
        r("/v1/agent/keyring/", self.agent_keyring)
        r("/v1/client/gc", self.client_gc)
        r("/v1/agent/health", self.agent_health)
        r("/v1/agent/servers", self.agent_servers)
        r("/v1/agent/members", self.agent_members)
        r("/v1/regions", self.regions)
        r("/v1/validate/job", self.validate_job)
        r("/v1/search", self.search)
        r("/v1/metrics", self.metrics)
        r("/v1/trace", self.trace)
        r("/v1/trace/distributed", self.trace_distributed)
        r("/v1/flight", self.flight)

    # -- jobs ------------------------------------------------------------

    def jobs_index(self, req: Request):
        if req.method == "GET":
            self._authorize(req, "read-job")
            ns = req.options.namespace

            def run(s):
                jobs = [j for j in s.jobs() if j.namespace == ns]
                return [_job_stub(j, s) for j in _prefix_filter(jobs, req.options.prefix)]

            return _blocking(req, self.state, run)
        if req.method in ("PUT", "POST"):
            self._authorize(req, "submit-job")
            payload = req.json()
            if not isinstance(payload, dict) or payload.get("Job") is None:
                raise HTTPError(400, "Job must be specified")
            job = jsonapi.from_json_obj(Job, payload["Job"])
            _canonicalize_job(job)
            eval_id = self.server.register_job(job)
            job = self.state.job_by_id(job.namespace, job.id)
            req.response_index = self.state.latest_index
            return {
                "EvalID": eval_id,
                "EvalCreateIndex": self.state.latest_index,
                "JobModifyIndex": job.job_modify_index if job else 0,
                "Index": self.state.latest_index,
            }
        raise HTTPError(405, "method not allowed")

    def jobs_parse(self, req: Request):
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        body = req.json()
        hcl = (body or {}).get("JobHCL", "")
        if not hcl:
            raise HTTPError(400, "JobHCL is empty")
        from ..jobspec import parse_job

        try:
            job = parse_job(hcl)
        except ValueError as e:
            raise HTTPError(400, f"error parsing jobspec: {e}")
        if (body or {}).get("Canonicalize"):
            _canonicalize_job(job)
        return job

    def job_specific(self, req: Request):
        rest = _tail(req, "/v1/job/")
        for suffix, fn in (
            ("/evaluate", self._job_evaluate),
            ("/allocations", self._job_allocations),
            ("/evaluations", self._job_evaluations),
            ("/versions", self._job_versions),
            ("/deployments", self._job_deployments),
            ("/deployment", self._job_latest_deployment),
            ("/summary", self._job_summary),
            ("/periodic/force", self._job_periodic_force),
            ("/dispatch", self._job_dispatch),
            ("/stable", self._job_stable),
            ("/revert", self._job_revert),
            ("/plan", self._job_plan),
        ):
            if rest.endswith(suffix):
                return fn(req, rest[: -len(suffix)])
        return self._job_crud(req, rest)

    def _job_crud(self, req: Request, job_id: str):
        ns = req.options.namespace
        if req.method == "GET":
            self._authorize(req, "read-job")
            return _blocking(
                req, self.state,
                lambda s: _require(s.job_by_id(ns, job_id), f"job {job_id!r}"),
            )
        if req.method in ("PUT", "POST"):  # update (same as register)
            self._authorize(req, "submit-job")
            payload = req.json()
            job = jsonapi.from_json_obj(Job, (payload or {}).get("Job") or {})
            _canonicalize_job(job)
            if job.id != job_id:
                raise HTTPError(400, f"job ID does not match request path ({job.id!r})")
            eval_id = self.server.register_job(job)
            req.response_index = self.state.latest_index
            return {"EvalID": eval_id, "Index": self.state.latest_index}
        if req.method == "DELETE":
            self._authorize(req, "submit-job")
            purge = req.param("purge") in ("true", "1")
            eval_id = self.server.deregister_job(ns, job_id, purge=purge)
            req.response_index = self.state.latest_index
            return {"EvalID": eval_id, "Index": self.state.latest_index}
        raise HTTPError(405, "method not allowed")

    def _job_evaluate(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        try:
            eval_id = self.server.evaluate_job(req.options.namespace, job_id)
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {"EvalID": eval_id, "Index": self.state.latest_index}

    def _job_allocations(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace
        all_allocs = req.param("all") in ("true", "1")
        return _blocking(
            req, self.state,
            lambda s: [_alloc_stub(a) for a in s.allocs_by_job(ns, job_id, all_allocs)],
        )

    def _job_evaluations(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace
        return _blocking(req, self.state, lambda s: s.evals_by_job(ns, job_id))

    def _job_versions(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace

        def run(s):
            versions = s.job_versions.get((ns, job_id), [])
            if not versions:
                raise HTTPError(404, f"job {job_id!r} not found")
            return {"Versions": versions, "Diffs": None}

        return _blocking(req, self.state, run)

    def _job_deployments(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace
        return _blocking(
            req, self.state,
            lambda s: [d for d in s.deployments()
                       if d.namespace == ns and d.job_id == job_id],
        )

    def _job_latest_deployment(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace
        return _blocking(
            req, self.state, lambda s: s.latest_deployment_by_job_id(ns, job_id)
        )

    def _job_summary(self, req: Request, job_id: str):
        self._authorize(req, "read-job")
        ns = req.options.namespace

        def run(s):
            _require(s.job_by_id(ns, job_id), f"job {job_id!r}")
            return {
                "JobID": job_id,
                "Namespace": ns,
                "Summary": s.job_summary(ns, job_id),
            }

        return _blocking(req, self.state, run)

    def _job_periodic_force(self, req: Request, job_id: str):
        self._authorize(req, "submit-job")
        try:
            child_id = self.server.periodic_dispatcher.force_launch(
                req.options.namespace, job_id
            )
        except KeyError as e:
            raise HTTPError(404, str(e))
        req.response_index = self.state.latest_index
        return {"EvalCreateIndex": self.state.latest_index, "Index": self.state.latest_index,
                "ChildJobID": child_id or ""}

    def _job_dispatch(self, req: Request, job_id: str):
        self._authorize(req, "dispatch-job")
        body = req.json() or {}
        import base64

        try:
            payload = base64.b64decode(body.get("Payload") or "")
        except Exception as e:
            raise HTTPError(400, f"invalid payload encoding: {e}")
        meta = body.get("Meta") or {}
        try:
            child_id, eval_id = self.server.dispatch_job(
                req.options.namespace, job_id, payload, meta
            )
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {
            "DispatchedJobID": child_id,
            "EvalID": eval_id,
            "EvalCreateIndex": self.state.latest_index,
            "JobCreateIndex": self.state.latest_index,
            "Index": self.state.latest_index,
        }

    def _job_stable(self, req: Request, job_id: str):
        self._authorize(req, "submit-job")
        body = req.json() or {}
        try:
            self.server.set_job_stability(
                req.options.namespace, job_id,
                int(body.get("JobVersion") or 0), bool(body.get("Stable")),
            )
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {"Index": self.state.latest_index}

    def _job_revert(self, req: Request, job_id: str):
        self._authorize(req, "submit-job")
        body = req.json() or {}
        try:
            eval_id = self.server.revert_job(
                req.options.namespace, job_id,
                int(body.get("JobVersion") or 0),
                body.get("EnforcePriorVersion"),
            )
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {"EvalID": eval_id, "Index": self.state.latest_index}

    def _job_plan(self, req: Request, job_id: str):
        self._authorize(req, "submit-job")
        payload = req.json()
        if not isinstance(payload, dict) or payload.get("Job") is None:
            raise HTTPError(400, "Job must be specified")
        job = jsonapi.from_json_obj(Job, payload["Job"])
        _canonicalize_job(job)
        if job.id != job_id:
            raise HTTPError(400, "job ID does not match request path")
        try:
            annotations, failed_tg_allocs, next_index, jdiff = self.server.plan_job(
                job, diff=bool(payload.get("Diff"))
            )
        except ValueError as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {
            "Annotations": annotations,
            "FailedTGAllocs": failed_tg_allocs,
            "JobModifyIndex": next_index,
            "Diff": jdiff,
            "Index": self.state.latest_index,
        }

    # -- nodes -----------------------------------------------------------

    def nodes_index(self, req: Request):
        self._authorize(req, "node:read")
        return _blocking(
            req, self.state,
            lambda s: [_node_stub(n) for n in _prefix_filter(s.nodes(), req.options.prefix)],
        )

    def node_specific(self, req: Request):
        rest = _tail(req, "/v1/node/")
        for suffix, fn in (
            ("/evaluate", self._node_evaluate),
            ("/allocations", self._node_allocations),
            ("/drain", self._node_drain),
            ("/eligibility", self._node_eligibility),
            ("/purge", self._node_purge),
        ):
            if rest.endswith(suffix):
                return fn(req, rest[: -len(suffix)])
        self._authorize(req, "node:read")
        return _blocking(
            req, self.state,
            lambda s: _require(s.node_by_id(rest), f"node {rest!r}").without_secret(),
        )

    def _node_evaluate(self, req: Request, node_id: str):
        self._authorize(req, "node:write")
        _require(self.state.node_by_id(node_id), f"node {node_id!r}")
        eval_ids = self.server.create_node_evals(node_id)
        req.response_index = self.state.latest_index
        return {"EvalIDs": eval_ids, "EvalCreateIndex": self.state.latest_index,
                "NodeModifyIndex": self.state.latest_index, "Index": self.state.latest_index}

    def _node_allocations(self, req: Request, node_id: str):
        self._authorize(req, "node:read")
        return _blocking(req, self.state, lambda s: s.allocs_by_node(node_id))

    def _node_drain(self, req: Request, node_id: str):
        self._authorize(req, "node:write")
        body = req.json() or {}
        spec = body.get("DrainSpec")
        drain = None
        if spec is not None:
            from ..structs.structs import DrainStrategy

            drain = DrainStrategy(
                deadline_ns=int(spec.get("Deadline") or 0),
                ignore_system_jobs=bool(spec.get("IgnoreSystemJobs")),
            )
        self.server.update_node_drain(node_id, drain)
        req.response_index = self.state.latest_index
        return {"NodeModifyIndex": self.state.latest_index, "Index": self.state.latest_index}

    def _node_eligibility(self, req: Request, node_id: str):
        self._authorize(req, "node:write")
        body = req.json() or {}
        eligibility = body.get("Eligibility") or ""
        if eligibility not in ("eligible", "ineligible"):
            raise HTTPError(400, f"invalid scheduling eligibility {eligibility!r}")
        self.server.update_node_eligibility(node_id, eligibility)
        req.response_index = self.state.latest_index
        return {"NodeModifyIndex": self.state.latest_index, "Index": self.state.latest_index}

    def _node_purge(self, req: Request, node_id: str):
        self._authorize(req, "node:write")
        self.server.deregister_node(node_id)
        req.response_index = self.state.latest_index
        return {"EvalIDs": [], "NodeModifyIndex": self.state.latest_index,
                "Index": self.state.latest_index}

    # -- allocations -----------------------------------------------------

    def allocs_index(self, req: Request):
        self._authorize(req, "read-job")
        ns = req.options.namespace

        def run(s):
            allocs = [a for a in s.allocs() if a.namespace == ns]
            return [_alloc_stub(a) for a in _prefix_filter(allocs, req.options.prefix)]

        return _blocking(req, self.state, run)

    def alloc_specific(self, req: Request):
        rest = _tail(req, "/v1/allocation/")
        if rest.endswith("/stop"):
            self._authorize(req, "alloc-lifecycle")
            alloc_id = rest[: -len("/stop")]
            eval_id = self.server.stop_alloc(alloc_id)
            req.response_index = self.state.latest_index
            return {"EvalID": eval_id, "Index": self.state.latest_index}
        self._authorize(req, "read-job")

        def run(s):
            alloc = _require(s.alloc_by_id(rest), f"alloc {rest!r}")
            if alloc.job is None:
                alloc = alloc.copy_skip_job()
                alloc.job = s.job_by_id(alloc.namespace, alloc.job_id)
            return alloc

        return _blocking(req, self.state, run)

    # -- evaluations -----------------------------------------------------

    def evals_index(self, req: Request):
        self._authorize(req, "read-job")
        return _blocking(
            req, self.state,
            lambda s: _prefix_filter(s.evals(), req.options.prefix),
        )

    def eval_specific(self, req: Request):
        rest = _tail(req, "/v1/evaluation/")
        if rest.endswith("/allocations"):
            eval_id = rest[: -len("/allocations")]
            self._authorize(req, "read-job")
            return _blocking(
                req, self.state,
                lambda s: [_alloc_stub(a) for a in s.allocs_by_eval(eval_id)],
            )
        self._authorize(req, "read-job")
        return _blocking(
            req, self.state,
            lambda s: _require(s.eval_by_id(rest), f"eval {rest!r}"),
        )

    # -- deployments -----------------------------------------------------

    def deployments_index(self, req: Request):
        self._authorize(req, "read-job")
        return _blocking(
            req, self.state,
            lambda s: _prefix_filter(s.deployments(), req.options.prefix),
        )

    def deployment_specific(self, req: Request):
        rest = _tail(req, "/v1/deployment/")
        dw = self.server.deployment_watcher
        try:
            if rest.startswith("promote/"):
                self._authorize(req, "submit-job")
                body = req.json() or {}
                groups = None if body.get("All") else body.get("Groups")
                dw.promote(rest[len("promote/"):], groups)
            elif rest.startswith("fail/"):
                self._authorize(req, "submit-job")
                dw.fail(rest[len("fail/"):])
            elif rest.startswith("pause/"):
                self._authorize(req, "submit-job")
                body = req.json() or {}
                dw.pause(rest[len("pause/"):], bool(body.get("Pause")))
            elif rest.startswith("allocation-health/"):
                self._authorize(req, "submit-job")
                body = req.json() or {}
                dw.set_alloc_health(
                    rest[len("allocation-health/"):],
                    body.get("HealthyAllocationIDs") or [],
                    body.get("UnhealthyAllocationIDs") or [],
                )
            elif rest.startswith("allocations/"):
                self._authorize(req, "read-job")
                d_id = rest[len("allocations/"):]
                return _blocking(
                    req, self.state,
                    lambda s: [_alloc_stub(a) for a in s.allocs()
                               if a.deployment_id == d_id],
                )
            else:
                self._authorize(req, "read-job")
                return _blocking(
                    req, self.state,
                    lambda s: _require(s.deployment_by_id(rest), f"deployment {rest!r}"),
                )
        except (ValueError,) as e:
            raise HTTPError(400, str(e))
        req.response_index = self.state.latest_index
        return {"EvalID": "", "Index": self.state.latest_index}

    # -- status / operator / system -------------------------------------

    def status_leader(self, req: Request):
        server = self.server
        if not server.is_leader:
            return "unknown"
        host, port = self.agent.http.addr
        return f"{host}:{port}"

    def status_peers(self, req: Request):
        return [p for p in self.agent.peer_names()]

    def operator_scheduler_config(self, req: Request):
        if req.method == "GET":
            self._authorize(req, "operator:read")
            index, config = self.state.scheduler_config()
            req.response_index = index
            return {"SchedulerConfig": config, "Index": index}
        if req.method in ("PUT", "POST"):
            self._authorize(req, "operator:write")
            body = req.json() or {}
            config = jsonapi.from_json_obj(SchedulerConfiguration, body)
            self.server.raft_apply("scheduler-config", config)
            return {"Updated": True, "Index": self.state.latest_index}
        raise HTTPError(405, "method not allowed")

    def operator_raft_config(self, req: Request):
        self._authorize(req, "operator:read")
        return {
            "Servers": [
                {"ID": name, "Node": name, "Address": addr, "Leader": leader,
                 "Voter": True}
                for name, addr, leader in self.agent.raft_servers()
            ],
            "Index": self.state.latest_index,
        }

    def operator_raft_peer(self, req: Request):
        """DELETE /v1/operator/raft/peer?id=<peer-id> — replicated removal
        of a raft peer (reference operator_endpoint.go RaftRemovePeerByID,
        command/agent/operator_endpoint.go:37)."""
        if req.method != "DELETE":
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "operator:write")
        peer_id = req.param("id")
        if not peer_id:
            raise HTTPError(400, "missing ?id=<peer-id>")
        self.agent.remove_raft_peer(peer_id)
        return {"Removed": peer_id, "Index": self.state.latest_index}

    def operator_autopilot_config(self, req: Request):
        from ..server.autopilot import AutopilotConfig

        if req.method == "GET":
            self._authorize(req, "operator:read")
            index, config = self.state.autopilot_config()
            req.response_index = index
            return config or AutopilotConfig()
        if req.method in ("PUT", "POST"):
            self._authorize(req, "operator:write")
            body = req.json() or {}
            config = jsonapi.from_json_obj(AutopilotConfig, body)
            self.server.raft_apply("autopilot-config", config)
            return {"Updated": True, "Index": self.state.latest_index}
        raise HTTPError(405, "method not allowed")

    def operator_autopilot_health(self, req: Request):
        self._authorize(req, "operator:read")
        if self.agent.autopilot is None:
            raise HTTPError(404, "autopilot requires a server-mode agent")
        servers = self.agent.autopilot.server_health()
        healthy = all(s.healthy for s in servers) if servers else False
        voters = sum(1 for s in servers if s.voter and s.healthy)
        return {
            "Healthy": healthy,
            "FailureTolerance": max(0, voters - (len(servers) // 2 + 1)),
            "Servers": [jsonapi.to_json_obj(s) for s in servers],
        }

    def agent_monitor(self, req: Request):
        """Agent log tail (reference /v1/agent/monitor). Default is one
        poll; ``follow=true`` keeps the response open and SERVER-PUSHES
        new log lines as they are emitted (chunked, one line per chunk
        batch — the reference's streaming monitor frames)."""
        self._authorize(req, "agent:read")
        try:
            seq = int(req.param("seq", "0"))
        except ValueError:
            raise HTTPError(400, "seq must be an integer")
        level = req.param("log_level", "info")
        if req.param("follow", "") not in ("true", "1"):
            return self.agent.monitor.tail(seq=seq, level=level)

        monitor = self.agent.monitor

        def stream():
            import time as time_mod

            cursor = seq
            # idle cap bounds abandoned followers (disconnects are only
            # observable on write)
            idle_deadline = time_mod.monotonic() + 600.0
            while True:
                out = monitor.tail(seq=cursor, level=level)
                lines, cursor = out["Lines"], out["Seq"]
                if lines:
                    idle_deadline = time_mod.monotonic() + 600.0
                    yield ("\n".join(lines) + "\n").encode()
                    continue
                if time_mod.monotonic() > idle_deadline:
                    return
                time_mod.sleep(0.25)

        from .http import StreamingResponse

        return StreamingResponse(stream(), content_type="text/plain")

    def agent_pprof(self, req: Request):
        """Debug dumps gated on enable_debug (http.go:220 pprof)."""
        if not self.agent.config.enable_debug:
            raise HTTPError(404, "debug endpoints disabled (enable_debug)")
        self._authorize(req, "agent:read")
        kind = req.param("type", "threads")
        from . import monitor as monitor_mod

        if kind in ("threads", "goroutine"):
            return monitor_mod.thread_dump().encode()
        if kind == "heap":
            return monitor_mod.heap_dump()
        raise HTTPError(400, f"unknown profile type {kind!r}")

    def system_gc(self, req: Request):
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "operator:write")
        self.server.force_gc()
        return {}

    def system_reconcile(self, req: Request):
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "operator:write")
        return {}

    # -- agent -----------------------------------------------------------

    def agent_self(self, req: Request):
        self._authorize(req, "agent:read")
        return self.agent.self_info()

    def agent_health(self, req: Request):
        out = {}
        if self.agent.server is not None:
            out["server"] = {"ok": True, "message": "ok"}
        if self.agent.client is not None:
            out["client"] = {"ok": True, "message": "ok"}
        return out

    def agent_servers(self, req: Request):
        self._authorize(req, "agent:read")
        return self.agent.known_servers()

    def agent_members(self, req: Request):
        self._authorize(req, "agent:read")
        return {"ServerName": self.agent.config.name,
                "ServerRegion": self.agent.config.region,
                "ServerDC": self.agent.config.datacenter,
                "Members": self.agent.members()}

    def agent_join(self, req: Request):
        """PUT /v1/agent/join?address=host:port[&address=...] — runtime
        gossip join (reference command/agent/http.go:181 + agent
        endpoint Join)."""
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "agent:write")
        addrs = req.query.get("address") or []
        if not addrs:
            raise HTTPError(400, "missing ?address=host:port")
        try:
            n = self.agent.join(addrs)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return {"num_joined": n, "error": "" if n else "no peers responded"}

    def agent_force_leave(self, req: Request):
        """PUT /v1/agent/force-leave?node=<name> — evict a (failed)
        member from gossip (reference http.go:183, serf RemoveFailedNode)."""
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "agent:write")
        node = req.param("node")
        if not node:
            raise HTTPError(400, "missing ?node=<name>")
        try:
            ok = self.agent.force_leave(node)
        except ValueError as e:
            raise HTTPError(400, str(e))
        if not ok:
            raise HTTPError(404, f"unknown member {node!r}")
        return {}

    def agent_keyring(self, req: Request):
        """/v1/agent/keyring/<list|install|use|remove> — gossip keyring
        rotation (reference http.go:185 + serf keyring protocol)."""
        op = req.path[len("/v1/agent/keyring/"):].strip("/")
        if op == "list":
            self._authorize(req, "agent:write")
            try:
                keys = self.agent.keyring("list", "")
            except ValueError as e:
                raise HTTPError(400, str(e))
            num_nodes = len(self.agent.members()) or 1
            return {
                "Keys": {k: num_nodes for k in keys},
                # serf's keyring -list contract: the sealing key is named
                # explicitly, not implied by map order
                "PrimaryKeys": {keys[0]: num_nodes} if keys else {},
                "NumNodes": num_nodes,
            }
        if op not in ("install", "use", "remove"):
            raise HTTPError(404, f"unknown keyring op {op!r}")
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "agent:write")
        body = req.json() or {}
        key = body.get("Key", "")
        if not key:
            raise HTTPError(400, "missing Key")
        try:
            self.agent.keyring(op, key)
        except ValueError as e:
            raise HTTPError(400, str(e))
        return {}

    def client_gc(self, req: Request):
        """PUT /v1/client/gc — force terminal-alloc GC on this node
        (reference http.go:176 -> client/gc.go CollectAll). Destructive:
        GET is rejected like the sibling cluster-ops endpoints."""
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        self._authorize(req, "node:write")
        if self.agent.client is None:
            raise HTTPError(400, "agent is not running a client")
        collected = self.agent.client.garbage_collect(force=True)
        return {"Collected": collected}

    def regions(self, req: Request):
        return self.agent.regions()

    def metrics(self, req: Request):
        """Telemetry snapshot (reference http.go:189 /v1/metrics; supports
        ?format=prometheus like the reference)."""
        from ..utils.metrics import global_sink

        if req.param("format") == "prometheus":
            return global_sink().prometheus().encode()
        return global_sink().summary()

    def trace(self, req: Request):
        """Eval-lifecycle trace snapshot (nomad-trace): tail-latency
        summary, in-flight eval records (enqueue -> dequeue -> invoke ->
        submit -> apply stamps, host/device path, OCC attempt), recent
        completions, and — when this agent runs a server — per-worker
        current spans and the device batcher's dispatch profile.
        ?recent=N bounds the completed-record tail (default 64)."""
        from ..trace import lifecycle

        try:
            recent = int(req.param("recent") or 64)
        except ValueError:
            raise HTTPError(400, "recent must be an integer")
        out = lifecycle.snapshot(recent=max(0, recent))
        srv = self.agent.server
        if srv is not None:
            out["workers"] = srv.watchdog.worker_spans()
            if srv.device_batcher is not None:
                out["dispatch_profile"] = srv.device_batcher.dispatch_profile()
        return out

    def trace_distributed(self, req: Request):
        """Stitched cross-process trace view (nomad-xtrace): this
        process's span ring merged into per-trace span trees, with the
        stitched bottleneck ledger and the per-method RPC table. A
        single-agent view covers one process; chaos harnesses stitch all
        replicas via Trace.Export. ?recent=N bounds the trace tail
        (default 16)."""
        from ..rpc import transport
        from ..trace import attribution, context, stitch

        try:
            recent = int(req.param("recent") or 16)
        except ValueError:
            raise HTTPError(400, "recent must be an integer")
        exported = context.export()
        out = stitch.stitch([exported["spans"]], recent=max(0, recent))
        out["stitched_report"] = attribution.stitched_report(out.pop("spans"))
        out["rpc"] = transport.rpc_stats()
        out["dropped"] = exported["dropped"]
        return out

    def flight(self, req: Request):
        """Flight-recorder snapshot (nomad-flightrec): the last N frames
        of the leader's continuous sampler plus the live critical-path
        bottleneck report. ?recent=N bounds the frame tail (default 64);
        a non-server (client-only) agent serves the attribution report
        with no frames."""
        from ..trace import attribution

        try:
            recent = int(req.param("recent") or 64)
        except ValueError:
            raise HTTPError(400, "recent must be an integer")
        srv = self.agent.server
        if srv is not None:
            out = srv.flight.snapshot(recent=max(0, recent))
        else:
            out = {"armed": False, "frames": []}
        out["bottleneck_report"] = attribution.bottleneck_report()
        return out

    def search(self, req: Request):
        """Prefix search across objects (reference nomad/search_endpoint.go;
        truncates at 20 matches per context like truncateLimitQuery)."""
        if req.method not in ("PUT", "POST"):
            raise HTTPError(405, "method not allowed")
        body = req.json() or {}
        prefix = body.get("Prefix", "")
        context = body.get("Context", "all") or "all"
        ns = req.options.namespace
        limit = 20
        state = self.state
        sources = {
            "jobs": lambda: sorted(
                j.id for j in state.jobs() if j.namespace == ns and j.id.startswith(prefix)
            ),
            "evals": lambda: sorted(
                e.id for e in state.evals() if e.id.startswith(prefix)
            ),
            "allocs": lambda: sorted(
                a.id for a in state.allocs() if a.id.startswith(prefix)
            ),
            "nodes": lambda: sorted(
                n.id for n in state.nodes() if n.id.startswith(prefix)
            ),
            "deployment": lambda: sorted(
                d.id for d in state.deployments() if d.id.startswith(prefix)
            ),
        }
        if context != "all":
            if context not in sources:
                raise HTTPError(400, f"invalid search context {context!r}")
            wanted = [context]
        else:
            wanted = list(sources)
        cap_for = {
            "jobs": "read-job",
            "evals": "read-job",
            "allocs": "read-job",
            "deployment": "read-job",
            "nodes": "node:read",
        }
        matches: Dict[str, List[str]] = {}
        truncations: Dict[str, bool] = {}
        for ctx in wanted:
            self._authorize(req, cap_for[ctx])
            ids = sources[ctx]()
            truncations[ctx] = len(ids) > limit
            matches[ctx] = ids[:limit]
        req.response_index = self.state.latest_index
        return {"Matches": matches, "Truncations": truncations, "Index": self.state.latest_index}

    def validate_job(self, req: Request):
        self._authorize(req, "read-job")
        payload = req.json()
        if not isinstance(payload, dict) or payload.get("Job") is None:
            raise HTTPError(400, "Job must be specified")
        job = jsonapi.from_json_obj(Job, payload["Job"])
        _canonicalize_job(job)
        errors = _validate_job(job)
        return {
            "DriverConfigValidated": True,
            "ValidationErrors": errors,
            "Error": "; ".join(errors) if errors else "",
        }


# ---------------------------------------------------------------------------
# Stubs — trimmed list views, like the reference's structs.JobListStub etc.
# ---------------------------------------------------------------------------


def _job_stub(job: Job, state) -> dict:
    return {
        "ID": job.id,
        "ParentID": job.parent_id,
        "Name": job.name,
        "Namespace": job.namespace,
        "Datacenters": job.datacenters,
        "Type": job.type,
        "Priority": job.priority,
        "Periodic": job.is_periodic(),
        "ParameterizedJob": job.is_parameterized(),
        "Stop": job.stop,
        "Status": job.status,
        "StatusDescription": job.status_description,
        "JobSummary": {"JobID": job.id, "Namespace": job.namespace,
                       "Summary": state.job_summary(job.namespace, job.id)},
        "CreateIndex": job.create_index,
        "ModifyIndex": job.modify_index,
        "JobModifyIndex": job.job_modify_index,
        "SubmitTime": 0,
        "Version": job.version,
    }


def _alloc_stub(alloc: Allocation) -> dict:
    return {
        "ID": alloc.id,
        "EvalID": alloc.eval_id,
        "Name": alloc.name,
        "Namespace": alloc.namespace,
        "NodeID": alloc.node_id,
        "NodeName": alloc.node_name,
        "JobID": alloc.job_id,
        "JobType": alloc.job.type if alloc.job else "",
        "JobVersion": alloc.job.version if alloc.job else 0,
        "TaskGroup": alloc.task_group,
        "DesiredStatus": alloc.desired_status,
        "DesiredDescription": alloc.desired_description,
        "ClientStatus": alloc.client_status,
        "ClientDescription": alloc.client_description,
        "DeploymentStatus": jsonapi.to_json_obj(alloc.deployment_status),
        "FollowupEvalID": alloc.followup_eval_id,
        "TaskStates": jsonapi.to_json_obj(alloc.task_states),
        "CreateIndex": alloc.create_index,
        "ModifyIndex": alloc.modify_index,
        "CreateTime": alloc.create_time_ns,
        "ModifyTime": alloc.modify_time_ns,
    }


def _node_stub(node: Node) -> dict:
    return {
        "ID": node.id,
        "Datacenter": node.datacenter,
        "Name": node.name,
        "NodeClass": node.node_class,
        "Version": node.attributes.get("nomad.version", ""),
        "Drain": node.drain,
        "SchedulingEligibility": node.scheduling_eligibility,
        "Status": node.status,
        "StatusDescription": node.status_description,
        "CreateIndex": node.create_index,
        "ModifyIndex": node.modify_index,
    }


def _canonicalize_job(job: Job) -> None:
    """Fill defaults the way api.Job.Canonicalize does."""
    if not job.id:
        raise HTTPError(400, "Job ID is missing")
    if not job.name:
        job.name = job.id
    if not job.namespace:
        job.namespace = "default"
    if not job.datacenters:
        job.datacenters = ["dc1"]
    for tg in job.task_groups:
        if tg.count == 0:
            tg.count = 1


def _validate_job(job: Job) -> List[str]:
    errors = []
    if not job.id:
        errors.append("job ID is required")
    if not job.task_groups:
        errors.append("job must have at least one task group")
    seen = set()
    for tg in job.task_groups:
        if tg.name in seen:
            errors.append(f"duplicate task group {tg.name!r}")
        seen.add(tg.name)
        if tg.count < 0:
            errors.append(f"task group {tg.name!r} has negative count")
        if not tg.tasks:
            errors.append(f"task group {tg.name!r} has no tasks")
    return errors
