"""Built-in web UI served at /ui.

Fills the role of the reference's Ember SPA (``ui/``, served by the agent
at http.go:213) with a no-build-step single-file app over the same /v1
JSON API: jobs (list/detail/stop), allocations (task states, events, log
viewer via the fs API), nodes (attributes, drain/eligibility), evals,
deployments (promote/fail), and servers (members, raft config, autopilot
health). ACL token entry is stored in localStorage and sent as
X-Nomad-Token, like the reference UI's token page.
"""
from __future__ import annotations

UI_HTML = r"""<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>Nomad-TPU</title>
<style>
:root{--bg:#f7f8fa;--panel:#fff;--ink:#1f2430;--mut:#68707f;--line:#e3e6eb;
--brand:#16a394;--bad:#c4442e;--warn:#b98a00;--ok:#2f855a;--mono:ui-monospace,Menlo,monospace}
*{box-sizing:border-box}body{margin:0;font:14px/1.45 system-ui,sans-serif;
background:var(--bg);color:var(--ink)}
header{display:flex;align-items:center;gap:18px;background:var(--panel);
border-bottom:1px solid var(--line);padding:10px 20px;position:sticky;top:0}
header b{color:var(--brand);font-size:16px}
nav a{color:var(--mut);text-decoration:none;margin-right:14px;padding:4px 2px}
nav a.on{color:var(--ink);border-bottom:2px solid var(--brand)}
#token{margin-left:auto;font:12px var(--mono);width:200px;padding:4px 6px;
border:1px solid var(--line);border-radius:4px}
main{max-width:1100px;margin:18px auto;padding:0 16px}
table{width:100%;border-collapse:collapse;background:var(--panel);
border:1px solid var(--line);border-radius:6px;overflow:hidden}
th,td{text-align:left;padding:8px 12px;border-bottom:1px solid var(--line)}
th{font-size:12px;text-transform:uppercase;letter-spacing:.04em;color:var(--mut)}
tr:last-child td{border-bottom:0}
tbody tr:hover{background:#f0f4f8;cursor:pointer}
.tag{display:inline-block;padding:1px 8px;border-radius:10px;font-size:12px}
.t-running,.t-ready,.t-complete,.t-successful,.t-alive{background:#e3f5ec;color:var(--ok)}
.t-pending,.t-paused{background:#fdf3d7;color:var(--warn)}
.t-failed,.t-dead,.t-down,.t-lost{background:#fbe6e0;color:var(--bad)}
.t-blocked,.t-other{background:#e8eaf0;color:var(--mut)}
h2{margin:18px 0 10px}h3{margin:16px 0 8px}
.kv{display:grid;grid-template-columns:220px 1fr;gap:4px 14px;background:var(--panel);
border:1px solid var(--line);border-radius:6px;padding:12px}
.kv div:nth-child(odd){color:var(--mut)}
pre{background:#101418;color:#d6dde6;padding:12px;border-radius:6px;
overflow:auto;font:12px/1.5 var(--mono);max-height:420px;white-space:pre-wrap}
button{background:var(--brand);color:#fff;border:0;border-radius:4px;
padding:6px 12px;cursor:pointer;margin-right:8px}
button.risk{background:var(--bad)}
.crumb{color:var(--mut);margin-bottom:6px}.crumb a{color:var(--brand)}
.err{background:#fbe6e0;color:var(--bad);padding:10px;border-radius:6px;margin:10px 0}
.mut{color:var(--mut)}
.meters{display:grid;grid-template-columns:repeat(auto-fill,minmax(240px,1fr));
gap:10px;margin:8px 0}
.meter{background:var(--panel);border:1px solid var(--line);border-radius:6px;
padding:10px 12px}
.meter .lbl{font-size:12px;color:var(--mut);margin-bottom:4px}
.meter .val{font:600 14px/1.2 system-ui;color:var(--ink);margin-bottom:6px}
.meter .bar{height:6px;background:var(--line);border-radius:3px;overflow:hidden}
.meter .bar i{display:block;height:100%;background:var(--brand);border-radius:3px}
.meter .spark{display:block;margin-top:6px;color:var(--brand);width:100%}
.topo{display:grid;grid-template-columns:repeat(auto-fill,minmax(210px,1fr));gap:8px}
.topo-node{background:var(--panel);border:1px solid var(--line);border-radius:6px;
  padding:8px;cursor:pointer}
.topo-node .bar{height:5px;background:var(--line);border-radius:3px;
  overflow:hidden;margin-top:6px}
.topo-node .bar i{display:block;height:100%;background:var(--brand)}
.topo-node .chips{margin-top:6px;line-height:14px}
.chip{display:inline-block;width:10px;height:10px;border-radius:2px;
  margin:0 2px 2px 0;cursor:pointer}
button.act{padding:2px 8px;font-size:12px;margin-right:4px}
button.act.warn{background:var(--bad)}
.logbar{display:flex;gap:8px;align-items:center;margin:8px 0}
.logbar select,.logbar input[type=text]{font:12px var(--mono);padding:4px 6px;
border:1px solid var(--line);border-radius:4px}
.term{background:#101418;color:#d6dde6;padding:12px;border-radius:6px;
overflow:auto;font:12px/1.5 var(--mono);height:260px;white-space:pre-wrap}
.termin{width:100%;font:12px var(--mono);padding:6px 8px;margin-top:6px;
border:1px solid var(--line);border-radius:4px;background:#101418;color:#d6dde6}
</style>
</head>
<body>
<header>
  <b>nomad-tpu</b>
  <nav id="nav"></nav>
  <input id="token" placeholder="ACL token" title="X-Nomad-Token">
</header>
<main id="main">loading…</main>
<script>
"use strict";
const $ = s => document.querySelector(s);
const NAV = [["jobs","Jobs"],["run","Run Job"],["nodes","Nodes"],
             ["topo","Topology"],["allocs","Allocations"],
             ["evals","Evaluations"],["deploys","Deployments"],
             ["servers","Servers"]];
const tokenBox = $("#token");
tokenBox.value = localStorage.getItem("nomad_token") || "";
tokenBox.onchange = () => { localStorage.setItem("nomad_token", tokenBox.value); render(); };

async function api(path, opts) {
  const headers = {};
  const tok = localStorage.getItem("nomad_token");
  if (tok) headers["X-Nomad-Token"] = tok;
  // merge caller headers INTO the token headers — Object.assign at the
  // top level would replace the headers object and drop the token
  opts = opts || {};
  const merged = Object.assign({}, headers, opts.headers || {});
  const r = await fetch(path, Object.assign({}, opts, {headers: merged}));
  if (!r.ok) throw new Error(r.status + " " + await r.text());
  const ct = r.headers.get("Content-Type") || "";
  return ct.includes("json") ? r.json() : r.text();
}
const esc = s => String(s ?? "").replace(/[&<>"'`]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;","`":"&#96;"}[c]));
const tag = s => { const k = String(s||"other").toLowerCase();
  const known = ["running","ready","complete","successful","alive","pending",
                 "paused","failed","dead","down","lost","blocked"];
  return `<span class="tag t-${known.includes(k)?k:"other"}">${esc(s)}</span>`; };
const short = id => esc(String(id||"").slice(0,8));
const when = ns => ns ? new Date(ns/1e6).toLocaleString() : "";
function table(headers, rows, onclickPrefix) {
  const h = headers.map(x=>`<th>${x}</th>`).join("");
  const b = rows.map(r => {
    // navigation via a data attribute + delegated listener: IDs are
    // user-controlled and must never be spliced into inline JS
    const link = onclickPrefix && r.__id ?
      ` data-href="${esc(onclickPrefix + "/" + encodeURIComponent(r.__id))}"` : "";
    return `<tr${link}>` + r.cells.map(c=>`<td>${c}</td>`).join("") + "</tr>";
  }).join("");
  return `<table><thead><tr>${h}</tr></thead><tbody>${b || ""}</tbody></table>`
    + (rows.length ? "" : `<p class="mut">none</p>`);
}
document.addEventListener("click", e => {
  // rows, topology cards and alloc chips all navigate the same way;
  // closest() picks the innermost target (chip inside a node card)
  const el = e.target.closest("[data-href]");
  if (el) location.hash = el.dataset.href;
});

const pages = {
  // job submit/edit: HCL in, parse -> plan preview -> register
  // (the Ember app's job-run flow; /v1/jobs/parse + /v1/job/<id>/plan)
  async run(id) {
    let seed = "";
    if (id) {
      try {
        const j = await api("/v1/job/" + encodeURIComponent(id));
        seed = JSON.stringify(j, null, 2);
      } catch (e) { seed = ""; }
    }
    const html = `<h2>${id ? "Edit Job" : "Run Job"}</h2>
      <p class="mut">Paste an HCL jobspec (or JSON when editing); Plan
      previews the scheduler diff without committing, Run registers.</p>
      <textarea id="jobspec" class="termin" style="height:260px"
        placeholder='job "example" { ... }'>${esc(seed)}</textarea>
      <p style="margin-top:8px">
        <button id="plan-btn">Plan</button>
        <button id="run-btn">Run</button></p>
      <div id="run-out"></div>`;
    return {html, after: () => {
      const out = $("#run-out");
      async function parsed() {
        const src = $("#jobspec").value;
        const trimmed = src.trim();
        if (trimmed.startsWith("{")) {
          const j = JSON.parse(trimmed);
          return j.Job || j;
        }
        return api("/v1/jobs/parse", {method: "POST",
          headers: {"Content-Type": "application/json"},
          body: JSON.stringify({JobHCL: src})});
      }
      $("#plan-btn").addEventListener("click", async () => {
        try {
          const job = await parsed();
          const plan = await api("/v1/job/" + encodeURIComponent(job.ID) + "/plan",
            {method: "PUT", headers: {"Content-Type": "application/json"},
             body: JSON.stringify({Job: job, Diff: true})});
          out.innerHTML = `<h3>Plan</h3><pre>${esc(JSON.stringify(plan, null, 2))}</pre>`;
        } catch (e) { out.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
      });
      $("#run-btn").addEventListener("click", async () => {
        try {
          const job = await parsed();
          await api("/v1/jobs", {method: "POST",
            headers: {"Content-Type": "application/json"},
            body: JSON.stringify({Job: job})});
          location.hash = "#/jobs/" + encodeURIComponent(job.ID);
        } catch (e) { out.innerHTML = `<div class="err">${esc(e.message)}</div>`; }
      });
    }};
  },
  async jobs() {
    const jobs = await api("/v1/jobs");
    return `<h2>Jobs <a href="#/run" style="float:right;font-size:14px">+ Run Job</a></h2>` + table(
      ["ID","Type","Priority","Status","Groups"],
      jobs.map(j => ({__id: j.ID, cells: [
        esc(j.ID), esc(j.Type), j.Priority, tag(j.Status),
        Object.keys(j.JobSummary?.Summary || {}).length]})),
      "#/jobs");
  },
  async job(id) {
    const j = await api("/v1/job/" + encodeURIComponent(id));
    const allocs = await api(`/v1/job/${encodeURIComponent(id)}/allocations?all=true`);
    const evals = await api(`/v1/job/${encodeURIComponent(id)}/evaluations`);
    return `<div class="crumb"><a href="#/jobs">jobs</a> / ${esc(id)}</div>
      <h2>${esc(j.Name || id)} ${tag(j.Status)}</h2>
      <p><button class="risk" data-stop-job="${esc(id)}">Stop job</button>
         <a href="#/run/${encodeURIComponent(id)}"><button>Edit job</button></a></p>
      <div class="kv"><div>Type</div><div>${esc(j.Type)}</div>
        <div>Priority</div><div>${j.Priority}</div>
        <div>Datacenters</div><div>${esc((j.Datacenters||[]).join(", "))}</div>
        <div>Version</div><div>${j.Version ?? 0}</div></div>
      <h3>Allocations</h3>` + table(
        ["ID","Group","Desired","Client status","Node"],
        (allocs||[]).map(a => ({__id: a.ID, cells: [
          short(a.ID), esc(a.TaskGroup), esc(a.DesiredStatus),
          tag(a.ClientStatus), short(a.NodeID)]})), "#/allocs")
      + `<h3>Evaluations</h3>` + table(
        ["ID","Triggered by","Status"],
        (evals||[]).map(e => ({cells: [short(e.ID), esc(e.TriggeredBy),
                                       tag(e.Status)]})));
  },
  async allocs() {
    const allocs = await api("/v1/allocations");
    return `<h2>Allocations</h2>` + table(
      ["ID","Job","Group","Desired","Client status","Modified"],
      allocs.map(a => ({__id: a.ID, cells: [
        short(a.ID), esc(a.JobID), esc(a.TaskGroup), esc(a.DesiredStatus),
        tag(a.ClientStatus), when(a.ModifyTime)]})), "#/allocs");
  },
  async alloc(id) {
    const a = await api("/v1/allocation/" + encodeURIComponent(id));
    const states = a.TaskStates || {};
    const tasks = Object.keys(states);
    const opts = tasks.map(t => `<option>${esc(t)}</option>`).join("");
    const html = `<div class="crumb"><a href="#/allocs">allocations</a> / ${short(id)}</div>
      <h2>${esc(a.Name || id)} ${tag(a.ClientStatus)}</h2>
      <div class="kv"><div>Job</div><div><a href="#/jobs/${esc(a.JobID)}">${esc(a.JobID)}</a></div>
        <div>Node</div><div><a href="#/nodes/${esc(a.NodeID)}">${short(a.NodeID)}</a></div>
        <div>Desired</div><div>${esc(a.DesiredStatus)}</div>
        <div>Previous alloc</div><div>${short(a.PreviousAllocation) || "—"}</div></div>
      <h3>Resource usage</h3><div class="meters" id="meters">
        <div class="meter"><div class="lbl">loading…</div></div></div>
      ${tasks.map(t => `<h3>Task ${esc(t)} ${tag(states[t].State)}</h3>` + table(
        ["Time","Type","Message"],
        (states[t].Events||[]).map(e => ({cells: [
          when(e.Time), esc(e.Type), esc(e.DisplayMessage || e.Message || "")]}))
      )).join("")}
      <h3>Logs</h3>
      <div class="logbar">
        <select id="log-task">${opts}</select>
        <select id="log-type"><option>stdout</option><option>stderr</option></select>
        <label><input type="checkbox" id="log-follow" checked> follow</label>
      </div>
      <pre id="log-view">(loading…)</pre>
      <h3>Exec</h3>
      <div class="logbar">
        <select id="exec-task">${opts}</select>
        <input type="text" id="exec-cmd" size="40" value="/bin/sh" title="command">
        <button id="exec-run">Run</button>
        <button id="exec-stop" class="risk" disabled>Stop</button>
      </div>
      <div class="term" id="term">(no session — Run starts an interactive
websocket exec against the task)</div>
      <input class="termin" id="term-in" placeholder="stdin — Enter sends a line" disabled>`;
    // the hook travels WITH the page result, so a stale fetch that lost
    // the navigation race can never install its wiring on another page
    return {html, after: () => wireAllocExtras(id, tasks)};
  },
  async nodes() {
    const nodes = await api("/v1/nodes");
    return `<h2>Nodes</h2>` + table(
      ["ID","Name","DC","Class","Eligibility","Status"],
      nodes.map(n => ({__id: n.ID, cells: [
        short(n.ID), esc(n.Name), esc(n.Datacenter), esc(n.NodeClass||"—"),
        esc(n.SchedulingEligibility), tag(n.Status)]})), "#/nodes");
  },
  async node(id) {
    const n = await api("/v1/node/" + encodeURIComponent(id));
    const allocs = await api(`/v1/node/${encodeURIComponent(id)}/allocations`);
    const attrs = Object.entries(n.Attributes || {}).sort();
    return `<div class="crumb"><a href="#/nodes">nodes</a> / ${short(id)}</div>
      <h2>${esc(n.Name)} ${tag(n.Status)}</h2>
      <div class="kv"><div>Datacenter</div><div>${esc(n.Datacenter)}</div>
        <div>Class</div><div>${esc(n.NodeClass)||"—"}</div>
        <div>Drain</div><div>${n.Drain ? "yes" : "no"}</div>
        <div>Eligibility</div><div>${esc(n.SchedulingEligibility)}</div>
        <div>HTTP</div><div>${esc(n.HTTPAddr||"")}</div></div>
      <h3>Allocations</h3>` + table(
        ["ID","Job","Client status"],
        (allocs||[]).map(a => ({__id: a.ID, cells: [
          short(a.ID), esc(a.JobID), tag(a.ClientStatus)]})), "#/allocs")
      + `<h3>Attributes</h3>` + table(["Key","Value"],
        attrs.map(([k,v]) => ({cells: [esc(k), esc(v)]})));
  },
  async evals() {
    const evals = await api("/v1/evaluations");
    return `<h2>Evaluations</h2>` + table(
      ["ID","Job","Type","Triggered by","Status"],
      evals.map(e => ({cells: [short(e.ID), esc(e.JobID), esc(e.Type),
                               esc(e.TriggeredBy), tag(e.Status)]})));
  },
  async deploys() {
    const ds = await api("/v1/deployments");
    // promote/fail actions on ACTIVE deployments (the Ember app's
    // deployment controls; reference ui/app deployments route). Promote
    // only renders when a group actually has unpromoted canaries — the
    // server rejects promoting anything else.
    const act = d => {
      if (!["running","paused"].includes(d.Status)) return "";
      const canPromote = Object.values(d.TaskGroups || {}).some(
        s => (s.DesiredCanaries || 0) > 0 && !s.Promoted);
      return (canPromote
        ? `<button class="act" data-dep-promote="${esc(d.ID)}">promote</button>`
        : "") +
        `<button class="act warn" data-dep-fail="${esc(d.ID)}">fail</button>`;
    };
    const tgRow = d => Object.entries(d.TaskGroups || {}).map(([g, s]) =>
      `${esc(g)}: ${s.PlacedAllocs||0}/${s.DesiredTotal||0} placed, ` +
      `${s.HealthyAllocs||0} healthy` + (s.Promoted ? ", promoted" : "")
    ).join("<br>");
    return `<h2>Deployments</h2>` + table(
      ["ID","Job","Status","Groups","Description","Actions"],
      ds.map(d => ({cells: [short(d.ID), esc(d.JobID), tag(d.Status),
                            tgRow(d), esc(d.StatusDescription), act(d)]})));
  },
  async topo() {
    // Cluster topology (the Ember app's topology viz, ui/app topology
    // route): one card per node, reserved-capacity fill bars for cpu
    // and memory from the scheduler's view of non-terminal allocs,
    // colored chips per alloc linking to the alloc page.
    const [nodes, stubs] = await Promise.all([
      api("/v1/nodes"), api("/v1/allocations"),
    ]);
    const live = stubs.filter(a => a.DesiredStatus === "run"
      && !["complete","failed","lost"].includes(a.ClientStatus));
    // list entries are slim stubs (the reference's AllocListStub):
    // resources come from the detail endpoint, fetched concurrently
    // and capped so a C1M-scale cluster doesn't stampede the agent
    const CAP = 500;
    const detailed = await Promise.all(live.slice(0, CAP).map(a =>
      api("/v1/allocation/" + encodeURIComponent(a.ID)).catch(() => a)));
    const byNode = {};
    for (const a of detailed) {
      (byNode[a.NodeID] = byNode[a.NodeID] || []).push(a);
    }
    const infos = await Promise.all(nodes.map(n =>
      api("/v1/node/" + encodeURIComponent(n.ID)).catch(() => null)));
    const hue = s => { let h = 0;
      for (const c of String(s)) h = (h * 31 + c.charCodeAt(0)) % 360;
      return h; };
    const cards = nodes.map((n, i) => {
      const info = infos[i] || {};
      const res = info.NodeResources || {};
      const cpuCap = res.CPUShares || 0, memCap = res.MemoryMB || 0;
      const mine = byNode[n.ID] || [];
      let cpu = 0, mem = 0;
      for (const a of mine) {
        const ar = a.AllocatedResources || {};
        for (const t of Object.values(ar.Tasks || {})) {
          cpu += t.CPUShares || 0; mem += t.MemoryMB || 0;
        }
      }
      const pct = (v, cap) => cap ? Math.min(100, 100 * v / cap) : 0;
      const chips = mine.slice(0, 64).map(a =>
        `<i class="chip" data-href="#/allocs/${encodeURIComponent(a.ID)}"
            title="${esc(a.JobID)} · ${esc(a.TaskGroup)}"
            style="background:hsl(${hue(a.JobID)},55%,45%)"></i>`).join("")
        + (mine.length > 64 ? `<span class="mut">+${mine.length - 64}</span>` : "");
      return `<div class="topo-node" data-href="#/nodes/${encodeURIComponent(n.ID)}">
        <div class="lbl">${esc(n.Name)} ${tag(n.Status)}
          <span class="mut">${mine.length} allocs</span></div>
        <div class="bar"><i style="width:${pct(cpu, cpuCap).toFixed(1)}%"></i></div>
        <div class="mut" style="font-size:11px">cpu ${cpu}/${cpuCap} MHz</div>
        <div class="bar"><i style="width:${pct(mem, memCap).toFixed(1)}%"></i></div>
        <div class="mut" style="font-size:11px">mem ${mem}/${memCap} MiB</div>
        <div class="chips">${chips}</div>
      </div>`;
    }).join("");
    const capNote = live.length > CAP
      ? ` (cards sample the first ${CAP} — counts, bars and chips all
         reflect the sample, not the full cluster)` : "";
    return `<h2>Topology</h2>
      <p class="mut">${nodes.length} nodes · ${live.length} scheduled
      allocations${capNote} · chip color = job</p>
      <div class="topo">${cards || '<p class="mut">no nodes</p>'}</div>`;
  },
  async servers() {
    const members = await api("/v1/agent/members");
    const ms = members.Members || members;
    let raft = {Servers: []}, health = null;
    try { raft = await api("/v1/operator/raft/configuration"); } catch (e) {}
    try { health = await api("/v1/operator/autopilot/health"); } catch (e) {}
    return `<h2>Server members</h2>` + table(
      ["Name","Address","Status","Leader","Region"],
      ms.map(m => ({cells: [esc(m.Name), esc(m.Addr)+":"+m.Port, tag(m.Status),
                            m.Leader ? "yes" : "", esc(m.Tags?.region||"")]})))
      + `<h3>Raft configuration</h3>` + table(
        ["ID","Address","Leader","Voter"],
        (raft.Servers||[]).map(s => ({cells: [esc(s.ID), esc(s.Address),
          s.Leader ? "yes" : "", s.Voter ? "yes" : ""]})))
      + (health ? `<h3>Autopilot ${health.Healthy ? tag("ready") : tag("failed")}</h3>`
        + table(["Server","Serf","Healthy","Last index"],
          (health.Servers||[]).map(s => ({cells: [esc(s.Name), esc(s.SerfStatus),
            s.Healthy ? tag("ready") : tag("failed"), s.LastIndex]}))) : "");
  },
};

async function stopJob(id) {
  if (!confirm("Stop job " + id + "?")) return;
  try { await api("/v1/job/" + encodeURIComponent(id), {method: "DELETE"}); }
  catch (e) { alert(e.message); }
  render();
}
document.addEventListener("click", e => {
  const btn = e.target.closest("[data-stop-job]");
  if (btn) stopJob(btn.dataset.stopJob);
});

async function deploymentAction(id, action) {
  if (!confirm(action + " deployment " + id.slice(0, 8) + "?")) return;
  try {
    const body = action === "promote" ? {All: true} : {};
    await api(`/v1/deployment/${action}/${encodeURIComponent(id)}`,
              {method: "PUT", body: JSON.stringify(body),
               headers: {"Content-Type": "application/json"}});
  } catch (e) { alert(e.message); }
  render();
}
document.addEventListener("click", e => {
  const p = e.target.closest("[data-dep-promote]");
  if (p) { deploymentAction(p.dataset.depPromote, "promote"); return; }
  const f = e.target.closest("[data-dep-fail]");
  if (f) deploymentAction(f.dataset.depFail, "fail");
});

// -- alloc-page live extras: meters, server-push logs, exec terminal -----
let pageCleanup = null;     // torn down on navigation (streams, sockets)
const b64encode = s => btoa(String.fromCharCode(...new TextEncoder().encode(s)));
const b64decode = b => new TextDecoder().decode(
  Uint8Array.from(atob(b), c => c.charCodeAt(0)));

function meter(label, pct, detail, sparkSvg) {
  const w = Math.max(0, Math.min(100, pct || 0));
  return `<div class="meter"><div class="lbl">${esc(label)}</div>` +
    `<div class="val">${esc(detail)}</div>` +
    `<div class="bar"><i style="width:${w.toFixed(1)}%"></i></div>` +
    (sparkSvg || "") + `</div>`;
}

// Inline SVG sparkline over a rolling sample window (the Ember app's
// primary-metric charts; reference ui/app stats time-series). Points
// scale to the window max so spikes stay visible.
function spark(points) {
  if (!points || points.length < 2) return "";
  const W = 220, H = 36, n = points.length;
  const max = Math.max(...points, 1e-9);
  const xy = points.map((v, i) => {
    const x = (i / (n - 1)) * (W - 2) + 1;
    const y = H - 2 - (Math.max(0, v) / max) * (H - 6);
    return x.toFixed(1) + "," + y.toFixed(1);
  });
  return `<svg class="spark" viewBox="0 0 ${W} ${H}" width="${W}" height="${H}"` +
    ` preserveAspectRatio="none"><polyline points="${xy.join(" ")}"` +
    ` fill="none" stroke="currentColor" stroke-width="1.5"/></svg>`;
}

const SPARK_WINDOW = 40;  // ~2 minutes at the 3s refresh

function wireAllocExtras(id, tasks) {
  const cleanups = [];
  pageCleanup = () => cleanups.forEach(fn => { try { fn(); } catch (e) {} });

  // utilization meters + live sparklines: a rolling per-task history of
  // cpu% and RSS sampled from /v1/client/allocation/<id>/stats
  const history = {};  // task -> {cpu: [], mem: []}
  async function refreshMeters() {
    try {
      const s = await api(`/v1/client/allocation/${encodeURIComponent(id)}/stats`);
      const parts = [];
      for (const [t, ts] of Object.entries(s.Tasks || {})) {
        const cpu = ts.ResourceUsage?.CpuStats?.Percent || 0;
        const rssMib = (ts.ResourceUsage?.MemoryStats?.RSS || 0) / 1048576;
        const h = history[t] = history[t] || {cpu: [], mem: []};
        h.cpu.push(cpu); h.mem.push(rssMib);
        if (h.cpu.length > SPARK_WINDOW) { h.cpu.shift(); h.mem.shift(); }
        parts.push(meter(`${t} · CPU`, cpu, cpu.toFixed(1) + " %", spark(h.cpu)));
        parts.push(meter(`${t} · memory`, 0, rssMib.toFixed(1) + " MiB", spark(h.mem)));
      }
      if (parts.length) $("#meters").innerHTML = parts.join("");
      else $("#meters").innerHTML = `<div class="meter"><div class="lbl">no running tasks</div></div>`;
    } catch (e) {
      $("#meters").innerHTML = `<div class="meter"><div class="lbl">stats unavailable</div><div class="val mut">${esc(e.message)}</div></div>`;
    }
  }
  refreshMeters();
  const mt = setInterval(refreshMeters, 3000);
  cleanups.push(() => clearInterval(mt));

  // logs: server-push follow stream (fetch + ReadableStream) or one-shot
  let logAbort = null;
  async function startLogs() {
    if (logAbort) { logAbort.abort(); logAbort = null; }
    const t = $("#log-task").value, kind = $("#log-type").value;
    const follow = $("#log-follow").checked;
    const view = $("#log-view");
    view.textContent = "";
    const tok = localStorage.getItem("nomad_token");
    const headers = tok ? {"X-Nomad-Token": tok} : {};
    const url = `/v1/client/fs/logs/${encodeURIComponent(id)}?task=` +
      `${encodeURIComponent(t)}&type=${kind}` + (follow ? "&follow=true&origin=end&offset=4096" : "");
    const ctl = new AbortController();
    logAbort = ctl;
    cleanups.push(() => ctl.abort());
    try {
      const r = await fetch(url, {headers, signal: ctl.signal});
      if (!r.ok) { view.textContent = "(logs unavailable: " + r.status + ")"; return; }
      const reader = r.body.getReader();
      const dec = new TextDecoder();
      for (;;) {
        const {done, value} = await reader.read();
        if (done) break;
        view.textContent += dec.decode(value, {stream: true});
        if (view.textContent.length > 200000)
          view.textContent = view.textContent.slice(-150000);
        view.scrollTop = view.scrollHeight;
      }
    } catch (e) { /* aborted on navigation / toggle */ }
  }
  ["log-task","log-type","log-follow"].forEach(x =>
    $("#"+x).addEventListener("change", startLogs));
  startLogs();

  // exec: interactive websocket terminal (the agent's RFC6455 endpoint)
  let sock = null;
  function execStop() {
    if (sock) { try { sock.close(); } catch (e) {} sock = null; }
    $("#exec-run").disabled = false;
    $("#exec-stop").disabled = true;
    $("#term-in").disabled = true;
  }
  cleanups.push(execStop);
  $("#exec-run").addEventListener("click", () => {
    execStop();
    const t = $("#exec-task").value;
    const cmd = $("#exec-cmd").value.trim();
    if (!cmd) return;
    const term = $("#term");
    term.textContent = "$ " + cmd + "\n";
    const proto = location.protocol === "https:" ? "wss" : "ws";
    // browsers cannot set headers on WebSockets: the ACL token rides the
    // token query param the agent accepts alongside X-Nomad-Token
    const tok = localStorage.getItem("nomad_token");
    const url = `${proto}://${location.host}/v1/client/allocation/` +
      `${encodeURIComponent(id)}/exec?task=${encodeURIComponent(t)}` +
      `&command=${encodeURIComponent(JSON.stringify(cmd.split(/\s+/)))}` +
      (tok ? `&token=${encodeURIComponent(tok)}` : "");
    sock = new WebSocket(url);
    sock.onopen = () => {
      $("#exec-run").disabled = true;
      $("#exec-stop").disabled = false;
      const inp = $("#term-in");
      inp.disabled = false; inp.focus();
    };
    sock.onmessage = ev => {
      try {
        const frame = JSON.parse(ev.data);
        if (frame.stdout?.data) {
          term.textContent += b64decode(frame.stdout.data);
          term.scrollTop = term.scrollHeight;
        }
        if ("exit_code" in frame) {
          term.textContent += `\n(exit ${frame.exit_code})\n`;
          execStop();
        }
      } catch (e) {}
    };
    sock.onclose = execStop;
    sock.onerror = execStop;
  });
  $("#exec-stop").addEventListener("click", () => {
    if (sock) sock.send(JSON.stringify({stdin: {close: true}}));
    execStop();
  });
  $("#term-in").addEventListener("keydown", ev => {
    if (ev.key !== "Enter" || !sock) return;
    const line = ev.target.value + "\n";
    ev.target.value = "";
    $("#term").textContent += line;
    sock.send(JSON.stringify({stdin: {data: b64encode(line)}}));
  });
}

let timer = null;
let renderSeq = 0;
async function render() {
  const seq = ++renderSeq;  // stale async completions must not clobber
  if (pageCleanup) { pageCleanup(); pageCleanup = null; }
  const hash = location.hash.replace(/^#\//, "") || "jobs";
  const [page, id] = hash.split("/");
  $("#nav").innerHTML = NAV.map(([k, label]) =>
    `<a href="#/${k}" class="${page===k?"on":""}">${label}</a>`).join("");
  const fn = id && pages[page.replace(/s$/, "")] ? pages[page.replace(/s$/, "")]
           : pages[page] || pages.jobs;
  let result;
  try {
    result = await fn(id ? decodeURIComponent(id) : undefined);
  } catch (e) {
    result = `<div class="err">${esc(e.message)}</div>`;
  }
  if (seq !== renderSeq) return;  // navigation happened mid-fetch
  const html = typeof result === "string" ? result : result.html;
  $("#main").innerHTML = html;
  if (typeof result === "object" && result.after) result.after();
  clearTimeout(timer);
  // auto-refresh list pages — never the Run Job editor, which would
  // wipe the jobspec being typed
  if (!id && page !== "run") timer = setTimeout(render, 4000);
}
window.addEventListener("hashchange", render);
render();
</script>
</body>
</html>
"""


def register_ui(mux, agent) -> None:
    """Serve the SPA at /ui — http.go:213's slot. (No catch-all "/"
    route: the mux treats trailing-slash prefixes as wildcards, and the
    UI must not shadow unknown /v1 paths' 404s.)"""

    def serve(req):
        req.response_content_type = "text/html; charset=utf-8"
        return UI_HTML.encode()

    mux.register("/ui", serve)
    mux.register("/ui/", serve)
