"""Minimal RFC 6455 WebSocket: server-side upgrade + client, frames only.

Fills the transport slot of the reference's interactive exec stream
(command/agent/alloc_endpoint.go execStream upgrades to a WebSocket and
exchanges json-framed stdio; nomad/structs/streaming_rpc.go is the server-
side registry). Implements exactly what that protocol needs: the upgrade
handshake, unfragmented text/binary/close/ping frames, client masking, and
a tiny blocking client for the CLI/SDK side.
"""
from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
from typing import Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT, OP_TEXT, OP_BINARY = 0x0, 0x1, 0x2
OP_CLOSE, OP_PING, OP_PONG = 0x8, 0x9, 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def server_handshake(handler) -> bool:
    """Complete the upgrade on a BaseHTTPRequestHandler (hijacked).
    Returns False (and sends 400) if the request isn't a WS upgrade."""
    key = handler.headers.get("Sec-WebSocket-Key")
    upgrade = (handler.headers.get("Upgrade") or "").lower()
    if upgrade != "websocket" or not key:
        handler.wfile.write(
            b"HTTP/1.1 400 Bad Request\r\nContent-Length: 0\r\n\r\n"
        )
        return False
    resp = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(key)}\r\n"
        "\r\n"
    )
    handler.wfile.write(resp.encode())
    handler.wfile.flush()
    return True


def write_frame(wfile, payload: bytes, opcode: int = OP_BINARY,
                mask: bool = False) -> None:
    header = bytearray([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        header.append(mask_bit | n)
    elif n < 1 << 16:
        header.append(mask_bit | 126)
        header += struct.pack(">H", n)
    else:
        header.append(mask_bit | 127)
        header += struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        header += key
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    wfile.write(bytes(header) + payload)
    wfile.flush()


def _read_exact(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket peer closed")
        buf += chunk
    return buf


def read_frame(rfile) -> Tuple[int, bytes]:
    """Returns (opcode, payload). Raises ConnectionError on EOF."""
    b0, b1 = _read_exact(rfile, 2)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", _read_exact(rfile, 2))
    elif n == 127:
        (n,) = struct.unpack(">Q", _read_exact(rfile, 8))
    key = _read_exact(rfile, 4) if masked else None
    payload = _read_exact(rfile, n) if n else b""
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, payload


class WebSocketClient:
    """Blocking client for the CLI/SDK side of interactive exec."""

    def __init__(self, host: str, port: int, path: str,
                 headers: Optional[dict] = None, tls_context=None,
                 timeout: float = 30.0) -> None:
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if tls_context is not None:
            sock = tls_context.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        key = base64.b64encode(os.urandom(16)).decode()
        lines = [
            f"GET {path} HTTP/1.1",
            f"Host: {host}:{port}",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Key: {key}",
            "Sec-WebSocket-Version: 13",
        ]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        self.rfile = sock.makefile("rb")
        self.wfile = sock.makefile("wb")
        status = self.rfile.readline()
        if b"101" not in status:
            body = status + self.rfile.read(2048)
            raise ConnectionError(f"websocket upgrade refused: {body[:300]!r}")
        while True:  # drain response headers
            line = self.rfile.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        expected = accept_key(key)
        # (accept header already consumed above; strict validation would
        # re-parse — the agents we dial are our own)
        self._expected_accept = expected
        # the connect timeout must not govern the session: an interactive
        # shell idle longer than it would die as a silent exit-0
        self.sock.settimeout(None)

    def send(self, payload: bytes, opcode: int = OP_BINARY) -> None:
        write_frame(self.wfile, payload, opcode, mask=True)

    def recv(self) -> Tuple[int, bytes]:
        while True:
            opcode, payload = read_frame(self.rfile)
            if opcode == OP_PING:
                write_frame(self.wfile, payload, OP_PONG, mask=True)
                continue
            return opcode, payload

    def close(self) -> None:
        try:
            write_frame(self.wfile, b"", OP_CLOSE, mask=True)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
