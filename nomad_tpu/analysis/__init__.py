"""nomad-lint: AST invariant checkers for the repo's load-bearing rules.

Headline rules (full table in ``nomad_tpu/analysis/README.md``):

  - ``jit-purity``        jax.jit-compiled functions (and their transitive
                          same-module callees) stay host-effect free
  - ``dtype-discipline``  no float64 creep in the integer parity encode path
  - ``fsm-determinism``   FSM apply handlers never read wall clock or RNG
  - ``lock-order``        whole-program lock acquisition-order cycles
  - ``condition-discipline`` waits re-check predicates, notifies hold locks
  - ``shared-state-discipline`` writes to attributes inferred shared across
                          thread roots are proven lock-guarded
                          (``# guarded-by:`` declarations stay
                          authoritative; ``# race-ok: <reason>`` suppresses
                          with a ratchet on stale claims)

Run: ``python -m nomad_tpu.analysis [paths...]`` — exits non-zero on any
finding not recorded in ``nomad_tpu/analysis/baseline.json`` and not
suppressed by an inline ``# nomad-lint: disable=<rule>`` comment.
The tier-1 suite runs the same pass in ``tests/test_static_analysis.py``.
"""
from .core import (  # noqa: F401
    Finding,
    apply_baseline,
    default_checkers,
    load_baseline,
    run_paths,
    run_source,
    write_baseline,
)

__all__ = [
    "Finding",
    "apply_baseline",
    "default_checkers",
    "load_baseline",
    "run_paths",
    "run_source",
    "write_baseline",
]
