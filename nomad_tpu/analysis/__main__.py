"""CLI: ``python -m nomad_tpu.analysis [paths...]``.

Exit 0 when every finding is baselined or suppressed AND no baseline
entry is stale; 1 on new findings or stale baseline entries (the
ratchet is enforced both ways — a fixed finding must be pruned, not
left as a silent credit new regressions could spend); 2 on bad usage.

``--write-baseline`` records the current findings as the new baseline.
``--prune`` rewrites the baseline in place with only the stale entries
removed (the surgical version: it never ADDS entries, so it cannot
launder a new finding into the baseline). ``--rule`` restricts the run
to a comma-separated set of rules — baseline matching is restricted to
the same rules so unrelated entries are not reported stale.
``--changed-only`` scopes REPORTING (and baseline matching) to the
given files for fast pre-commit runs, while the collect pass still sees
the whole tree so cross-module rules keep their whole-program facts.

``--json`` emits a machine-readable object:

    {
      "findings":       [{rule, file, line, message, rendered}, ...],
      "counts":         {rule: int, ...},
      "stale_baseline": [{rule, file, message}, ...],
      "rule_wall_ms":   {rule: float, ...,    # per-rule wall time
                         "call-graph": float} # shared interprocedural
                                              # build (lock-order /
                                              # condition-discipline /
                                              # shared-state-discipline)
    }
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (
    apply_baseline,
    load_baseline,
    run_paths,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="nomad-lint: AST invariant checks "
                    "(jit-purity, dtype-discipline, lock-order, "
                    "condition-discipline, shared-state-discipline, "
                    "fsm-determinism, ...)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to lint (default: nomad_tpu)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: the shipped "
                             "nomad_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record current findings as the new baseline")
    parser.add_argument("--prune", action="store_true",
                        help="rewrite the baseline with stale (fixed) "
                             "entries removed; never adds entries")
    parser.add_argument("--rule", action="append", default=None,
                        help="only run/report these rules (repeatable or "
                             "comma-separated); baseline matching is "
                             "restricted to the same rules")
    parser.add_argument("--changed-only", action="append", default=None,
                        metavar="PATH",
                        help="only report findings in these files "
                             "(repeatable or comma-separated); the whole "
                             "tree is still collected so cross-module "
                             "rules stay whole-program. Baseline matching "
                             "is restricted to the same files.")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit a JSON object: rendered findings, "
                             "per-rule counts, stale baseline entries, "
                             "per-rule wall time (rule_wall_ms)")
    args = parser.parse_args(argv)

    paths = args.paths or ["nomad_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    rules = None
    if args.rule:
        rules = {r.strip() for part in args.rule for r in part.split(",")
                 if r.strip()}
        if not rules:
            print("error: --rule given but empty", file=sys.stderr)
            return 2

    only_rel = None
    if args.changed_only:
        changed = [c.strip() for part in args.changed_only
                   for c in part.split(",") if c.strip()]
        if not changed:
            print("error: --changed-only given but empty", file=sys.stderr)
            return 2
        # deleted files are legitimate "changed" inputs: they simply
        # cannot have findings, so they scope to nothing
        only_rel = {
            os.path.relpath(os.path.abspath(c), os.getcwd())
            .replace(os.sep, "/")
            for c in changed
        }

    timings: dict = {}
    findings = run_paths(paths, rel_to=os.getcwd(), only_rel=only_rel,
                         timings=timings)
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    stale = []
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
        if rules is not None:
            baseline = [e for e in baseline if e.get("rule") in rules]
        if only_rel is not None:
            baseline = [e for e in baseline if e.get("file") in only_rel]
        findings, stale = apply_baseline(findings, baseline)

    if args.prune:
        if args.no_baseline or not os.path.exists(baseline_path):
            print("error: --prune needs an existing baseline", file=sys.stderr)
            return 2
        full = load_baseline(args.baseline or DEFAULT_BASELINE)
        budget = {}
        for ent in stale:
            key = (ent.get("rule", ""), ent.get("file", ""),
                   ent.get("message", ""))
            budget[key] = budget.get(key, 0) + 1
        kept = []
        for ent in full:
            key = (ent.get("rule", ""), ent.get("file", ""),
                   ent.get("message", ""))
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                continue
            kept.append(ent)
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump(kept, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"pruned {len(stale)} stale entr"
              f"{'y' if len(stale) == 1 else 'ies'}; "
              f"{len(kept)} kept in {baseline_path}")
        stale = []

    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1

    if args.as_json:
        print(json.dumps({
            "findings": [
                {"rule": f.rule, "file": f.file, "line": f.line,
                 "message": f.message, "rendered": f.render()}
                for f in findings
            ],
            "counts": counts,
            "stale_baseline": stale,
            "rule_wall_ms": {
                rule: round(sec * 1000.0, 3)
                for rule, sec in sorted(timings.items())
            },
        }, indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f.render())
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) — "
                  "re-run with --prune to drop them", file=sys.stderr)
    if findings:
        print(f"{len(findings)} new finding(s)", file=sys.stderr)
        return 1
    if stale:
        print("stale baseline entries fail the run: the ratchet only "
              "tightens", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
