"""CLI: ``python -m nomad_tpu.analysis [paths...]``.

Exit 0 when every finding is baselined or suppressed; 1 otherwise; 2 on
bad usage. ``--write-baseline`` records the current findings as the new
baseline (the ratchet: fix a finding, re-write, commit the smaller file).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (
    apply_baseline,
    load_baseline,
    run_paths,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nomad_tpu.analysis",
        description="nomad-lint: AST invariant checks "
                    "(jit-purity, dtype-discipline, lock-discipline, "
                    "fsm-determinism)",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help="files/directories to lint (default: nomad_tpu)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON (default: the shipped "
                             "nomad_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--write-baseline", action="store_true",
                        help="record current findings as the new baseline")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as JSON")
    args = parser.parse_args(argv)

    paths = args.paths or ["nomad_tpu"]
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    findings = run_paths(paths, rel_to=os.getcwd())

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        write_baseline(baseline_path, findings)
        print(f"wrote {len(findings)} finding(s) to {baseline_path}")
        return 0

    stale = []
    if not args.no_baseline and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
        findings, stale = apply_baseline(findings, baseline)

    if args.as_json:
        print(json.dumps(
            [f.__dict__ for f in findings], indent=2, sort_keys=True
        ))
    else:
        for f in findings:
            print(f.render())
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) — "
                  "re-run with --write-baseline to prune", file=sys.stderr)
    if findings:
        print(f"{len(findings)} new finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
