"""blocking-read-discipline: the read surface stays blocking-query clean.

Two invariants keep the nomad-watch serving layer trustworthy:

1. **Read endpoints route through the blocking wrapper.** Every
   ``rpc.register("Noun.Verb", fn)`` in the endpoint registry whose verb
   is read-shaped (``List*``/``Get*``/``Summary``/``Allocations``/
   ``Evaluations``) must reach ``serve_read``/``blocking_read`` somewhere
   in its handler — that is the one funnel that stamps QueryMeta under
   the store's lock and honors ``min_query_index``/``allow_stale``. A
   read endpoint outside the funnel silently returns index-less
   responses that break client ``min_query_index`` chaining. Deliberate
   exceptions carry a ``# blocking-read-waiver: <reason>`` comment on or
   just above the registration.

2. **Watch-hub callbacks are read-only observers.** Functions handed to
   ``hub.add_callback`` run on the flusher thread, downstream of the FSM
   apply path: a callback that writes state (``upsert_*``/``delete_*``/
   ``update_*``/``raft_apply``/``apply``) or takes a store lock
   (``with x._lock``/``.acquire()``) can deadlock apply against the
   flusher or re-enter raft from the notification path.

Scope: invariant 1 applies to endpoint registry modules (basename
``endpoints.py``); invariant 2 applies everywhere outside this analysis
package.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, ParsedModule

RULE = "blocking-read-discipline"

_BLOCKING_FUNNELS = {"serve_read", "blocking_read"}
_READ_VERBS = {"Summary", "Allocations", "Evaluations"}
_WAIVER_MARK = "blocking-read-waiver"
# how far above a registration the waiver comment block may start
_WAIVER_LOOKBACK = 4

_MUTATOR_PREFIXES = ("upsert_", "delete_", "update_", "set_")
_MUTATOR_EXACT = {"raft_apply", "apply", "enqueue", "enqueue_all"}


def _is_endpoints_module(rel: str) -> bool:
    return rel.replace("\\", "/").rsplit("/", 1)[-1] == "endpoints.py"


def _read_verb(method: str) -> bool:
    verb = method.rsplit(".", 1)[-1]
    return (
        verb.startswith("List")
        or verb.startswith("Get")
        or verb in _READ_VERBS
    )


def _has_waiver(lines: List[str], lineno: int) -> bool:
    lo = max(1, lineno - _WAIVER_LOOKBACK)
    for i in range(lo, min(lineno + 1, len(lines) + 1)):
        if _WAIVER_MARK in lines[i - 1]:
            return True
    return False


def _local_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """Every def in the module, nested ones included (endpoint handlers
    are typically closures inside ``bind_server``)."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _calls_funnel(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _BLOCKING_FUNNELS:
            return True
        if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_FUNNELS:
            return True
    return False


def _receiver_tail(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts)).lower()


def _callback_violation(fn: ast.AST) -> Optional[str]:
    """First state-write / lock-acquire inside a callback body, or None."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr.startswith(_MUTATOR_PREFIXES) or attr in _MUTATOR_EXACT:
                return f"calls state mutator '.{attr}()'"
            if attr == "acquire":
                return "acquires a lock ('.acquire()')"
        elif isinstance(node, ast.With):
            for item in node.items:
                name = _receiver_tail(item.context_expr)
                tail = name.rsplit(".", 1)[-1]
                if tail.endswith(("_lock", "_cond")):
                    return f"takes lock 'with {name}'"
    return None


class BlockingReadDisciplineChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        rel = module.rel.replace("\\", "/")
        if "nomad_tpu/analysis/" in rel or rel.startswith("analysis/"):
            return []
        findings: List[Finding] = []
        if _is_endpoints_module(rel):
            findings.extend(self._check_endpoints(module))
        findings.extend(self._check_callbacks(module))
        return findings

    # -- invariant 1: read endpoints route through the funnel ------------

    def _check_endpoints(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        defs = _local_defs(module.tree)
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "register"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            method = node.args[0].value
            if not _read_verb(method):
                continue
            if _has_waiver(module.lines, node.lineno):
                continue
            handler = node.args[1]
            routed = False
            if isinstance(handler, ast.Lambda):
                routed = _calls_funnel(handler)
            elif isinstance(handler, ast.Name) and handler.id in defs:
                routed = _calls_funnel(defs[handler.id])
            if not routed:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"read endpoint '{method}' does not route through the "
                    f"blocking_read/serve_read funnel: responses carry no "
                    f"QueryMeta index and min_query_index chaining breaks "
                    f"(add '# {_WAIVER_MARK}: <reason>' if deliberate)",
                ))
        return findings

    # -- invariant 2: hub callbacks stay read-only -----------------------

    def _check_callbacks(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        defs = _local_defs(module.tree)
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_callback"
                and node.args
            ):
                continue
            recv = _receiver_tail(node.func.value)
            if "hub" not in recv and "watch" not in recv:
                continue  # flight-recorder publishers etc. are not ours
            cb = node.args[0]
            target: Optional[ast.AST] = None
            if isinstance(cb, ast.Lambda):
                target = cb
            elif isinstance(cb, ast.Name) and cb.id in defs:
                target = defs[cb.id]
            if target is None:
                continue
            why = _callback_violation(target)
            if why is not None:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"watch-hub notify callback {why}: callbacks run on "
                    f"the flusher thread downstream of FSM apply and must "
                    f"be read-only observers",
                ))
        return findings
