"""condition-discipline: Condition waits loop on their predicate and
notifies hold the lock.

Two classic condition-variable bugs this rule pins down statically:

- **Bare wait.** ``cond.wait()`` returns on notify, timeout, OR a
  spurious wakeup; code that waits once and proceeds acts on a
  predicate that may not hold. Every ``wait()`` on an inventoried
  Condition must sit lexically inside a ``while``-predicate loop in the
  same function (``wait_for`` carries its own loop and is exempt).

- **Unheld notify.** ``notify()``/``notify_all()`` without the
  condition's lock held raises ``RuntimeError`` at runtime — but only
  on the path that executes it. The checker proves the lock statically:
  the call is lexically inside a ``with`` of the condition (or the lock
  it wraps), the enclosing function follows the repo's ``*_locked``
  caller-holds naming convention, or every resolved call site of the
  enclosing function (transitively, depth-bounded — the same
  conservative name-based call graph the lock-order pass builds) sits
  under the lock.

Shares :class:`~nomad_tpu.analysis.lock_order.WholeProgramLockAnalysis`
with the lock-order rule; conditions are recognized from the same
inventory (``threading.Condition(...)`` / ``witness_condition(...)``
assignments), so an ``Event.wait`` or a subprocess ``wait()`` never
trips it.
"""
from __future__ import annotations

from typing import List, Optional

from .core import Finding, ParsedModule
from .lock_order import WholeProgramLockAnalysis

RULE = "condition-discipline"


class ConditionDisciplineChecker:
    rule = RULE

    def __init__(self, analysis: Optional[WholeProgramLockAnalysis] = None
                 ) -> None:
        self.analysis = analysis or WholeProgramLockAnalysis()
        self._findings: Optional[List[Finding]] = None

    def collect(self, module: ParsedModule) -> None:
        self.analysis.add_module(module)

    def _compute(self) -> List[Finding]:
        if self._findings is not None:
            return self._findings
        self.analysis.analyze()
        findings: List[Finding] = []
        for unit in self.analysis._units:
            rel = unit.mod.pm.rel
            fn = unit.qual
            for _key, lineno, in_while, is_wait_for in unit.waits:
                if is_wait_for or in_while:
                    continue
                findings.append(Finding(
                    RULE, rel, lineno,
                    f"Condition.wait() outside a while-predicate loop in "
                    f"{fn} — spurious wakeups and timeouts return with "
                    f"the predicate unchecked (use `while not pred: "
                    f"cond.wait(...)` or wait_for)",
                ))
            for lock_key, meth, lineno, lex_held in unit.notifies:
                if self.analysis.notify_held(unit, lock_key, lex_held):
                    continue
                findings.append(Finding(
                    RULE, rel, lineno,
                    f"{meth}() on condition guarding '{lock_key}' in {fn} "
                    f"is not provably issued with the lock held (no "
                    f"enclosing 'with', no *_locked caller convention, "
                    f"and not every call site holds it)",
                ))
        self._findings = findings
        return findings

    def check(self, module: ParsedModule) -> List[Finding]:
        return [f for f in self._compute() if f.file == module.rel]
