"""Shared machinery for nomad-lint: parsing, findings, suppressions,
baseline handling and the multi-checker runner.

The linter is stdlib-``ast`` only (no third-party deps) so it runs in
every environment the test suite runs in. Checkers are small classes
with an optional ``collect(module)`` pre-pass (for cross-module facts,
e.g. ``# guarded-by`` declarations) and a ``check(module)`` pass that
yields findings. Line-based facts (comments) come from ``module.lines``
since the AST drops them.
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str  # posix-style path, relative to the scan root's parent
    line: int
    message: str

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: line numbers drift with unrelated edits, so
        baselined findings match on (rule, file, message) only."""
        return (self.rule, self.file, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class ParsedModule:
    path: str  # absolute filesystem path
    rel: str   # posix display/baseline path
    tree: ast.Module
    lines: List[str]


# `# nomad-lint: disable=rule-a,rule-b` on the finding's line suppresses it.
_SUPPRESS_RE = re.compile(r"#\s*nomad-lint:\s*disable=([\w\-, ]+)")


def suppressed_rules(lines: Sequence[str], lineno: int) -> frozenset:
    """Rules disabled on a given 1-based source line."""
    if not (1 <= lineno <= len(lines)):
        return frozenset()
    m = _SUPPRESS_RE.search(lines[lineno - 1])
    if not m:
        return frozenset()
    return frozenset(part.strip() for part in m.group(1).split(",") if part.strip())


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local alias -> full dotted module/name for every import in the
    module (function-local imports included: the linter resolves names
    syntactically, not by scope)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def resolve_call_name(func: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Dotted name of a call target with its first segment de-aliased
    (``_time.monotonic`` -> ``time.monotonic``, ``np.random.x`` ->
    ``numpy.random.x``)."""
    name = dotted_name(func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    full = aliases.get(head)
    if full is not None:
        name = full + ("." + rest if rest else "")
    return name


def body_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested function/class
    definitions (those are separate units, reached only if called), but
    including lambdas and comprehensions, which execute inline."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def parse_file(path: str, rel: str) -> Tuple[Optional[ParsedModule], Optional[Finding]]:
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, Finding("parse", rel, e.lineno or 1, f"syntax error: {e.msg}")
    return ParsedModule(path=path, rel=rel, tree=tree, lines=source.splitlines()), None


def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames.sort()
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
    return files


def default_checkers() -> list:
    from .blocking_read_discipline import BlockingReadDisciplineChecker
    from .condition_discipline import ConditionDisciplineChecker
    from .dtype_discipline import DtypeDisciplineChecker
    from .fault_injection_discipline import FaultInjectionDisciplineChecker
    from .fsm_determinism import FsmDeterminismChecker
    from .jit_purity import JitPurityChecker
    from .lock_order import LockOrderChecker, WholeProgramLockAnalysis
    from .metrics_discipline import MetricsDisciplineChecker
    from .pipeline_stage_discipline import PipelineStageDisciplineChecker
    from .rpc_telemetry_discipline import RpcTelemetryDisciplineChecker
    from .shared_state import SharedStateDisciplineChecker
    from .subprocess_discipline import SubprocessDisciplineChecker
    from .trace_span_discipline import TraceSpanDisciplineChecker

    # ONE interprocedural call-graph build, shared by the three
    # concurrency rules (add_module is idempotent, analyze() memoizes)
    shared_analysis = WholeProgramLockAnalysis()
    return [
        JitPurityChecker(),
        DtypeDisciplineChecker(),
        FsmDeterminismChecker(),
        TraceSpanDisciplineChecker(),
        PipelineStageDisciplineChecker(),
        FaultInjectionDisciplineChecker(),
        SubprocessDisciplineChecker(),
        MetricsDisciplineChecker(),
        LockOrderChecker(analysis=shared_analysis),
        ConditionDisciplineChecker(analysis=shared_analysis),
        SharedStateDisciplineChecker(analysis=shared_analysis),
        RpcTelemetryDisciplineChecker(),
        BlockingReadDisciplineChecker(),
    ]


def run_paths(paths: Sequence[str], rel_to: Optional[str] = None,
              checkers: Optional[list] = None,
              only_rel: Optional[set] = None,
              timings: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Run every checker over the python files under ``paths``; returns
    suppression-filtered findings (baseline NOT applied — see
    ``apply_baseline``). ``rel_to`` anchors display/baseline paths.

    ``only_rel`` restricts REPORTING to the given rel paths while the
    collect pass still sees the whole tree (``--changed-only``: the
    cross-module facts stay whole-program, the findings are scoped).
    ``timings``, if given, accumulates per-rule wall seconds; the shared
    call-graph build is reported separately under ``call-graph`` and
    also included in whichever rule forced it."""
    rel_to = rel_to or os.getcwd()
    if checkers is None:
        checkers = default_checkers()

    modules: List[ParsedModule] = []
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(path), rel_to).replace(os.sep, "/")
        module, err = parse_file(path, rel)
        if err is not None:
            findings.append(err)
        if module is not None:
            modules.append(module)

    import time as _time
    for checker in checkers:
        collect = getattr(checker, "collect", None)
        if collect is not None:
            t0 = _time.perf_counter()
            for module in modules:
                collect(module)
            if timings is not None:
                rule = getattr(checker, "rule", type(checker).__name__)
                timings[rule] = timings.get(rule, 0.0) \
                    + _time.perf_counter() - t0
    for checker in checkers:
        t0 = _time.perf_counter()
        for module in modules:
            if only_rel is not None and module.rel not in only_rel:
                continue
            for f in checker.check(module):
                if f.rule not in suppressed_rules(module.lines, f.line) \
                        and "all" not in suppressed_rules(module.lines, f.line):
                    findings.append(f)
        if timings is not None:
            rule = getattr(checker, "rule", type(checker).__name__)
            timings[rule] = timings.get(rule, 0.0) + _time.perf_counter() - t0
    if timings is not None:
        # surface the one-shot shared call-graph build on its own line
        for checker in checkers:
            wall = getattr(getattr(checker, "analysis", None),
                           "analyze_wall_s", 0.0)
            if wall:
                timings["call-graph"] = max(timings.get("call-graph", 0.0),
                                            wall)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return findings


def run_source(source: str, rel: str, checkers: Optional[list] = None,
               extra_modules: Sequence[Tuple[str, str]] = ()) -> List[Finding]:
    """Fixture entry point: lint in-memory source (used by the unit
    tests). ``extra_modules`` are additional (source, rel) pairs that
    participate in the collect pass (cross-module lock declarations)."""
    if checkers is None:
        checkers = default_checkers()
    modules: List[ParsedModule] = []
    findings: List[Finding] = []
    for src, rel_i in [*extra_modules, (source, rel)]:
        try:
            tree = ast.parse(src, filename=rel_i)
        except SyntaxError as e:
            findings.append(Finding("parse", rel_i, e.lineno or 1,
                                    f"syntax error: {e.msg}"))
            continue
        modules.append(ParsedModule(path=rel_i, rel=rel_i, tree=tree,
                                    lines=src.splitlines()))
    for checker in checkers:
        collect = getattr(checker, "collect", None)
        if collect is not None:
            for module in modules:
                collect(module)
    for checker in checkers:
        for module in modules:
            if module.rel != rel:
                continue  # fixtures lint only the module under test
            for f in checker.check(module):
                if f.rule not in suppressed_rules(module.lines, f.line) \
                        and "all" not in suppressed_rules(module.lines, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return findings


# ---------------------------------------------------------------------------
# Baseline: a JSON list of {rule, file, message} records for pre-existing
# violations. Matching is a multiset subtraction on Finding.key() so fixed
# findings become stale entries (reported by --prune hint) and NEW findings
# of an already-baselined kind still surface once the old count is used up.
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> List[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, list):
        raise ValueError("baseline must be a JSON list")
    return data


def apply_baseline(findings: Sequence[Finding],
                   baseline: Sequence[dict]) -> Tuple[List[Finding], List[dict]]:
    """Returns (new_findings, stale_baseline_entries)."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for ent in baseline:
        key = (ent.get("rule", ""), ent.get("file", ""), ent.get("message", ""))
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            new.append(f)
    stale = [
        {"rule": k[0], "file": k[1], "message": k[2]}
        for k, count in sorted(budget.items()) for _ in range(count) if count > 0
    ]
    return new, stale


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    data = [
        {"rule": f.rule, "file": f.file, "message": f.message}
        for f in findings
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
