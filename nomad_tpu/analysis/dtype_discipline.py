"""dtype-discipline: no float64 creep in the integer-parity encode path.

The parity engine's guarantee (PARITY.md) is that patched/cached encodes
are bit-identical to fresh ones. The inline encode path casts capacities
to the eval dtype (int32 in parity mode) BEFORE subtracting; a float64
subtraction cast to int64 afterwards rounds differently on fractional
capacities — exactly the ``epoch_usage_arrays`` divergence this checker
exists to catch mechanically.

Scoped to the integer-spec modules (``tpu/encode.py``, ``tpu/intscore.py``
— the rest of the host codebase legitimately computes in float64). Two
sub-patterns:

  A. ``(x - y).astype(np.int64)`` where the subtraction operands are not
     each themselves ``.astype(...)`` casts: the subtraction ran in
     whatever dtype the operands carried (float64 capacities) instead of
     the eval dtype.
  B. binary arithmetic where one operand is provably float64 — a literal
     ``np.float64(...)`` call or a variable assigned from an allocation
     with an explicit ``np.float64`` dtype — without an ``.astype`` cast.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import Finding, ParsedModule, dotted_name, resolve_call_name

RULE = "dtype-discipline"

TARGET_SUFFIXES = ("tpu/encode.py", "tpu/intscore.py")

_ALLOC_FNS = {
    "numpy.zeros", "numpy.ones", "numpy.full", "numpy.empty",
    "numpy.array", "numpy.asarray", "numpy.zeros_like", "numpy.full_like",
    "np.zeros", "np.ones", "np.full", "np.empty",
    "np.array", "np.asarray", "np.zeros_like", "np.full_like",
}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)


def _is_float64_ref(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """np.float64 / numpy.float64 / "float64"."""
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    return (aliases.get(head, head) + ("." + rest if rest else "")) in (
        "numpy.float64", "np.float64",
    )


def _is_int64_ref(node: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int64":
        return True
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    return (aliases.get(head, head) + ("." + rest if rest else "")) in (
        "numpy.int64", "np.int64",
    )


def _is_astype_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
    )


def _sub_leaves(node: ast.BinOp) -> List[ast.AST]:
    """Leaf operands of a +/- chain: ``a - b - c`` -> [a, b, c]."""
    out: List[ast.AST] = []
    for side in (node.left, node.right):
        if isinstance(side, ast.BinOp) and isinstance(side.op, (ast.Add, ast.Sub)):
            out.extend(_sub_leaves(side))
        else:
            out.append(side)
    return out


def _float64_alloc(call: ast.Call, aliases: Dict[str, str]) -> bool:
    """An array allocation whose explicit dtype is float64 (keyword or
    positional)."""
    fn = resolve_call_name(call.func, aliases)
    if fn is None:
        return False
    head = fn.split(".")[0]
    norm = fn if head == "numpy" else fn.replace(head, "np", 1)
    if norm not in _ALLOC_FNS and fn not in _ALLOC_FNS:
        return False
    for kw in call.keywords:
        if kw.arg == "dtype" and _is_float64_ref(kw.value, aliases):
            return True
    return any(_is_float64_ref(a, aliases) for a in call.args)


class DtypeDisciplineChecker:
    rule = RULE

    def __init__(self, restrict_to=TARGET_SUFFIXES):
        self.restrict_to = tuple(restrict_to)

    def check(self, module: ParsedModule) -> List[Finding]:
        if self.restrict_to and not module.rel.endswith(self.restrict_to):
            return []
        from .core import body_walk, import_aliases

        aliases = import_aliases(module.tree)
        findings: List[Finding] = []

        # sub-pattern B, per lexical scope (body_walk skips nested defs, so
        # every node belongs to exactly one scope): names assigned float64
        # allocations taint arithmetic they appear in un-cast
        scopes = [module.tree] + [
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            tainted: Set[str] = set()
            for node in body_walk(scope):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                        and _float64_alloc(node.value, aliases):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)
            if not tainted:
                continue
            for node in body_walk(scope):
                if isinstance(node, ast.BinOp) and isinstance(node.op, _ARITH_OPS):
                    for operand in (node.left, node.right):
                        if self._operand_float64(operand, tainted, aliases):
                            findings.append(Finding(
                                RULE, module.rel, node.lineno,
                                "float64 operand "
                                f"'{ast.unparse(operand)}' in arithmetic "
                                "without an explicit dtype cast",
                            ))
                            break

        # sub-pattern A: (a - b).astype(np.int64) with un-cast operands
        for node in ast.walk(module.tree):
            if not _is_astype_call(node):
                continue
            if not any(_is_int64_ref(a, aliases) for a in node.args):
                continue
            target = node.func.value
            if not (isinstance(target, ast.BinOp)
                    and isinstance(target.op, (ast.Add, ast.Sub))):
                continue
            uncast = [
                leaf for leaf in _sub_leaves(target)
                if not (_is_astype_call(leaf) or isinstance(leaf, ast.Constant))
            ]
            if uncast:
                findings.append(Finding(
                    RULE, module.rel, target.lineno,
                    "int64 cast of a subtraction whose operands are not "
                    "each .astype()-cast first "
                    f"(un-cast: {', '.join(ast.unparse(u) for u in uncast)})",
                ))
        return findings

    def _operand_float64(self, node: ast.AST, tainted: Set[str],
                         aliases: Dict[str, str]) -> bool:
        # a tainted name, a subscript/slice of one, or a float64 literal call
        cur = node
        while isinstance(cur, ast.Subscript):
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id in tainted:
            return True
        if isinstance(node, ast.Call) and _is_float64_ref(node.func, aliases):
            return True
        return False
