"""dtype-discipline: no float64 creep in the integer-parity encode path.

The parity engine's guarantee (PARITY.md) is that patched/cached encodes
are bit-identical to fresh ones. The inline encode path casts capacities
to the eval dtype (int32 in parity mode) BEFORE subtracting; a float64
subtraction cast to int64 afterwards rounds differently on fractional
capacities — exactly the ``epoch_usage_arrays`` divergence this checker
exists to catch mechanically.

Scoped to the integer-spec modules (``tpu/encode.py``, ``tpu/intscore.py``
— the rest of the host codebase legitimately computes in float64). Two
sub-patterns:

  A. ``(x - y).astype(np.int64)`` where the subtraction operands are not
     each themselves ``.astype(...)`` casts: the subtraction ran in
     whatever dtype the operands carried (float64 capacities) instead of
     the eval dtype.
  B. binary arithmetic where one operand is provably float64 — a literal
     ``np.float64(...)`` call or a variable assigned from an allocation
     with an explicit ``np.float64`` dtype — without an ``.astype`` cast.

Packed-mask layouts (sub-pattern C, its OWN wider target list — the
kernel modules that consume packed planes, ``tpu/engine.py`` and
``tpu/batcher.py`` included): the fused scan packs boolean planes into
uint8 feature lanes and 16-bit count lanes inside int32 (intscore
"Packed-mask lanes"). Crossing a packed boundary is only exact through
the blessed helpers, so the rule flags

  C1. raw ``>>`` / ``&`` bit surgery on a ``*packed*``-named array
      outside the ``pack_*``/``unpack_*`` helpers themselves — a
      hand-rolled unpack silently breaks when the lane layout moves;
  C2. float promotion of a ``*packed*``-named plane (``.astype`` to a
      float dtype, or arithmetic against a float literal) — packed
      lanes are integral bit patterns, not numbers.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from .core import Finding, ParsedModule, dotted_name, resolve_call_name

RULE = "dtype-discipline"

TARGET_SUFFIXES = ("tpu/encode.py", "tpu/intscore.py")

# sub-pattern C applies wherever packed planes travel: the encode that
# emits them, the scan/batcher modules that consume them
PACKED_TARGET_SUFFIXES = (
    "tpu/encode.py", "tpu/intscore.py", "tpu/engine.py", "tpu/batcher.py",
)

_FLOAT_DTYPES = {
    "numpy.float16", "numpy.float32", "numpy.float64",
    "np.float16", "np.float32", "np.float64",
    "jnp.float16", "jnp.float32", "jnp.float64", "jnp.bfloat16",
    "jax.numpy.float32", "jax.numpy.float64",
}

_ALLOC_FNS = {
    "numpy.zeros", "numpy.ones", "numpy.full", "numpy.empty",
    "numpy.array", "numpy.asarray", "numpy.zeros_like", "numpy.full_like",
    "np.zeros", "np.ones", "np.full", "np.empty",
    "np.array", "np.asarray", "np.zeros_like", "np.full_like",
}
_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)


def _is_float64_ref(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """np.float64 / numpy.float64 / "float64"."""
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    return (aliases.get(head, head) + ("." + rest if rest else "")) in (
        "numpy.float64", "np.float64",
    )


def _is_int64_ref(node: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant) and node.value == "int64":
        return True
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    return (aliases.get(head, head) + ("." + rest if rest else "")) in (
        "numpy.int64", "np.int64",
    )


def _is_astype_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
    )


def _sub_leaves(node: ast.BinOp) -> List[ast.AST]:
    """Leaf operands of a +/- chain: ``a - b - c`` -> [a, b, c]."""
    out: List[ast.AST] = []
    for side in (node.left, node.right):
        if isinstance(side, ast.BinOp) and isinstance(side.op, (ast.Add, ast.Sub)):
            out.extend(_sub_leaves(side))
        else:
            out.append(side)
    return out


def _float64_alloc(call: ast.Call, aliases: Dict[str, str]) -> bool:
    """An array allocation whose explicit dtype is float64 (keyword or
    positional)."""
    fn = resolve_call_name(call.func, aliases)
    if fn is None:
        return False
    head = fn.split(".")[0]
    norm = fn if head == "numpy" else fn.replace(head, "np", 1)
    if norm not in _ALLOC_FNS and fn not in _ALLOC_FNS:
        return False
    for kw in call.keywords:
        if kw.arg == "dtype" and _is_float64_ref(kw.value, aliases):
            return True
    return any(_is_float64_ref(a, aliases) for a in call.args)


def _is_float_dtype_ref(node: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(("float", "bfloat")):
        return True
    name = dotted_name(node)
    if name is None:
        return False
    head, _, rest = name.partition(".")
    return (aliases.get(head, head) + ("." + rest if rest else "")) in \
        _FLOAT_DTYPES or name in _FLOAT_DTYPES


def _packed_operand(node: ast.AST):
    """The ``*packed*``-named Name/Attribute inside an expression (the
    packed plane crossing a boundary), or None. Does NOT descend into
    ``pack_*``/``unpack_*`` calls: a plane passed THROUGH a blessed
    helper has already crossed the boundary legally."""
    stack = [node]
    while stack:
        sub = stack.pop()
        if isinstance(sub, ast.Call):
            fn = sub.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if fname.startswith(("pack_", "unpack_")):
                continue
            stack.extend(ast.iter_child_nodes(sub))
            continue
        if isinstance(sub, ast.Name) and "packed" in sub.id.lower():
            return sub.id
        if isinstance(sub, ast.Attribute) and "packed" in sub.attr.lower():
            return sub.attr
        stack.extend(ast.iter_child_nodes(sub))
    return None


class DtypeDisciplineChecker:
    rule = RULE

    def __init__(self, restrict_to=TARGET_SUFFIXES,
                 packed_targets=PACKED_TARGET_SUFFIXES):
        self.restrict_to = tuple(restrict_to)
        self.packed_targets = tuple(packed_targets)

    def check(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        if self.restrict_to and module.rel.endswith(self.restrict_to):
            findings.extend(self._check_float64_creep(module))
        if self.packed_targets and module.rel.endswith(self.packed_targets):
            findings.extend(self._check_packed_lanes(module))
        return findings

    def _check_float64_creep(self, module: ParsedModule) -> List[Finding]:
        from .core import body_walk, import_aliases

        aliases = import_aliases(module.tree)
        findings: List[Finding] = []

        # sub-pattern B, per lexical scope (body_walk skips nested defs, so
        # every node belongs to exactly one scope): names assigned float64
        # allocations taint arithmetic they appear in un-cast
        scopes = [module.tree] + [
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            tainted: Set[str] = set()
            for node in body_walk(scope):
                if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                        and _float64_alloc(node.value, aliases):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            tainted.add(tgt.id)
            if not tainted:
                continue
            for node in body_walk(scope):
                if isinstance(node, ast.BinOp) and isinstance(node.op, _ARITH_OPS):
                    for operand in (node.left, node.right):
                        if self._operand_float64(operand, tainted, aliases):
                            findings.append(Finding(
                                RULE, module.rel, node.lineno,
                                "float64 operand "
                                f"'{ast.unparse(operand)}' in arithmetic "
                                "without an explicit dtype cast",
                            ))
                            break

        # sub-pattern A: (a - b).astype(np.int64) with un-cast operands
        for node in ast.walk(module.tree):
            if not _is_astype_call(node):
                continue
            if not any(_is_int64_ref(a, aliases) for a in node.args):
                continue
            target = node.func.value
            if not (isinstance(target, ast.BinOp)
                    and isinstance(target.op, (ast.Add, ast.Sub))):
                continue
            uncast = [
                leaf for leaf in _sub_leaves(target)
                if not (_is_astype_call(leaf) or isinstance(leaf, ast.Constant))
            ]
            if uncast:
                findings.append(Finding(
                    RULE, module.rel, target.lineno,
                    "int64 cast of a subtraction whose operands are not "
                    "each .astype()-cast first "
                    f"(un-cast: {', '.join(ast.unparse(u) for u in uncast)})",
                ))
        return findings

    def _operand_float64(self, node: ast.AST, tainted: Set[str],
                         aliases: Dict[str, str]) -> bool:
        # a tainted name, a subscript/slice of one, or a float64 literal call
        cur = node
        while isinstance(cur, ast.Subscript):
            cur = cur.value
        if isinstance(cur, ast.Name) and cur.id in tainted:
            return True
        if isinstance(node, ast.Call) and _is_float64_ref(node.func, aliases):
            return True
        return False

    # -- sub-pattern C: packed-lane discipline --------------------------

    def _check_packed_lanes(self, module: ParsedModule) -> List[Finding]:
        from .core import import_aliases

        aliases = import_aliases(module.tree)
        # the blessed helpers themselves ARE the raw bit surgery; skip
        # every node inside a pack_*/unpack_* def
        blessed = [
            (fn.lineno, fn.end_lineno or fn.lineno)
            for fn in ast.walk(module.tree)
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and fn.name.startswith(("pack_", "unpack_"))
        ]

        def in_blessed(node: ast.AST) -> bool:
            ln = getattr(node, "lineno", None)
            return ln is not None and any(a <= ln <= b for a, b in blessed)

        findings: List[Finding] = []
        seen_raw: Set[tuple] = set()  # (line, name): nested >>/& report once
        for node in ast.walk(module.tree):
            if in_blessed(node):
                continue
            # C1: raw >> / & surgery on a packed plane
            if isinstance(node, ast.BinOp) \
                    and isinstance(node.op, (ast.RShift, ast.BitAnd)):
                name = _packed_operand(node.left) or _packed_operand(node.right)
                if name:
                    if (node.lineno, name) not in seen_raw:
                        seen_raw.add((node.lineno, name))
                        findings.append(Finding(
                            RULE, module.rel, node.lineno,
                            f"raw bit unpack of packed plane '{name}' outside "
                            "the blessed intscore helpers (use unpack_feat_lane"
                            "/unpack_count_lo/unpack_count_hi)",
                        ))
                    continue
            # C2a: .astype(<float dtype>) on a packed plane
            if _is_astype_call(node) \
                    and any(_is_float_dtype_ref(a, aliases) for a in node.args):
                name = _packed_operand(node.func.value)
                if name:
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"float promotion of packed plane '{name}' "
                        "(packed lanes are integral bit patterns; unpack "
                        "through the blessed helpers before float math)",
                    ))
                    continue
            # C2b: arithmetic between a packed plane and a float literal
            if isinstance(node, ast.BinOp) and isinstance(node.op, _ARITH_OPS):
                for lhs, rhs in ((node.left, node.right),
                                 (node.right, node.left)):
                    if isinstance(rhs, ast.Constant) \
                            and isinstance(rhs.value, float):
                        name = _packed_operand(lhs)
                        if name:
                            findings.append(Finding(
                                RULE, module.rel, node.lineno,
                                "float promotion of packed plane "
                                f"'{name}' in arithmetic with a float "
                                "literal (unpack the lane first)",
                            ))
                            break
        return findings
