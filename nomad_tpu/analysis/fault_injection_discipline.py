"""fault-injection-discipline: chaos hooks only via the chaos registry.

The chaos harness (``nomad_tpu/chaos/``) stays trustworthy only if the
production side of it stays inert and uniform:

1. **Production modules touch chaos ONLY through the registry's
   ``fire`` hook.** The blessed shape is ``from ..chaos.injector import
   fire as <alias>`` plus calls to that alias. Anything else — importing
   ``ChaosInjector``/``ChaosFault`` into production code, ``if CHAOS:``
   flags, ``os.environ`` lookups with CHAOS keys, any other chaos-named
   identifier — is an ad-hoc injection branch: a second code path that
   ships to production, drifts from the registry's arm/disarm
   accounting, and silently changes behavior outside chaos runs.

2. **Every ``arm`` has a ``disarm`` in a ``finally``.** An injector that
   outlives its test poisons every run after it (the registry is a
   process-global slot). A function that arms an injector must contain
   a ``try`` whose ``finally`` calls ``disarm``/``disarm_all``;
   module-scope arms are flagged outright.

3. ``fire`` calls with a constant point name must name a registered
   injection point — a typo'd point is a hook that never fires.

Scope: rule 1 applies to production modules (``nomad_tpu/`` excluding
``nomad_tpu/chaos/`` and test files); rules 2-3 apply everywhere outside
``nomad_tpu/chaos/`` itself (the harness package owns its documented
driver-level ``finally``; consumers — tests, benches — are exactly where
a leaked arm does damage).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding, ParsedModule

RULE = "fault-injection-discipline"

# kept in sync with chaos.injector.POINTS (imported lazily to avoid
# coupling the linter's import graph to the package under lint)
_KNOWN_POINTS = (
    "device_dispatch",
    "plan_apply",
    "broker_ack",
    "raft_apply",
    "heartbeat",
    "unblock_enqueue",
    "watch_notify",
)

_ARM_RECEIVER_HINTS = ("chaos", "inj")


def _norm(rel: str) -> str:
    return rel.replace("\\", "/")


def _in_chaos_pkg(rel: str) -> bool:
    rel = _norm(rel)
    return "nomad_tpu/chaos/" in rel or rel.startswith("chaos/")


def _is_test_file(rel: str) -> bool:
    rel = _norm(rel)
    base = rel.rsplit("/", 1)[-1]
    return "tests/" in rel or base.startswith("test_") or base == "conftest.py"


# Harness modules living OUTSIDE nomad_tpu/chaos/: replay drivers that
# legitimately build on the chaos harness (subclass CrashReplay, spawn
# ServerProcess fleets) but ship next to the subsystem they exercise.
_HARNESS_MODULES = (
    "nomad_tpu/watch/serve.py",  # ServeReplay — the serve-100Kwatch bench
)


def _production_scope(rel: str) -> bool:
    rel = _norm(rel)
    if "nomad_tpu/analysis/" in rel or rel.startswith("analysis/"):
        return False  # the linter itself names chaos in its rules
    if any(rel.endswith(h) for h in _HARNESS_MODULES):
        return False
    return (
        ("nomad_tpu/" in rel or not rel.startswith(("tests/", "bench")))
        and not _in_chaos_pkg(rel)
        and not _is_test_file(rel)
    )


def _chaos_import_module(node: ast.ImportFrom) -> bool:
    mod = node.module or ""
    return "chaos" in mod.lower()


def _fire_aliases(tree: ast.AST) -> Set[str]:
    """Names the blessed ``fire`` hook is bound to in this module.

    Resolved from the raw ImportFrom nodes (not ``import_aliases``,
    which skips the relative imports production modules use)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and _chaos_import_module(node):
            for alias in node.names:
                if alias.name == "fire":
                    out.add(alias.asname or alias.name)
    return out


def _receiver_text(func: ast.expr) -> str:
    """Dotted receiver of an attribute call, best effort."""
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _looks_like_injector_arm(call: ast.Call) -> bool:
    if not (isinstance(call.func, ast.Attribute) and call.func.attr == "arm"):
        return False
    recv = _receiver_text(call.func.value).lower()
    if any(h in recv for h in _ARM_RECEIVER_HINTS):
        return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value in _KNOWN_POINTS:
        return True
    return False


def _is_disarm_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("disarm", "disarm_all")
    )


def _env_chaos_key(call: ast.Call) -> Optional[str]:
    """Constant CHAOS-ish key in an os.getenv/environ.get call."""
    name = _receiver_text(call.func) if isinstance(call.func, ast.Attribute) \
        else (call.func.id if isinstance(call.func, ast.Name) else "")
    if not name.endswith(("getenv", "environ.get")):
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str) \
            and "chaos" in call.args[0].value.lower():
        return call.args[0].value
    return None


class FaultInjectionDisciplineChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        if _in_chaos_pkg(module.rel):
            return []
        findings: List[Finding] = []
        aliases = _fire_aliases(module.tree)
        if _production_scope(module.rel):
            findings.extend(self._check_production(module, aliases))
        findings.extend(self._check_fire_points(module, aliases))
        findings.extend(self._check_arm_finally(module))
        return findings

    # -- rule 1: production modules --------------------------------------

    def _check_production(self, module: ParsedModule,
                          aliases: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and _chaos_import_module(node):
                for alias in node.names:
                    if alias.name != "fire":
                        findings.append(Finding(
                            RULE, module.rel, node.lineno,
                            f"production import of '{alias.name}' from the "
                            f"chaos package: production modules may import "
                            f"only the 'fire' hook — arming/handling chaos "
                            f"belongs to the harness",
                        ))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if "chaos" in alias.name.lower():
                        findings.append(Finding(
                            RULE, module.rel, node.lineno,
                            f"production 'import {alias.name}': chaos enters "
                            f"production only as 'from ..chaos.injector "
                            f"import fire as <alias>'",
                        ))
            elif isinstance(node, ast.Name) and "chaos" in node.id.lower() \
                    and node.id not in aliases:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"ad-hoc chaos conditioning '{node.id}' in production "
                    f"code: injection points go through the chaos "
                    f"registry's fire() hook, not module flags",
                ))
            elif isinstance(node, ast.Attribute) \
                    and "chaos" in node.attr.lower():
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"ad-hoc chaos attribute '{node.attr}' in production "
                    f"code: injection points go through the chaos "
                    f"registry's fire() hook",
                ))
            elif isinstance(node, ast.Call):
                key = _env_chaos_key(node)
                if key is not None:
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"environment-gated chaos ('{key}') in production "
                        f"code: fault behavior must be armed through the "
                        f"chaos registry, not env vars",
                    ))
            elif isinstance(node, ast.Subscript):
                recv = _receiver_text(node.value) \
                    if isinstance(node.value, (ast.Attribute, ast.Name)) else ""
                if recv.endswith("environ") \
                        and isinstance(node.slice, ast.Constant) \
                        and isinstance(node.slice.value, str) \
                        and "chaos" in node.slice.value.lower():
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"environment-gated chaos ('{node.slice.value}') in "
                        f"production code: fault behavior must be armed "
                        f"through the chaos registry, not env vars",
                    ))
        return findings

    # -- rule 3: fire() point names --------------------------------------

    def _check_fire_points(self, module: ParsedModule,
                           aliases: Set[str]) -> List[Finding]:
        if not aliases:
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in aliases):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str) \
                    and node.args[0].value not in _KNOWN_POINTS:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"fire({node.args[0].value!r}): unknown injection point "
                    f"— known points: {', '.join(_KNOWN_POINTS)}",
                ))
        return findings

    # -- rule 2: arm/finally ---------------------------------------------

    def _check_arm_finally(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        func_nodes = [
            n for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        in_func: Set[int] = set()
        for fn in func_nodes:
            has_finally_disarm = any(
                isinstance(t, ast.Try) and any(
                    _is_disarm_call(sub)
                    for stmt in t.finalbody for sub in ast.walk(stmt)
                )
                for t in ast.walk(fn)
            )
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and _looks_like_injector_arm(node):
                    in_func.add(id(node))
                    if not has_finally_disarm:
                        findings.append(Finding(
                            RULE, module.rel, node.lineno,
                            "injector armed without a disarm in a 'finally' "
                            "in the same function: a leaked arm poisons "
                            "every later run in the process",
                        ))
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _looks_like_injector_arm(node) \
                    and id(node) not in in_func:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "injector armed at module scope: arm inside a function "
                    "with a matching disarm in a 'finally'",
                ))
        return findings
