"""fsm-determinism: FSM apply handlers must be wall-clock/RNG free.

Every server materializes state by replaying the same log through
``NomadFSM.apply`` (server/fsm.py): any handler that reads the wall
clock or an RNG produces replica-divergent state — the timestamps the
FSM stores all arrive IN the log payload for exactly this reason.

Detection: module-level dict assignments whose target name contains
``DISPATCH`` are treated as apply dispatch tables; their values
(``Class.method`` / bare functions) are the roots. Reachability follows
same-module calls — ``self.m(...)`` and ``Class.m(...)`` to methods of
the same class, bare names to module functions — and flags calls into
time/random/datetime/uuid/secrets namespaces plus ``os.urandom``.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import Finding, ParsedModule, body_walk, import_aliases, resolve_call_name

RULE = "fsm-determinism"

BANNED_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.",
    "datetime.", "uuid.", "secrets.",
)
BANNED_EXACT = {"os.urandom", "time"}


class FsmDeterminismChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        # class name -> {method name -> FunctionDef}
        classes: Dict[str, Dict[str, ast.AST]] = {}
        functions: Dict[str, ast.AST] = {}
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                classes[node.name] = {
                    m.name: m for m in node.body
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions[node.name] = node

        # roots: values of module-level *DISPATCH* dicts
        roots: List[Tuple[str, ast.AST, str]] = []  # (owner class or "", fn, label)
        for node in module.tree.body:
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            else:
                continue
            if not (isinstance(target, ast.Name) and "DISPATCH" in target.id.upper()):
                continue
            if not isinstance(value, ast.Dict):
                continue
            for v in value.values:
                if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name):
                    cls, meth = v.value.id, v.attr
                    fn = classes.get(cls, {}).get(meth)
                    if fn is not None:
                        roots.append((cls, fn, f"{cls}.{meth}"))
                elif isinstance(v, ast.Name) and v.id in functions:
                    roots.append(("", functions[v.id], v.id))
        if not roots:
            return []

        aliases = import_aliases(module.tree)
        findings: List[Finding] = []
        seen: Set[int] = {id(fn) for _, fn, _ in roots}
        queue = list(roots)
        while queue:
            cls, fn, label = queue.pop()
            for node in body_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee, owner = None, ""
                f = node.func
                if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                    if f.value.id == "self" and cls:
                        callee, owner = classes.get(cls, {}).get(f.attr), cls
                    elif f.value.id in classes:
                        callee, owner = classes[f.value.id].get(f.attr), f.value.id
                elif isinstance(f, ast.Name):
                    callee = functions.get(f.id)
                if callee is not None:
                    if id(callee) not in seen:
                        seen.add(id(callee))
                        queue.append((
                            owner, callee,
                            f"{getattr(callee, 'name', '?')} (from {label})",
                        ))
                    continue
                name = resolve_call_name(f, aliases)
                if name is None:
                    continue
                if name in BANNED_EXACT or any(
                    name.startswith(p) for p in BANNED_PREFIXES
                ):
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"nondeterministic call '{name}' reachable from "
                        f"FSM dispatch handler {label}",
                    ))
        return findings
