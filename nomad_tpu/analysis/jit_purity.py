"""jit-purity: functions compiled by ``jax.jit`` must be pure.

A traced function runs ONCE per compile-cache shape; host-side effects
inside it (wall clock, RNG, threading, prints, global mutation) execute
at trace time only and silently vanish — or worse, bake a trace-time
value into the compiled executable. The scan bodies behind the placement
engine's parity guarantees (PARITY.md) must therefore never touch the
host environment.

Detection: a function is a jit ENTRY when it is decorated with
``jax.jit`` / ``partial(jax.jit, ...)`` or passed to a ``jax.jit(...)``
call. From every entry, same-module callees are resolved by bare name
(any FunctionDef with that name, nested ones included — the engine's
builder pattern returns closures) and the reachable set is scanned for:

  - calls into banned namespaces (time, random, numpy.random,
    threading, datetime, uuid, secrets, os.urandom) and bare ``print``
  - ``global`` / ``nonlocal`` declarations (rebinding escapes the trace)
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .core import Finding, ParsedModule, body_walk, import_aliases, resolve_call_name

RULE = "jit-purity"

BANNED_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.", "threading.",
    "datetime.", "uuid.", "secrets.",
)
BANNED_EXACT = {"print", "os.urandom", "time", "input"}


def _is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for ``jax.jit`` / ``jit`` (imported from jax) references and
    ``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        # resolve_call_name de-aliases `from jax import jit` to jax.jit
        if resolve_call_name(node, aliases) == "jax.jit":
            return True
    if isinstance(node, ast.Call):
        fn = resolve_call_name(node.func, aliases)
        if fn in ("functools.partial", "partial") and node.args:
            return _is_jit_expr(node.args[0], aliases)
    return False


class JitPurityChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        aliases = import_aliases(module.tree)

        # name -> FunctionDefs (nested defs included; bare-name resolution)
        by_name: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)

        entries: List[Tuple[ast.AST, str]] = []
        seen_ids: Set[int] = set()

        def add_entry(fn: ast.AST, why: str) -> None:
            if id(fn) not in seen_ids:
                seen_ids.add(id(fn))
                entries.append((fn, why))

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec, aliases):
                        add_entry(node, f"@jit function '{node.name}'")
            elif isinstance(node, ast.Call) and _is_jit_expr(node.func, aliases):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, []):
                            add_entry(fn, f"jit-compiled function '{arg.id}'")
                    elif isinstance(arg, ast.Lambda):
                        add_entry(arg, "jit-compiled lambda")

        # transitive same-module closure over bare-name calls
        queue = list(entries)
        reach: List[Tuple[ast.AST, str]] = []
        while queue:
            fn, why = queue.pop()
            reach.append((fn, why))
            for node in body_walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    for callee in by_name.get(node.func.id, []):
                        if id(callee) not in seen_ids:
                            seen_ids.add(id(callee))
                            name = getattr(callee, "name", "<lambda>")
                            queue.append(
                                (callee, f"'{name}' (reached from {why})")
                            )

        findings: List[Finding] = []
        for fn, why in reach:
            findings.extend(self._scan_function(module, fn, why, aliases))
        return findings

    def _scan_function(self, module: ParsedModule, fn: ast.AST, why: str,
                       aliases: Dict[str, str]) -> Iterable[Finding]:
        for node in body_walk(fn):
            if isinstance(node, ast.Call):
                name = resolve_call_name(node.func, aliases)
                if name is None:
                    continue
                if name in BANNED_EXACT or any(
                    name.startswith(p) for p in BANNED_PREFIXES
                ):
                    yield Finding(
                        RULE, module.rel, node.lineno,
                        f"impure call '{name}' inside {why}",
                    )
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = "global" if isinstance(node, ast.Global) else "nonlocal"
                yield Finding(
                    RULE, module.rel, node.lineno,
                    f"{kw} mutation of {', '.join(node.names)} inside {why}",
                )
