"""lock-discipline: annotated shared attributes are only written under
their lock.

Shared mutable state that scheduler worker threads and the batcher's
dispatcher thread both touch (the ``DeviceBatcher.stats`` counters) is
declared at its initializing assignment:

    self.stats = {...}  # guarded-by: _lock

From then on the checker enforces, across the WHOLE analyzed file set
(the engine's forced-kernel path mutates ``batcher.stats`` from another
module — exactly the race this rule exists for):

  - writes to ``self.<attr>`` inside the DECLARING class must sit inside
    a ``with <expr>.<lockname>:`` block (the annotated line itself is
    the declaration and is exempt);
  - writes to ``<other>.<attr>`` (non-self base) anywhere must too —
    attribute names are assumed unique enough among ANNOTATED attributes
    that a non-self write to one is a write to the guarded object.

"Write" covers plain/augmented assignment to the attribute and to any
subscript chain rooted at it (``x.stats["k"] += 1``).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ParsedModule

RULE = "lock-discipline"

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")


def _base_attribute(target: ast.AST) -> Optional[ast.Attribute]:
    """The Attribute node at the root of a write target: ``x.a`` for
    ``x.a``, ``x.a[k]`` and ``x.a[k][j]``; None for plain names."""
    cur = target
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    return cur if isinstance(cur, ast.Attribute) else None


class LockDisciplineChecker:
    rule = RULE

    def __init__(self) -> None:
        # attr -> lockname, across all collected modules
        self.guarded: Dict[str, str] = {}
        # (module rel, class name, attr) declared there; declaration linenos
        self.declaring: Set[Tuple[str, str, str]] = set()
        self.decl_lines: Set[Tuple[str, int]] = set()

    # -- pass 1: find `# guarded-by:` annotations ------------------------

    def collect(self, module: ParsedModule) -> None:
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    line = module.lines[node.lineno - 1] \
                        if node.lineno <= len(module.lines) else ""
                    m = _GUARDED_RE.search(line)
                    if m:
                        self.guarded[tgt.attr] = m.group(1)
                        self.declaring.add((module.rel, cls.name, tgt.attr))
                        self.decl_lines.add((module.rel, node.lineno))

    # -- pass 2: flag unguarded writes -----------------------------------

    def check(self, module: ParsedModule) -> List[Finding]:
        if not self.guarded:
            return []
        findings: List[Finding] = []

        def visit(node: ast.AST, stack: List[ast.AST], cls: Optional[str]) -> None:
            if isinstance(node, ast.ClassDef):
                cls = node.name
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                attr = _base_attribute(tgt)
                if attr is None or attr.attr not in self.guarded:
                    continue
                is_self = isinstance(attr.value, ast.Name) and attr.value.id == "self"
                if is_self:
                    if cls is None or (module.rel, cls, attr.attr) not in self.declaring:
                        continue  # an unrelated class's same-named attr
                    if (module.rel, node.lineno) in self.decl_lines:
                        continue  # the annotated declaration itself
                lock = self.guarded[attr.attr]
                held = set()
                for anc in stack:
                    if isinstance(anc, (ast.With, ast.AsyncWith)):
                        for item in anc.items:
                            expr = item.context_expr
                            if isinstance(expr, ast.Attribute):
                                held.add(expr.attr)
                            elif isinstance(expr, ast.Name):
                                held.add(expr.id)
                if lock not in held:
                    base = ast.unparse(attr.value)
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        f"write to '{base}.{attr.attr}' (guarded-by "
                        f"{lock}) outside a 'with ....{lock}:' block",
                    ))
            stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child, stack, cls)
            stack.pop()

        visit(module.tree, [], None)
        return findings
