"""lock-order: whole-program lock acquisition-order analysis.

nomad-lockdep's static side. The pass:

1. **Inventories** every lock/condition creation site — plain
   ``threading.Lock()/RLock()/Condition()`` assignments and the witness
   factories (``witness_lock``/``witness_rlock``/``witness_condition``
   from ``nomad_tpu/utils/lock_witness.py``). Locks are keyed
   ``module.Class._lockname`` (``module._lockname`` at module level);
   witness factory calls contribute their literal name argument, which
   is what keeps the static keys and the runtime witness keys identical
   by construction. Conditions are normalized to the lock they wrap
   (``threading.Condition(self._lock)`` — acquiring the condition IS
   acquiring the lock).

2. Builds a **conservative name-based interprocedural call graph**
   (shared with ``condition-discipline`` and
   ``shared-state-discipline`` — one instance per lint run): ``self.m()``
   resolves through the class (and by-name base classes), ``self.a.m()``
   and local ``x = ClassName(...); x.m()`` resolve through recorded
   constructor types, module aliases resolve through (relative) imports,
   and as a last resort a bare method name resolves to every definition
   of that name when there are at most ``_FALLBACK_CAP`` of them. Two
   first-class-function idioms the repo leans on are resolved
   explicitly, because the raft -> FSM -> store path flows through both:
   module-level **dispatch tables** (``_DISPATCH = {KEY: Cls.handler}``;
   a call through ``_DISPATCH[k]`` or a local bound from
   ``_DISPATCH.get(k)`` fans out to every table entry) and **callback
   attributes** (``self.fsm.on_x = self.blocked.m`` recorded globally by
   attribute name; ``self.on_x(...)`` where ``on_x`` is not a method
   resolves to every recorded assignment).

3. **Walks** ``with <lock>:`` nesting through calls: every unit gets a
   lexical summary (acquisitions, calls, each with the lexically-held
   key set at the site), then held sets propagate through the call graph
   from every unit (memoized on (unit, held-set)). Acquiring B while A
   is held emits the order edge ``A -> B`` with the first call chain
   that produced it.

4. Reports every **strongly connected component** of the edge graph as
   a potential deadlock, with both acquisition chains in the message.
   Messages carry files + call chains but no line numbers, so baseline
   entries survive unrelated drift.

``build_static_graph()`` exposes the edge set to the runtime witness's
teardown cross-check: every witnessed edge must be present here, which
makes a witness-armed stress run a soundness test for this pass.

Thread/timer targets (``threading.Thread(target=f)``) are deliberately
NOT walked inline — the callee runs on a fresh thread with an empty
held set, so no order edge crosses a spawn.
"""
from __future__ import annotations

import ast
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Finding, ParsedModule, dotted_name

RULE = "lock-order"

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_COND_CTORS = {"threading.Condition"}
_FACTORY_LOCKS = {"witness_lock", "witness_rlock",
                  "module_witness_lock", "module_witness_rlock"}
_FACTORY_CONDS = {"witness_condition"}
_FALLBACK_CAP = 3
_MAX_DEPTH = 14

# Bare-name fallback is OFF for names that collide with dict/list/set/IO/
# socket/threading protocol methods: `buf.write(...)` or `d.update(...)`
# on an unresolvable base is overwhelmingly a stdlib object, and resolving
# it to a same-named repo method manufactures wild cross-subsystem call
# chains (a dict.update inside the metrics sink must not "call" the HTTP
# client's update()).
_FALLBACK_DENY = frozenset({
    "update", "get", "put", "pop", "append", "extend", "insert", "add",
    "remove", "discard", "clear", "copy", "keys", "values", "items",
    "setdefault", "sort", "index", "count", "reverse",
    "write", "writelines", "read", "readline", "readlines", "flush",
    "close", "open", "seek", "tell",
    "recv", "send", "sendall", "connect", "accept", "bind", "listen",
    "join", "start", "run", "stop", "cancel", "set", "is_set",
    "acquire", "release", "locked", "wait", "notify", "notify_all",
    "result", "done", "submit", "shutdown",
    "encode", "decode", "strip", "split", "format", "replace",
})


def _modparts(rel: str) -> Tuple[str, ...]:
    parts = rel.replace("\\", "/").split("/")
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[0] == "nomad_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return tuple(p for p in parts if p)


class _Class:
    def __init__(self, name: str, mod: "_Mod", node: ast.ClassDef) -> None:
        self.name = name
        self.mod = mod
        self.node = node
        self.bases: List[str] = [
            b for b in (dotted_name(x) for x in node.bases) if b
        ]
        self.methods: Dict[str, "_Unit"] = {}
        self.attr_locks: Dict[str, str] = {}   # attr -> lock key
        self.attr_conds: Dict[str, str] = {}   # attr -> lock key it wraps
        self.attr_types: Dict[str, str] = {}   # attr -> dotted ctor name


class _Unit:
    __slots__ = ("qual", "node", "mod", "cls", "acquires", "calls",
                 "notifies", "waits", "scanned")

    def __init__(self, qual: str, node: ast.AST, mod: "_Mod",
                 cls: Optional[_Class]) -> None:
        self.qual = qual
        self.node = node
        self.mod = mod
        self.cls = cls
        # lexical summaries, filled by _scan_unit:
        self.acquires: List[Tuple[str, int, Tuple[str, ...]]] = []
        self.calls: List[Tuple[List["_Unit"], int, Tuple[str, ...]]] = []
        self.notifies: List[Tuple[str, str, int, Tuple[str, ...]]] = []
        self.waits: List[Tuple[str, int, bool, bool]] = []
        self.scanned = False


class _Mod:
    def __init__(self, pm: ParsedModule) -> None:
        self.pm = pm
        self.parts = _modparts(pm.rel)
        self.stem = self.parts[-1] if self.parts else pm.rel
        self.funcs: Dict[str, _Unit] = {}
        self.classes: Dict[str, _Class] = {}
        self.mod_locks: Dict[str, str] = {}
        self.mod_conds: Dict[str, str] = {}
        # dispatch tables: name -> dotted callable refs from the dict literal
        self.tables: Dict[str, List[str]] = {}
        # alias -> ("mod", parts) | ("sym", parts, symbol) | ("ext", dotted)
        self.aliases: Dict[str, Tuple] = {}


class WholeProgramLockAnalysis:
    """Shared engine for the lock-order and condition-discipline rules."""

    def __init__(self) -> None:
        self.mods: Dict[Tuple[str, ...], _Mod] = {}
        self._units: List[_Unit] = []
        self._method_index: Dict[str, List[_Unit]] = {}
        self._class_index: Dict[str, List[_Class]] = {}
        self._cond_attr_names: Set[str] = set()
        self._analyzed = False
        # edge -> (file, line, chain string)
        self.edge_sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.graph: Dict[str, Set[str]] = {}
        # reverse call index: unit -> [(caller unit, lexical held at site)]
        self.callers: Dict[_Unit, List[Tuple[_Unit, Tuple[str, ...]]]] = {}
        # callback registry: attr name -> every unit ever assigned to it
        self.callback_attrs: Dict[str, List[_Unit]] = {}
        # wall time of the one-shot analyze() build, for --json timings
        self.analyze_wall_s = 0.0

    # -- collect ---------------------------------------------------------

    def add_module(self, pm: ParsedModule) -> None:
        mod = _Mod(pm)
        if mod.parts in self.mods:
            return
        self.mods[mod.parts] = mod
        self._collect_aliases(mod)
        self._collect_defs(mod)

    def _collect_aliases(self, mod: _Mod) -> None:
        pkg = mod.parts[:-1]
        for node in ast.walk(mod.pm.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    parts = tuple(a.name.split("."))
                    if parts and parts[0] == "nomad_tpu":
                        parts = parts[1:]
                    mod.aliases[a.asname or a.name.split(".")[0]] = (
                        ("mod", parts) if a.asname else ("mod", parts[:1])
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    base = tuple((node.module or "").split("."))
                    if base and base[0] == "nomad_tpu":
                        base = base[1:]
                elif node.level - 1 <= len(pkg):
                    up = len(pkg) - (node.level - 1)
                    base = pkg[:up] + tuple(
                        (node.module or "").split(".") if node.module else ()
                    )
                else:
                    continue
                base = tuple(p for p in base if p)
                for a in node.names:
                    if a.name == "*":
                        continue
                    # the name may be a submodule OR a symbol; record both
                    mod.aliases[a.asname or a.name] = ("from", base, a.name)

    def _collect_defs(self, mod: _Mod) -> None:
        for node in mod.pm.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                u = _Unit(f"{mod.stem}.{node.name}", node, mod, None)
                mod.funcs[node.name] = u
                self._units.append(u)
            elif isinstance(node, ast.ClassDef):
                cls = _Class(node.name, mod, node)
                mod.classes[node.name] = cls
                self._class_index.setdefault(node.name, []).append(cls)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        u = _Unit(f"{mod.stem}.{cls.name}.{sub.name}",
                                  sub, mod, cls)
                        cls.methods[sub.name] = u
                        self._units.append(u)
                        self._method_index.setdefault(sub.name, []).append(u)
                self._collect_class_attrs(mod, cls)
            elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)) or \
                    (isinstance(node, ast.AnnAssign)
                     and isinstance(node.target, ast.Name)
                     and node.value is not None):
                if isinstance(node, ast.Assign):
                    name = node.targets[0].id
                else:
                    name = node.target.id
                if isinstance(node.value, ast.Dict):
                    refs = [r for r in (dotted_name(v) for v in node.value.values
                                        if v is not None) if r]
                    if refs:
                        mod.tables[name] = refs
                    continue
                kind = self._ctor_kind(node.value, mod)
                if kind is None:
                    continue
                what, key = kind
                key = key or f"{mod.stem}.{name}"
                if what == "lock":
                    mod.mod_locks[name] = key
                elif what == "cond":
                    lk = self._cond_lock_arg(node.value, mod, None)
                    mod.mod_conds[name] = lk or key
                    mod.mod_locks.setdefault(name, lk or key)

    @staticmethod
    def _ann_names(annotation: ast.AST) -> List[str]:
        """Candidate class names inside a type annotation — ``NomadFSM``,
        ``Optional[NomadFSM]``, ``List[NomadFSM]``, ``"NomadFSM"``."""
        names: List[str] = []
        for n in ast.walk(annotation):
            if isinstance(n, ast.Name) and n.id[:1].isupper() \
                    and n.id not in {"Optional", "List", "Dict", "Tuple",
                                     "Set", "Sequence", "Iterable",
                                     "Callable", "Union", "Any", "Type"}:
                names.append(n.id)
            elif isinstance(n, ast.Constant) and isinstance(n.value, str) \
                    and n.value[:1].isupper():
                names.append(n.value.rsplit(".", 1)[-1])
        return names

    def _collect_class_attrs(self, mod: _Mod, cls: _Class) -> None:
        # class-wide param -> annotated-class map, so `self.state = state`
        # (and `state or StateStore()`) types the attribute from the
        # parameter annotation
        param_anns: Dict[str, str] = {}
        for fn in ast.walk(cls.node):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for a in (list(getattr(fn.args, "posonlyargs", []))
                      + list(fn.args.args) + list(fn.args.kwonlyargs)):
                if a.annotation is None:
                    continue
                for name in self._ann_names(a.annotation):
                    param_anns.setdefault(a.arg, name)
                    break
        for node in ast.walk(cls.node):
            if isinstance(node, ast.AnnAssign):
                tgt = node.target
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    for name in self._ann_names(node.annotation):
                        cls.attr_types.setdefault(tgt.attr, name)
                        break
                continue
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            attr = tgt.attr
            kind = self._ctor_kind(node.value, mod)
            if kind is not None:
                what, key = kind
                key = key or f"{mod.stem}.{cls.name}.{attr}"
                if what == "lock":
                    cls.attr_locks.setdefault(attr, key)
                else:
                    lk = self._cond_lock_arg(node.value, mod, cls)
                    cls.attr_conds.setdefault(attr, lk or key)
                    if lk is None:
                        cls.attr_locks.setdefault(attr, key)
                    self._cond_attr_names.add(attr)
                continue
            # typed attribute: self.x = ClassName(...), self.x = param,
            # self.x = param or ClassName(...)
            vals = (node.value.values if isinstance(node.value, ast.BoolOp)
                    else [node.value])
            for v in vals:
                if isinstance(v, ast.Call):
                    ctor = dotted_name(v.func)
                    if ctor and (ctor[:1].isupper() or ("." in ctor and
                            ctor.rsplit(".", 1)[-1][:1].isupper())):
                        cls.attr_types.setdefault(attr, ctor)
                        break
                elif isinstance(v, ast.Name) and v.id in param_anns:
                    cls.attr_types.setdefault(attr, param_anns[v.id])
                    break

    def _ctor_kind(self, value: ast.AST, mod: _Mod
                   ) -> Optional[Tuple[str, Optional[str]]]:
        """('lock'|'cond', explicit key or None) for a lock-creating
        expression, else None."""
        if not isinstance(value, ast.Call):
            return None
        name = dotted_name(value.func) or ""
        tail = name.rsplit(".", 1)[-1]
        # de-alias the head for threading-as-_threading style imports
        head = name.split(".", 1)[0]
        ali = mod.aliases.get(head)
        if ali and ali[0] == "mod" and ali[1] == ("threading",):
            name = "threading." + name.split(".", 1)[1] if "." in name else name
        if name in _LOCK_CTORS or (
                tail in {"Lock", "RLock"} and head in {"threading", "_threading"}):
            return ("lock", None)
        if name in _COND_CTORS or (
                tail == "Condition" and head in {"threading", "_threading"}):
            return ("cond", None)
        if tail in _FACTORY_LOCKS:
            return ("lock", self._literal_arg(value))
        if tail in _FACTORY_CONDS:
            return ("cond", self._literal_arg(value))
        return None

    @staticmethod
    def _literal_arg(call: ast.Call) -> Optional[str]:
        if call.args and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return call.args[0].value
        return None

    def _cond_lock_arg(self, call: ast.Call, mod: _Mod,
                       cls: Optional[_Class]) -> Optional[str]:
        """The lock key a Condition(...) wraps, resolved lazily by attr
        name: ``Condition(self._lock)`` -> the class's ``_lock`` key."""
        args = list(call.args)
        name = dotted_name(call.func) or ""
        if name.rsplit(".", 1)[-1] in _FACTORY_CONDS and args:
            args = args[1:]  # first arg is the witness name literal
        if not args:
            return None
        a = args[0]
        if isinstance(a, ast.Attribute) and isinstance(a.value, ast.Name) \
                and a.value.id == "self" and cls is not None:
            # the lock attr may not be collected yet; derive its key the
            # same way the collector will
            return cls.attr_locks.get(
                a.attr, f"{mod.stem}.{cls.name}.{a.attr}")
        if isinstance(a, ast.Name):
            return mod.mod_locks.get(a.id, f"{mod.stem}.{a.id}")
        return None

    # -- resolution ------------------------------------------------------

    def _class_by_name(self, dotted: str, mod: _Mod) -> Optional[_Class]:
        """Resolve a constructor name to a collected class: module-local,
        imported (aliased), or globally unique by simple name."""
        head, _, rest = dotted.partition(".")
        if not rest and head in mod.classes:
            return mod.classes[head]
        ali = mod.aliases.get(head)
        if ali is not None:
            if ali[0] == "from" and not rest:
                # `from .x import ClassName`
                target = self.mods.get(ali[1] + (ali[2],))
                if target is None:
                    target = self.mods.get(ali[1])
                    if target is not None:
                        if ali[2] in target.classes:
                            return target.classes[ali[2]]
                        # package re-export (`from ..state import
                        # StateStore` through state/__init__): follow the
                        # __init__'s own alias one hop
                        ali2 = target.aliases.get(ali[2])
                        if ali2 is not None and ali2[0] == "from":
                            t2 = self.mods.get(ali2[1])
                            if t2 is not None and ali2[2] in t2.classes:
                                return t2.classes[ali2[2]]
                            t2 = self.mods.get(ali2[1] + (ali2[2],))
                            if t2 is not None and ali2[2] in t2.classes:
                                return t2.classes[ali2[2]]
            if ali[0] == "from" and rest:
                # `from . import x` then `x.ClassName(...)`
                target = self.mods.get(ali[1] + (ali[2],))
                if target is not None and rest in target.classes:
                    return target.classes[rest]
            if ali[0] == "mod" and rest:
                target = self.mods.get(ali[1])
                if target is not None and rest in target.classes:
                    return target.classes[rest]
        cands = self._class_index.get(dotted.rsplit(".", 1)[-1], [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _cls_chain(self, cls: _Class) -> List[_Class]:
        chain, seen = [], set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            chain.append(c)
            for b in c.bases:
                bc = self._class_by_name(b, c.mod)
                if bc is not None:
                    stack.append(bc)
        return chain

    def _attr_lock_key(self, cls: _Class, attr: str,
                       conds_too: bool = True) -> Optional[str]:
        for c in self._cls_chain(cls):
            if attr in c.attr_locks:
                return c.attr_locks[attr]
            if conds_too and attr in c.attr_conds:
                return c.attr_conds[attr]
        return None

    def _attr_type(self, cls: _Class, attr: str) -> Optional[_Class]:
        for c in self._cls_chain(cls):
            t = c.attr_types.get(attr)
            if t is not None:
                return self._class_by_name(t, c.mod)
        return None

    def _module_of_alias(self, mod: _Mod, name: str) -> Optional[_Mod]:
        ali = mod.aliases.get(name)
        if ali is None:
            return None
        if ali[0] == "mod":
            return self.mods.get(ali[1])
        if ali[0] == "from":
            return self.mods.get(ali[1] + (ali[2],))
        return None

    def _table_units(self, mod: _Mod, table: str) -> List[_Unit]:
        """Units named by a dispatch-table literal: ``Cls.method`` refs
        resolve through the class index, bare names through the module."""
        out: List[_Unit] = []
        for ref in mod.tables.get(table, ()):
            head, _, rest = ref.partition(".")
            if rest:
                c = self._class_by_name(head, mod)
                if c is not None:
                    u = c.methods.get(rest.rsplit(".", 1)[-1])
                    if u is not None:
                        out.append(u)
            elif head in mod.funcs:
                out.append(mod.funcs[head])
        return out

    def _resolve_callable_ref(self, value: ast.AST,
                              unit: _Unit) -> List[_Unit]:
        """A non-call reference to a function/bound method — the right
        side of a callback assignment like ``x.on_f = self.broker.m``."""
        mod, cls = unit.mod, unit.cls
        if isinstance(value, ast.Attribute):
            base = value.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and cls is not None:
                for c in self._cls_chain(cls):
                    if value.attr in c.methods:
                        return [c.methods[value.attr]]
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls is not None:
                t = self._attr_type(cls, base.attr)
                if t is not None:
                    for c in self._cls_chain(t):
                        if value.attr in c.methods:
                            return [c.methods[value.attr]]
        elif isinstance(value, ast.Name) and value.id in mod.funcs:
            return [mod.funcs[value.id]]
        return []

    def _collect_callbacks(self) -> None:
        """Global pass (all modules added, before any unit is scanned):
        every ``<expr>.<attr> = <callable ref>`` assignment registers
        the callee under the ATTRIBUTE NAME, so ``self.<attr>(...)``
        where ``<attr>`` is not a method fans out to every assignment —
        name-based and conservative, like the rest of the resolver."""
        def register(attr: str, value: ast.AST, u: _Unit) -> None:
            targets = self._resolve_callable_ref(value, u)
            if targets:
                reg = self.callback_attrs.setdefault(attr, [])
                for t in targets:
                    if t not in reg:
                        reg.append(t)

        for u in self._units:
            for node in ast.walk(u.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Attribute):
                    register(node.targets[0].attr, node.value, u)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in {"append", "add", "register",
                                               "insert"} \
                        and isinstance(node.func.value, ast.Attribute) \
                        and node.args:
                    # observer lists: x.leadership_observers.append(cb)
                    register(node.func.value.attr, node.args[-1], u)

    def resolve_lock_expr(self, expr: ast.AST, unit: _Unit,
                          local_types: Dict[str, _Class]) -> Optional[str]:
        """Lock key for a ``with``-context / condition expression."""
        mod, cls = unit.mod, unit.cls
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return self._attr_lock_key(cls, expr.attr)
                t = local_types.get(base.id)
                if t is not None:
                    return self._attr_lock_key(t, expr.attr)
                m2 = self._module_of_alias(mod, base.id)
                if m2 is not None:
                    return m2.mod_locks.get(expr.attr) \
                        or m2.mod_conds.get(expr.attr)
            elif isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and cls is not None:
                t = self._attr_type(cls, base.attr)
                if t is not None:
                    return self._attr_lock_key(t, expr.attr)
        elif isinstance(expr, ast.Name):
            return mod.mod_locks.get(expr.id) or mod.mod_conds.get(expr.id)
        return None

    def resolve_call(self, call: ast.Call, unit: _Unit,
                     local_types: Dict[str, _Class],
                     local_tables: Optional[Dict[str, List[_Unit]]] = None,
                     ) -> List[_Unit]:
        mod, cls = unit.mod, unit.cls
        f = call.func
        if isinstance(f, ast.Subscript) and isinstance(f.value, ast.Name) \
                and f.value.id in mod.tables:
            # direct table dispatch: _DISPATCH[kind](...)
            return self._table_units(mod, f.value.id)
        if isinstance(f, ast.Name):
            if local_tables and f.id in local_tables:
                # handler = _DISPATCH.get(kind); handler(...)
                return local_tables[f.id]
            if f.id in mod.funcs:
                return [mod.funcs[f.id]]
            ali = mod.aliases.get(f.id)
            if ali is not None and ali[0] == "from":
                target = self.mods.get(ali[1])
                if target is not None and ali[2] in target.funcs:
                    return [target.funcs[ali[2]]]
            c = self._class_by_name(f.id, mod)
            if c is not None:
                init = c.methods.get("__init__")
                return [init] if init is not None else []
            return []
        if not isinstance(f, ast.Attribute):
            return []
        meth = f.attr
        base = f.value
        if isinstance(base, ast.Subscript):
            # self.fsms[peer].apply(...) — container annotations already
            # unwrap to the element class
            base = base.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                for c in self._cls_chain(cls):
                    if meth in c.methods:
                        return [c.methods[meth]]
                # not a method: a callback attribute someone wired up
                return list(self.callback_attrs.get(meth, ()))
            t = local_types.get(base.id)
            if t is not None:
                for c in self._cls_chain(t):
                    if meth in c.methods:
                        return [c.methods[meth]]
                return []
            m2 = self._module_of_alias(mod, base.id)
            if m2 is not None:
                return [m2.funcs[meth]] if meth in m2.funcs else []
        elif isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and cls is not None:
            t = self._attr_type(cls, base.attr)
            if t is not None:
                for c in self._cls_chain(t):
                    if meth in c.methods:
                        return [c.methods[meth]]
                return []
        # conservative fallback: a method name with very few definitions,
        # unless the name shadows a stdlib container/IO/thread protocol
        if meth in _FALLBACK_DENY:
            return []
        cands = self._method_index.get(meth, [])
        if 1 <= len(cands) <= _FALLBACK_CAP:
            return list(cands)
        return []

    # -- lexical scan ----------------------------------------------------

    def _scan_unit(self, unit: _Unit) -> None:
        if unit.scanned:
            return
        unit.scanned = True
        local_types: Dict[str, _Class] = {}
        local_tables: Dict[str, List[_Unit]] = {}

        # parameter annotations seed the local type map (fsm: NomadFSM)
        args = getattr(unit.node, "args", None)
        if args is not None:
            for a in (list(getattr(args, "posonlyargs", []))
                      + list(args.args) + list(args.kwonlyargs)):
                if a.annotation is None:
                    continue
                for name in self._ann_names(a.annotation):
                    c = self._class_by_name(name, unit.mod)
                    if c is not None:
                        local_types.setdefault(a.arg, c)
                        break

        # one quick pass for local constructor types (x = ClassName(...))
        def prescan(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign) and len(child.targets) == 1 \
                        and isinstance(child.targets[0], ast.Name):
                    tgt = child.targets[0].id
                    if isinstance(child.value, ast.Call):
                        fn = child.value.func
                        # handler = _DISPATCH.get(kind)
                        if isinstance(fn, ast.Attribute) \
                                and fn.attr == "get" \
                                and isinstance(fn.value, ast.Name) \
                                and fn.value.id in unit.mod.tables:
                            local_tables.setdefault(tgt, self._table_units(
                                unit.mod, fn.value.id))
                        else:
                            ctor = dotted_name(fn)
                            if ctor:
                                c = self._class_by_name(ctor, unit.mod)
                                if c is not None:
                                    local_types.setdefault(tgt, c)
                    elif isinstance(child.value, ast.Subscript) \
                            and isinstance(child.value.value, ast.Name) \
                            and child.value.value.id in unit.mod.tables:
                        # handler = _DISPATCH[kind]
                        local_tables.setdefault(tgt, self._table_units(
                            unit.mod, child.value.value.id))
                elif isinstance(child, (ast.For, ast.AsyncFor)):
                    # `for fsm in self.fsms:` / `for i, fsm in
                    # enumerate(self.fsms):` — the container annotation
                    # already unwraps to the element class
                    it, tgt_node = child.iter, child.target
                    if isinstance(it, ast.Call) and it.args:
                        fn = it.func
                        if isinstance(fn, ast.Name) and fn.id in {
                                "enumerate", "sorted", "list", "reversed",
                                "tuple"}:
                            if fn.id == "enumerate" \
                                    and isinstance(tgt_node, ast.Tuple) \
                                    and len(tgt_node.elts) == 2:
                                tgt_node = tgt_node.elts[1]
                            it = it.args[0]
                    elif isinstance(it, ast.Call) \
                            and isinstance(it.func, ast.Attribute) \
                            and it.func.attr == "values":
                        it = it.func.value
                    name = tgt_node.id if isinstance(tgt_node, ast.Name) \
                        else None
                    t: Optional[_Class] = None
                    if isinstance(it, ast.Attribute) \
                            and isinstance(it.value, ast.Name) \
                            and it.value.id == "self" and unit.cls is not None:
                        t = self._attr_type(unit.cls, it.attr)
                        # `for cb in self.leadership_observers: cb(...)`
                        cbs = self.callback_attrs.get(it.attr)
                        if name is not None and cbs:
                            local_tables.setdefault(name, list(cbs))
                    if name is not None and t is not None:
                        local_types.setdefault(name, t)
                prescan(child)

        prescan(unit.node)

        # nested `def` bodies are skipped by the main walk — a closure
        # handed to Thread(target=...) runs with an EMPTY held set, not
        # this frame's. But a nested function CALLED here runs inline on
        # this thread: scan its body at the call site under the caller's
        # current held set (lifecycle._emit_trace_spans's `emit` closure
        # acquiring the span-ring lock is the canonical case).
        nested_defs: Dict[str, ast.AST] = {}
        for sub in ast.walk(unit.node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not unit.node:
                nested_defs.setdefault(sub.name, sub)
        inlining: Set[str] = set()

        def block(nodes: Iterable[ast.AST], held: Tuple[str, ...],
                  in_while: bool) -> None:
            for node in nodes:
                if node is None or isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new_held = held
                    for item in node.items:
                        # calls inside the context expression run BEFORE
                        # the acquisition
                        block(ast.iter_child_nodes(item.context_expr),
                              new_held, in_while)
                        if isinstance(item.context_expr, ast.Call):
                            self._scan_call(unit, item.context_expr,
                                            new_held, in_while, local_types,
                                            local_tables)
                        key = self.resolve_lock_expr(
                            item.context_expr, unit, local_types)
                        if key is not None and key not in new_held:
                            unit.acquires.append((key, node.lineno, new_held))
                            new_held = new_held + (key,)
                    block(node.body, new_held, in_while)
                    continue
                if isinstance(node, ast.While):
                    block([node.test], held, True)
                    block(node.body, held, True)
                    block(node.orelse, held, in_while)
                    continue
                if isinstance(node, ast.Call):
                    self._scan_call(unit, node, held, in_while, local_types,
                                    local_tables)
                    f = node.func
                    if isinstance(f, ast.Name) and f.id in nested_defs \
                            and f.id not in inlining:
                        inlining.add(f.id)
                        block(nested_defs[f.id].body, held, in_while)
                        inlining.discard(f.id)
                block(ast.iter_child_nodes(node), held, in_while)

        block(ast.iter_child_nodes(unit.node), (), False)

    def _scan_call(self, unit: _Unit, call: ast.Call, held: Tuple[str, ...],
                   in_while: bool, local_types: Dict[str, _Class],
                   local_tables: Dict[str, List[_Unit]]) -> None:
        f = call.func
        if isinstance(f, ast.Attribute):
            base_is_cond = (
                isinstance(f.value, ast.Attribute)
                and f.value.attr in self._cond_attr_names
            ) or (
                isinstance(f.value, ast.Name)
                and f.value.id in unit.mod.mod_conds
            )
            if f.attr in {"wait", "wait_for"} and base_is_cond:
                key = self.resolve_lock_expr(f.value, unit, local_types)
                unit.waits.append((
                    key or "?", call.lineno, in_while, f.attr == "wait_for"))
                return
            if f.attr in {"notify", "notify_all"} and base_is_cond:
                key = self.resolve_lock_expr(f.value, unit, local_types)
                if key is not None:
                    unit.notifies.append(
                        (key, f.attr, call.lineno, held))
                return
        targets = self.resolve_call(call, unit, local_types, local_tables)
        if targets:
            unit.calls.append((targets, call.lineno, held))
            for t in targets:
                self.callers.setdefault(t, []).append((unit, held))

    # -- interprocedural walk --------------------------------------------

    def analyze(self) -> None:
        if self._analyzed:
            return
        self._analyzed = True
        t0 = time.perf_counter()
        self._collect_callbacks()
        for u in self._units:
            self._scan_unit(u)
        memo: Set[Tuple[int, frozenset]] = set()

        def walk(unit: _Unit, entry_held: Tuple[str, ...],
                 chain: Tuple[str, ...], depth: int) -> None:
            key = (id(unit), frozenset(entry_held))
            if key in memo or depth > _MAX_DEPTH:
                return
            memo.add(key)
            chain = chain + (unit.qual,)
            for lock, lineno, lex in unit.acquires:
                for h in dict.fromkeys(entry_held + lex):
                    if h != lock:
                        self._add_edge(h, lock, unit.mod.pm.rel, lineno,
                                       chain)
            for targets, _lineno, lex in unit.calls:
                nh = tuple(dict.fromkeys(entry_held + lex))
                for t in targets:
                    walk(t, nh, chain, depth + 1)

        for u in self._units:
            walk(u, (), (), 0)
        self.analyze_wall_s = time.perf_counter() - t0

    def _add_edge(self, a: str, b: str, rel: str, lineno: int,
                  chain: Tuple[str, ...]) -> None:
        succ = self.graph.setdefault(a, set())
        if b in succ:
            return
        succ.add(b)
        self.edge_sites[(a, b)] = (rel, lineno, " -> ".join(chain[-4:]))

    # -- outputs ---------------------------------------------------------

    def edges(self) -> Set[Tuple[str, str]]:
        self.analyze()
        return {(a, b) for a, succ in self.graph.items() for b in succ}

    def cycles(self) -> List[List[str]]:
        """Strongly connected components with >= 2 nodes, sorted."""
        self.analyze()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        nodes = sorted(set(self.graph)
                       | {b for s in self.graph.values() for b in s})

        def strong(v: str) -> None:
            work = [(v, iter(sorted(self.graph.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(self.graph.get(w, ())))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in nodes:
            if v not in index:
                strong(v)
        return sorted(sccs)

    # -- condition-discipline support ------------------------------------

    def notify_held(self, unit: _Unit, lock_key: str,
                    lex_held: Tuple[str, ...]) -> bool:
        """Is a notify site provably issued with ``lock_key`` held?
        Lexical with-block, the ``*_locked`` caller-holds convention, or
        every (transitive, depth-bounded) call site under the lock."""
        if lock_key in lex_held:
            return True

        def fn_name(u: _Unit) -> str:
            return u.qual.rsplit(".", 1)[-1]

        def check(u: _Unit, depth: int, seen: Set[int]) -> bool:
            if fn_name(u).endswith("_locked"):
                return True
            if depth > 3 or id(u) in seen:
                return False
            seen.add(id(u))
            sites = self.callers.get(u, [])
            if not sites:
                return False
            return all(
                lock_key in held or check(caller, depth + 1, seen)
                for caller, held in sites
            )

        return check(unit, 0, set())


class LockOrderChecker:
    """Registered checker: reports each lock-order SCC once, attributed
    to the file of its lexically-first edge site."""

    rule = RULE

    def __init__(self, analysis: Optional[WholeProgramLockAnalysis] = None
                 ) -> None:
        self.analysis = analysis or WholeProgramLockAnalysis()
        self._findings: Optional[List[Finding]] = None

    def collect(self, module: ParsedModule) -> None:
        self.analysis.add_module(module)

    def _compute(self) -> List[Finding]:
        if self._findings is not None:
            return self._findings
        findings: List[Finding] = []
        for comp in self.analysis.cycles():
            in_comp = set(comp)
            edges = sorted(
                (a, b) for (a, b) in self.analysis.edge_sites
                if a in in_comp and b in in_comp
            )
            parts = []
            for a, b in edges:
                rel, _lineno, chain = self.analysis.edge_sites[(a, b)]
                parts.append(f"{a} -> {b} [{rel} via {chain}]")
            first = self.analysis.edge_sites[edges[0]]
            findings.append(Finding(
                RULE, first[0], first[1],
                "potential deadlock: lock-order cycle {%s}; edges: %s"
                % (", ".join(comp), "; ".join(parts)),
            ))
        self._findings = findings
        return findings

    def check(self, module: ParsedModule) -> List[Finding]:
        return [f for f in self._compute() if f.file == module.rel]


# -- the witness cross-check entry point ------------------------------------

_STATIC_CACHE: Dict[str, Set[Tuple[str, str]]] = {}


def build_static_graph(root: Optional[str] = None) -> Set[Tuple[str, str]]:
    """Whole-tree lock-order edges, for the runtime witness's teardown
    cross-check. ``root`` defaults to the installed ``nomad_tpu``
    package; results are cached per root."""
    from .core import iter_py_files, parse_file

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.abspath(root)
    cached = _STATIC_CACHE.get(root)
    if cached is not None:
        return cached
    analysis = WholeProgramLockAnalysis()
    base = os.path.dirname(root)
    for path in iter_py_files([root]):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        pm, _err = parse_file(path, rel)
        if pm is not None:
            analysis.add_module(pm)
    edges = analysis.edges()
    _STATIC_CACHE[root] = edges
    return edges
