"""metrics-discipline: metric names are literals from registered families.

The InmemSink aggregates by exact metric name, so the name SET must be
bounded at compile time: a name minted per eval id / node id / error
string grows every retained interval without bound and makes
``/v1/metrics`` rendering quadratic. Three obligations at every
``metrics.incr_counter/add_sample/set_gauge/measure_since`` call site:

  1. the name argument is a dotted ``nomad.*`` string literal, an
     UPPER_CASE module constant, or an f-string whose literal head is
     ``nomad.<family>...`` (a bounded enum suffix like the eval type is
     fine — the family stays greppable);
  2. f-string names must NOT appear lexically inside a for/while loop —
     that is the "minted in a hot loop" cardinality smell. Loops publish
     dynamic key sets through the blessed doors in
     ``utils.metric_names``: ``publish_family(prefix, mapping)`` for
     gauges, ``family_sample``/``family_counter`` for bounded dynamic
     keys under a registered family (the RPC layer's per-method names);
  3. the name's family (``nomad.<second segment>``) is documented in
     ``utils/metric_names.py`` FAMILIES (enforced when that registry is
     in the scanned module set, i.e. on full-tree runs; fixtures opt in
     via ``extra_modules``).

``publish_family`` itself must be called with a literal registered
prefix. The registry module is exempt (it IS the blessed door), as is
``utils/metrics.py`` (the sink's internal fan-out plumbing).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .core import Finding, ParsedModule, import_aliases, resolve_call_name

RULE = "metrics-discipline"

_CHECKED = {"incr_counter", "add_sample", "set_gauge", "measure_since"}
_NAME_RE = re.compile(r"^nomad\.[a-z0-9_]+(\.[a-zA-Z0-9_\-]+)+$")
_HEAD_RE = re.compile(r"^nomad\.[a-z0-9_]+\.")
_CONST_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")

#: modules exempt from call-site checks: the blessed dynamic-name door
#: and the sink's own plumbing
_EXEMPT = ("utils/metric_names.py", "utils/metrics.py")


def _is_metrics_call(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """'set_gauge' etc. when the call targets the metrics module (any
    alias/relative-import spelling), else None."""
    name = resolve_call_name(call.func, aliases)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] in _CHECKED and len(parts) >= 2 \
            and parts[-2].lstrip("_") == "metrics":
        return parts[-1]
    return None


#: the blessed dynamic-name doors in utils/metric_names.py; each takes a
#: literal registered family prefix as its first argument
_BLESSED_DOORS = {"publish_family", "family_sample", "family_counter"}


def _blessed_door(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    name = resolve_call_name(call.func, aliases)
    if name is None:
        return None
    tail = name.split(".")[-1]
    return tail if tail in _BLESSED_DOORS else None


def _fstring_head(node: ast.JoinedStr) -> Optional[str]:
    """The leading literal part of an f-string, or None."""
    if node.values and isinstance(node.values[0], ast.Constant) \
            and isinstance(node.values[0].value, str):
        return node.values[0].value
    return None


def _const_name(node: ast.AST) -> Optional[str]:
    """Terminal identifier of a Name/Attribute, if UPPER_CASE constant."""
    if isinstance(node, ast.Name):
        seg = node.id
    elif isinstance(node, ast.Attribute):
        seg = node.attr
    else:
        return None
    return seg if _CONST_RE.match(seg) else None


class MetricsDisciplineChecker:
    rule = RULE

    def __init__(self) -> None:
        self._families: Set[str] = set()

    # -- collect: read FAMILIES keys from the registry module -----------

    def collect(self, module: ParsedModule) -> None:
        if not module.rel.endswith("utils/metric_names.py"):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if not any(isinstance(t, ast.Name) and t.id == "FAMILIES"
                       for t in targets):
                continue
            value = node.value
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Constant) \
                            and isinstance(key.value, str):
                        self._families.add(key.value)

    # -- check ----------------------------------------------------------

    def check(self, module: ParsedModule) -> List[Finding]:
        if module.rel.endswith(_EXEMPT):
            return []
        aliases = import_aliases(module.tree)
        findings: List[Finding] = []
        self._visit(module, module.tree, aliases, False, findings)
        return findings

    def _visit(self, module: ParsedModule, node: ast.AST,
               aliases: Dict[str, str], in_loop: bool,
               findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                # a nested def is its own execution context, not part of
                # the enclosing loop's per-iteration body
                self._visit(module, child, aliases, False, findings)
                continue
            child_in_loop = in_loop or isinstance(
                child, (ast.For, ast.AsyncFor, ast.While))
            if isinstance(child, ast.Call):
                self._check_call(module, child, aliases, child_in_loop,
                                 findings)
            self._visit(module, child, aliases, child_in_loop, findings)

    def _check_call(self, module: ParsedModule, call: ast.Call,
                    aliases: Dict[str, str], in_loop: bool,
                    findings: List[Finding]) -> None:
        door = _blessed_door(call, aliases)
        if door is not None:
            self._check_prefix(module, call, door, findings)
            return
        fn = _is_metrics_call(call, aliases)
        if fn is None or not call.args:
            return
        name_arg = call.args[0]

        if isinstance(name_arg, ast.Constant) \
                and isinstance(name_arg.value, str):
            if not _NAME_RE.match(name_arg.value):
                findings.append(Finding(
                    RULE, module.rel, call.lineno,
                    f"metric name {name_arg.value!r} is not a dotted "
                    f"'nomad.<family>.<name>' literal",
                ))
            else:
                self._check_family(module, call, name_arg.value, findings)
            return

        if isinstance(name_arg, ast.JoinedStr):
            head = _fstring_head(name_arg)
            if head is None or not _HEAD_RE.match(head):
                findings.append(Finding(
                    RULE, module.rel, call.lineno,
                    f"f-string metric name passed to {fn}() has no "
                    f"'nomad.<family>.' literal head — the family must "
                    f"be greppable",
                ))
                return
            if in_loop:
                findings.append(Finding(
                    RULE, module.rel, call.lineno,
                    f"f-string metric name minted inside a loop at {fn}() "
                    f"— unbounded cardinality kills the InmemSink; "
                    f"publish the dict through "
                    f"metric_names.publish_family(...)",
                ))
                return
            self._check_family(module, call, head, findings)
            return

        if _const_name(name_arg) is not None:
            return  # module constant: bounded by construction

        findings.append(Finding(
            RULE, module.rel, call.lineno,
            f"metric name passed to {fn}() is dynamic (not a 'nomad.*' "
            f"literal, UPPER_CASE constant, or literal-headed f-string)",
        ))

    def _check_prefix(self, module: ParsedModule, call: ast.Call,
                      door: str, findings: List[Finding]) -> None:
        if not call.args:
            return
        prefix = call.args[0]
        if not (isinstance(prefix, ast.Constant)
                and isinstance(prefix.value, str)
                and prefix.value.startswith("nomad.")):
            findings.append(Finding(
                RULE, module.rel, call.lineno,
                f"{door}() prefix must be a 'nomad.*' string "
                f"literal",
            ))
            return
        self._check_family(module, call, prefix.value, findings)

    def _check_family(self, module: ParsedModule, call: ast.Call,
                      name: str, findings: List[Finding]) -> None:
        if not self._families:
            return  # registry not in the scanned set (unit fixtures)
        family = ".".join(name.split(".")[:2])
        if family not in self._families:
            findings.append(Finding(
                RULE, module.rel, call.lineno,
                f"metric family {family!r} is not documented in "
                f"utils/metric_names.py FAMILIES",
            ))
