"""pipeline-stage-discipline: the async pipeline's stage boundaries.

The eval-lifecycle pipeline (``nomad_tpu/pipeline/``) only stays correct
— and only stays BOUNDED — if its stages respect two structural rules:

1. **Commits go through the plan queue, never around it.** Pipeline code
   must not apply raft entries (``server.raft_apply(...)``,
   ``raft.apply(...)``) or write the state store directly
   (``state.upsert_*`` / ``state.delete_*``): the Planner's batched
   waiter is the single serialization point, and a side-door write from
   the dispatch-stage thread would bypass both the per-payload failure
   isolation and the OCC evaluation that makes overlapping waves safe.

2. **Stage handoff only via bounded queues.** An unbounded
   ``queue.Queue()`` between stages turns a stalled consumer into
   unbounded memory growth (the exact convoy-to-OOM failure the
   pipeline exists to avoid). Construct ``BoundedStageQueue`` (or pass
   an explicit positive ``maxsize``) so backpressure propagates to the
   producer instead.

Scope is syntactic: modules whose path sits under ``nomad_tpu/pipeline/``.
Violations are recognized by call shape — a call whose resolved dotted
name ends in ``raft_apply``, a ``<...>.raft.apply(...)`` chain, an
attribute call named ``upsert_<x>``/``delete_<x>``, or a
``queue.Queue``/``SimpleQueue`` construction without a positive
``maxsize``.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from .core import Finding, ParsedModule, import_aliases, resolve_call_name

RULE = "pipeline-stage-discipline"

# attribute-call prefixes that constitute a direct state-store write
_STORE_WRITE_PREFIXES = ("upsert_", "delete_")


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return "nomad_tpu/pipeline/" in rel or rel.startswith("pipeline/")


def _unbounded_queue(call: ast.Call, name: Optional[str]) -> Optional[str]:
    """Reason string if this call constructs an unbounded stdlib queue."""
    if name in ("queue.SimpleQueue", "multiprocessing.SimpleQueue"):
        return f"'{name}' has no capacity bound"
    if name not in ("queue.Queue", "queue.LifoQueue", "queue.PriorityQueue"):
        return None
    maxsize: Optional[ast.expr] = None
    if call.args:
        maxsize = call.args[0]
    for kw in call.keywords:
        if kw.arg == "maxsize":
            maxsize = kw.value
    if maxsize is None:
        return f"'{name}' constructed without maxsize"
    if isinstance(maxsize, ast.Constant) and isinstance(maxsize.value, int) \
            and maxsize.value <= 0:
        return f"'{name}' constructed with maxsize<=0 (unbounded)"
    return None  # explicit non-constant/positive maxsize: caller's bound


class PipelineStageDisciplineChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        if not _in_scope(module.rel):
            return []
        aliases = import_aliases(module.tree)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = resolve_call_name(node.func, aliases)
            parts = name.split(".") if name else []

            # raft applies: server.raft_apply(...) / self.raft.apply(...)
            if parts and (parts[-1] == "raft_apply"
                          or (len(parts) >= 2 and parts[-1] == "apply"
                              and parts[-2] == "raft")):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"raft apply '{name}' from pipeline code: commits must "
                    f"go through plan_queue.enqueue so the Planner's "
                    f"batched waiter stays the single serialization point",
                ))
                continue

            # direct state-store writes: <x>.upsert_*/<x>.delete_*
            if isinstance(node.func, ast.Attribute) and any(
                node.func.attr.startswith(p) for p in _STORE_WRITE_PREFIXES
            ):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"state-store write '{node.func.attr}' from pipeline "
                    f"code: only the FSM mutates the store — hand results "
                    f"to the plan queue instead",
                ))
                continue

            # unbounded stage handoff queues
            reason = _unbounded_queue(node, name)
            if reason is not None:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"unbounded stage queue: {reason} — stage handoff must "
                    f"use BoundedStageQueue (or an explicit positive "
                    f"maxsize) so backpressure reaches the producer",
                ))
        return findings
