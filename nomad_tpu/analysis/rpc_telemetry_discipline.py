"""rpc-telemetry-discipline: RPC traffic must stay observable.

The transport's telemetry and tracing (nomad-xtrace) hang off exactly
two choke points: ``RPCServer.register`` / ``register_endpoint`` feed
the handler table whose dispatch loop records per-method latency
histograms and opens server spans, and ``RPCClient.call`` stamps the
outbound ``TraceContext`` into the envelope's ``trace`` field and opens
the client span. Code that slips around either choke point produces
RPCs that are invisible — no ``nomad.rpc.<method>.*`` series, no span,
a hole in every stitched trace. Three obligations everywhere outside
the transport itself:

  1. no raw handler-table inserts: ``<server>.handlers[...] = fn``
     bypasses ``register()`` (today they are equivalent, but the
     registry is the documented seam where per-method instrumentation
     attaches — and the stats table is BOUNDED by it);
  2. no reaching for the private frame plumbing: importing or calling
     ``_send_frame`` / ``_recv_frame`` / ``_read_exact`` builds a side
     channel the telemetry never sees;
  3. no hand-built request envelopes: a dict literal carrying both
     ``"seq"`` and ``"method"`` keys is wire-format assembly — those
     frames skip ``RPCClient.call`` and therefore never carry the
     TraceContext, so the receiving server span becomes a trace root
     and the cross-process tree silently splits.

Exempt: ``rpc/transport.py`` (it IS the choke point) and
``plugins/transport.py`` (the external-plugin frame protocol speaks the
same framing by design but is not a server RPC — plugin calls are
in-process children of the worker's span).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, ParsedModule, import_aliases, resolve_call_name

RULE = "rpc-telemetry-discipline"

#: the transport's private frame plumbing — any use outside the exempt
#: modules is a telemetry-invisible side channel
_PRIVATE_FRAME_FNS = {"_send_frame", "_recv_frame", "_read_exact"}

#: a dict literal with BOTH keys is a hand-assembled request envelope
_ENVELOPE_KEYS = {"seq", "method"}

_EXEMPT = ("rpc/transport.py", "plugins/transport.py")


def _dict_literal_keys(node: ast.Dict) -> set:
    keys = set()
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.add(k.value)
    return keys


class RpcTelemetryDisciplineChecker:
    rule = RULE

    def collect(self, module: ParsedModule) -> None:  # single-pass rule
        pass

    def check(self, module: ParsedModule) -> List[Finding]:
        if module.rel.endswith(_EXEMPT):
            return []
        aliases = import_aliases(module.tree)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            self._check_handler_insert(module, node, findings)
            self._check_frame_import(module, node, findings)
            self._check_frame_call(module, node, aliases, findings)
            self._check_envelope_literal(module, node, findings)
        return findings

    # -- 1: raw handler-table inserts -----------------------------------

    def _check_handler_insert(self, module: ParsedModule, node: ast.AST,
                              findings: List[Finding]) -> None:
        if not isinstance(node, ast.Assign):
            return
        for target in node.targets:
            if not isinstance(target, ast.Subscript):
                continue
            base = target.value
            if isinstance(base, ast.Attribute) and base.attr == "handlers":
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "raw handler-table insert bypasses RPCServer.register()"
                    " — the registry is where per-method telemetry and"
                    " server spans attach (and what bounds the stats"
                    " table)",
                ))

    # -- 2: private frame plumbing --------------------------------------

    def _check_frame_import(self, module: ParsedModule, node: ast.AST,
                            findings: List[Finding]) -> None:
        if not isinstance(node, ast.ImportFrom):
            return
        mod = node.module or ""
        if not mod.endswith("transport"):
            return
        for alias in node.names:
            if alias.name in _PRIVATE_FRAME_FNS:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"importing transport.{alias.name} builds a frame"
                    f" side channel the RPC telemetry never sees — go"
                    f" through RPCClient.call / RPCServer.register",
                ))

    def _check_frame_call(self, module: ParsedModule, node: ast.AST,
                          aliases: Dict[str, str],
                          findings: List[Finding]) -> None:
        if not isinstance(node, ast.Call):
            return
        name = resolve_call_name(node.func, aliases)
        if name is None:
            return
        parts = name.split(".")
        # require a transport qualifier: a module's OWN helper that
        # happens to share the name (agent/websocket.py frames its own
        # protocol) is not the RPC side channel this rule bans — the
        # import check above still catches `from ...transport import x`
        if (parts[-1] in _PRIVATE_FRAME_FNS and len(parts) >= 2
                and parts[-2].lstrip("_").endswith("transport")):
            findings.append(Finding(
                RULE, module.rel, node.lineno,
                f"direct transport.{parts[-1]}() call skips the"
                f" instrumented RPC path (no latency row, no span)",
            ))

    # -- 3: hand-built envelopes ----------------------------------------

    def _check_envelope_literal(self, module: ParsedModule, node: ast.AST,
                                findings: List[Finding]) -> None:
        if not isinstance(node, ast.Dict):
            return
        if _ENVELOPE_KEYS <= _dict_literal_keys(node):
            findings.append(Finding(
                RULE, module.rel, node.lineno,
                "hand-built RPC envelope ({'seq', 'method', ...} dict"
                " literal) skips RPCClient.call, so it carries no"
                " TraceContext — the receiving span becomes a trace root"
                " and the stitched tree splits",
            ))
