"""shared-state-discipline: whole-program shared-state race analysis.

nomad-race's static side, built on the same interprocedural engine as
``lock-order`` (one :class:`WholeProgramLockAnalysis` instance is shared
across the three concurrency rules). The pass:

1. **Inventories thread-entry roots** — the places a new flow of control
   starts: ``threading.Thread(target=f)`` / ``threading.Timer(.., f)``
   spawns (through lambdas and ``functools.partial`` too), executor
   ``.submit(f)``, the server's ``_schedule_leader_task(gen, iv, f)``
   leader tasks, RPC ``register("Svc.method", handler)`` dispatch
   handlers, and future ``add_done_callback(cb)`` hooks. A root spawned
   inside a loop (worker pools) or from two call sites is concurrent
   with itself.

2. **Propagates** root reachability over the call graph: every unit
   learns which roots can be on its stack.

3. **Infers shared state**: a class attribute (or module-level global)
   is *shared* when units reachable from >= 2 concurrent roots access it
   (one self-concurrent root counts). Synchronization objects (locks,
   conditions, events, queues, thread handles) are exempt, as are
   attributes of classes that declare no lock at all — those are data
   objects whose ownership is transferred through queues; the runtime
   race witness covers them dynamically.

4. **Proves every write** (plain/augmented assignment, ``del``, and
   mutating container method calls — the subscript chain root counts as
   the written attribute) to an inferred-shared attribute happens under
   a held lock of the owning class: a lexical ``with``, the
   ``*_locked`` naming convention, or the all-call-sites-held proof
   (``notify_held``) borrowed from condition-discipline. ``__init__``
   writes are exempt (thread start is a happens-before edge).

5. Keeps ``# guarded-by: <lockname>`` annotations as **authoritative
   guard declarations** (subsuming the old annotation-only
   ``lock-discipline`` rule): writes to an annotated attribute — by
   NAME, on any receiver — must hold the named lock, root-reachable or
   not, and are reported once (never double-reported by the inferred
   path).

Findings are suppressed line-by-line with ``# race-ok: <reason>`` — a
reasoned claim (single-writer, immutable-after-init, torn-read-benign)
that feeds the ratchet: a ``race-ok`` that no longer suppresses
anything is itself a finding, so stale claims can't linger. Messages
carry no line numbers, so baseline entries survive drift.

``build_static_shared()`` exposes the inferred-shared key set (same
``module.Class.attr`` namespace as the lock inventory and the
``tracked_*`` container factories in ``utils/race_witness.py``) to the
runtime witness's teardown cross-check: every field the Eraser witness
saw touched by >= 2 threads must be in this set, which makes a
witness-armed stress run a soundness test for the root inventory.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, ParsedModule, dotted_name
from .lock_order import (
    WholeProgramLockAnalysis,
    _Class,
    _FALLBACK_DENY,
    _Mod,
    _Unit,
)

RULE = "shared-state-discipline"

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")
_RACE_OK_RE = re.compile(r"#\s*race-ok:(.*)$")

# container methods that mutate the receiver in place
_MUTATING_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "update", "setdefault", "add", "sort", "reverse", "rotate",
})

# constructor tails that mint synchronization (or thread-handle) objects:
# writes to attributes holding these are lifecycle management, not data
_SYNC_TAILS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "LifoQueue", "PriorityQueue",
    "SimpleQueue", "BoundedStageQueue", "Thread", "Timer",
    "ThreadPoolExecutor", "local",
    "witness_lock", "witness_rlock", "witness_condition",
    "module_witness_lock", "module_witness_rlock",
})

_TRACKED_FACTORIES = frozenset({"tracked_dict", "tracked_list",
                                "tracked_deque"})

_ALL_CAPS_RE = re.compile(r"^_?[A-Z0-9_]+$")


def _base_attribute(target: ast.AST) -> Optional[ast.Attribute]:
    """The Attribute at the root of a write target: ``x.a`` for ``x.a``,
    ``x.a[k]`` and ``x.a[k][j]``; None for plain names."""
    cur = target
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    return cur if isinstance(cur, ast.Attribute) else None


def _base_name(target: ast.AST) -> Optional[ast.Name]:
    """The Name at the root of a subscripted write target."""
    cur = target
    while isinstance(cur, ast.Subscript):
        cur = cur.value
    return cur if isinstance(cur, ast.Name) else None


class _Access:
    __slots__ = ("key", "owner", "attr", "unit", "lineno", "held",
                 "kind", "is_self", "pseudo")

    def __init__(self, key: str, owner, attr: str, unit: _Unit,
                 lineno: int, held: Tuple[str, ...], kind: str,
                 is_self: bool) -> None:
        self.key = key
        self.owner = owner          # _Class, or _Mod for module globals
        self.attr = attr
        self.unit = unit
        self.lineno = lineno
        self.held = held            # resolved keys + "?<name>" pseudo entries
        self.kind = kind            # read|write|rmw|del|mutate
        self.is_self = is_self


class SharedStateDisciplineChecker:
    rule = RULE

    def __init__(self,
                 analysis: Optional[WholeProgramLockAnalysis] = None) -> None:
        self.analysis = analysis or WholeProgramLockAnalysis()
        # guarded-by annotations (ported from the old lock-discipline rule)
        self.guarded: Dict[str, str] = {}               # attr -> lockname
        self.declaring: Set[Tuple[str, str, str]] = set()
        self.decl_lines: Set[Tuple[str, int]] = set()
        # race-ok suppressions: (rel, lineno) -> reason
        self._race_ok: Dict[Tuple[str, int], str] = {}
        self._findings: Optional[List[Finding]] = None
        # outputs for build_static_shared / diagnostics
        self.shared_keys: Set[str] = set()
        self.root_inventory: Dict[str, bool] = {}       # qual -> self-concurrent

    # -- pass 1: cross-module facts --------------------------------------

    def collect(self, module: ParsedModule) -> None:
        self.analysis.add_module(module)
        # real COMMENT tokens only — docstrings that *mention* race-ok
        # (like this module's) must not register as suppressions
        try:
            reader = io.StringIO("\n".join(module.lines) + "\n").readline
            for tok in tokenize.generate_tokens(reader):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _RACE_OK_RE.search(tok.string)
                if m is not None:
                    self._race_ok[(module.rel, tok.start[0])] = \
                        m.group(1).strip()
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    line = module.lines[node.lineno - 1] \
                        if node.lineno <= len(module.lines) else ""
                    m = _GUARDED_RE.search(line)
                    if m:
                        self.guarded[tgt.attr] = m.group(1)
                        self.declaring.add((module.rel, cls.name, tgt.attr))
                        self.decl_lines.add((module.rel, node.lineno))

    # -- inventories -----------------------------------------------------

    def _prepass(self) -> None:
        """Per-class assigned/sync/tracked-attr sets and per-module
        mutable-global inventories."""
        self._assigned: Dict[int, Set[str]] = {}     # id(_Class) -> attrs
        self._sync: Dict[int, Set[str]] = {}
        self._tracked: Dict[Tuple[int, str], str] = {}  # (id, attr) -> key
        self._mod_globals: Dict[Tuple[str, ...], Set[str]] = {}
        self._mod_tracked: Dict[Tuple[str, ...], Dict[str, str]] = {}

        for mod in self.analysis.mods.values():
            for cls in mod.classes.values():
                assigned = self._assigned.setdefault(id(cls), set())
                sync = self._sync.setdefault(id(cls), set())
                for node in ast.walk(cls.node):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for tgt in targets:
                        if not (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            continue
                        assigned.add(tgt.attr)
                        if isinstance(node.value, ast.Call):
                            name = dotted_name(node.value.func) or ""
                            tail = name.rsplit(".", 1)[-1]
                            if tail in _SYNC_TAILS:
                                sync.add(tgt.attr)
                            elif tail in _TRACKED_FACTORIES:
                                lit = WholeProgramLockAnalysis._literal_arg(
                                    node.value)
                                if lit:
                                    self._tracked[(id(cls), tgt.attr)] = lit
                # sync objects published through a local
                # (``t = Thread(...); self._thread = t``)
                for meth in cls.methods.values():
                    local_sync: Set[str] = set()
                    for node in ast.walk(meth.node):
                        if not (isinstance(node, ast.Assign)
                                and isinstance(node.value, ast.Call)):
                            continue
                        name = dotted_name(node.value.func) or ""
                        if name.rsplit(".", 1)[-1] not in _SYNC_TAILS:
                            continue
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                local_sync.add(tgt.id)
                    if not local_sync:
                        continue
                    for node in ast.walk(meth.node):
                        if not (isinstance(node, ast.Assign)
                                and isinstance(node.value, ast.Name)
                                and node.value.id in local_sync):
                            continue
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Attribute) \
                                    and isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                sync.add(tgt.attr)
                sync.update(cls.attr_locks)
                sync.update(cls.attr_conds)

            # module-level mutable globals: container literals, tracked
            # factories, and scalars rebound via `global` in some unit
            names: Set[str] = set()
            tracked: Dict[str, str] = {}
            global_decls: Set[str] = set()
            for u in list(mod.funcs.values()) + [
                    m for c in mod.classes.values() for m in c.methods.values()]:
                for node in ast.walk(u.node):
                    if isinstance(node, ast.Global):
                        global_decls.update(node.names)
            for node in mod.pm.tree.body:
                tgt_name = None
                value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt_name, value = node.targets[0].id, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    tgt_name, value = node.target.id, node.value
                if tgt_name is None:
                    continue
                if _ALL_CAPS_RE.match(tgt_name) \
                        or (tgt_name.startswith("__")
                            and tgt_name.endswith("__")) \
                        or tgt_name in mod.mod_locks \
                        or tgt_name in mod.mod_conds \
                        or tgt_name in mod.tables:
                    continue
                is_container = isinstance(value, (
                    ast.Dict, ast.DictComp, ast.List, ast.ListComp,
                    ast.Set, ast.SetComp))
                if isinstance(value, ast.Call):
                    tail = (dotted_name(value.func) or "").rsplit(".", 1)[-1]
                    if tail in {"deque", "defaultdict", "OrderedDict",
                                "Counter"}:
                        is_container = True
                    elif tail in _TRACKED_FACTORIES:
                        lit = WholeProgramLockAnalysis._literal_arg(value)
                        if lit:
                            is_container = True
                            tracked[tgt_name] = lit
                if is_container or tgt_name in global_decls:
                    names.add(tgt_name)
            self._mod_globals[mod.parts] = names
            self._mod_tracked[mod.parts] = tracked

    def _canon(self, owner, attr: str) -> str:
        """Canonical key for an attribute access — declared-tracked
        literal if present, else ``stem.Class.attr`` on the DECLARING
        class (first in the MRO chain that assigns it)."""
        if isinstance(owner, _Mod):
            lit = self._mod_tracked.get(owner.parts, {}).get(attr)
            return lit or f"{owner.stem}.{attr}"
        for c in self.analysis._cls_chain(owner):
            if attr in self._assigned.get(id(c), ()):
                lit = self._tracked.get((id(c), attr))
                return lit or f"{c.mod.stem}.{c.name}.{attr}"
        return f"{owner.mod.stem}.{owner.name}.{attr}"

    def _is_exempt_attr(self, owner, attr: str) -> bool:
        if attr.startswith("__") and attr.endswith("__"):
            return True
        if isinstance(owner, _Mod):
            return False
        for c in self.analysis._cls_chain(owner):
            if attr in self._sync.get(id(c), ()):
                return True
            if attr in c.methods:
                return True
        return False

    def _owner_locks(self, owner) -> Dict[str, str]:
        """lockname -> lock key candidates of the owning class/module."""
        out: Dict[str, str] = {}
        if isinstance(owner, _Mod):
            for name, key in owner.mod_locks.items():
                out.setdefault(name, key)
            for name, key in owner.mod_conds.items():
                out.setdefault(name, key)
            return out
        for c in self.analysis._cls_chain(owner):
            for name, key in c.attr_locks.items():
                out.setdefault(name, key)
            for name, key in c.attr_conds.items():
                out.setdefault(name, key)
        return out

    _CTOR_NAMES = frozenset({
        "__init__", "__new__", "__setstate__", "__post_init__"})

    def _ctor_only(self, unit: _Unit, _depth: int = 0) -> bool:
        """True when ``unit`` runs only on the construction path: it IS a
        constructor-family method, or every call site (per the shared
        call graph) is a ctor-only method of the same class. Writes there
        happen-before the object is published to other threads, exactly
        like writes lexically inside ``__init__``."""
        if unit.cls is None:
            return False
        if unit.qual.rsplit(".", 1)[-1] in self._CTOR_NAMES:
            return True
        if _depth >= 3:
            return False
        sites = self.analysis.callers.get(unit)
        if not sites:
            return False
        return all(caller.cls is unit.cls
                   and self._ctor_only(caller, _depth + 1)
                   for caller, _held in sites)

    # -- local typing (light version of lock_order's prescan) ------------

    def _local_types(self, unit: _Unit) -> Dict[str, _Class]:
        lt: Dict[str, _Class] = {}
        args = getattr(unit.node, "args", None)
        if args is not None:
            for a in (list(getattr(args, "posonlyargs", []))
                      + list(args.args) + list(args.kwonlyargs)):
                if a.annotation is None:
                    continue
                for name in WholeProgramLockAnalysis._ann_names(a.annotation):
                    c = self.analysis._class_by_name(name, unit.mod)
                    if c is not None:
                        lt.setdefault(a.arg, c)
                        break
        for node in ast.walk(unit.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ctor = dotted_name(node.value.func)
                if ctor:
                    c = self.analysis._class_by_name(ctor, unit.mod)
                    if c is not None:
                        lt.setdefault(node.targets[0].id, c)
        return lt

    # -- thread-entry roots ----------------------------------------------

    def _is_threading(self, name: str, mod: _Mod) -> bool:
        head = name.split(".", 1)[0]
        if head in {"threading", "_threading"}:
            return True
        ali = mod.aliases.get(head)
        if ali is None:
            return False
        if ali[0] == "mod" and ali[1][:1] == ("threading",):
            return True
        if ali[0] == "from" and ali[1][:1] == ("threading",):
            return True
        return False

    def _callable_targets(self, expr: Optional[ast.AST], unit: _Unit,
                          lt: Dict[str, _Class]) -> List[_Unit]:
        if expr is None:
            return []
        if isinstance(expr, ast.Lambda):
            out: List[_Unit] = []
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call):
                    out.extend(self.analysis.resolve_call(node, unit, lt))
            return out
        if isinstance(expr, ast.Call):
            tail = (dotted_name(expr.func) or "").rsplit(".", 1)[-1]
            if tail == "partial" and expr.args:
                return self._callable_targets(expr.args[0], unit, lt)
            return []
        return self.analysis._resolve_callable_ref(expr, unit)

    def _spawn_targets(self, call: ast.Call, unit: _Unit,
                       lt: Dict[str, _Class]) -> List[_Unit]:
        f = call.func
        name = dotted_name(f) or ""
        tail = name.rsplit(".", 1)[-1]
        kws = {k.arg: k.value for k in call.keywords if k.arg}
        if tail == "Thread" and self._is_threading(name, unit.mod):
            return self._callable_targets(kws.get("target"), unit, lt)
        if tail == "Timer" and self._is_threading(name, unit.mod):
            fn = kws.get("function") or (
                call.args[1] if len(call.args) > 1 else None)
            return self._callable_targets(fn, unit, lt)
        if not isinstance(f, ast.Attribute):
            return []
        if f.attr == "submit" and call.args:
            return self._callable_targets(call.args[0], unit, lt)
        if f.attr == "_schedule_leader_task" and len(call.args) >= 3:
            return self._callable_targets(call.args[2], unit, lt)
        if f.attr == "add_done_callback" and call.args:
            return self._callable_targets(call.args[0], unit, lt)
        if f.attr == "register" and len(call.args) >= 2 \
                and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            return self._callable_targets(call.args[1], unit, lt)
        return []

    def _scan_roots(self) -> Dict[int, Tuple[_Unit, bool]]:
        """unit id -> (unit, self-concurrent?) for every thread-entry
        root. Self-concurrent: spawned inside a loop or from >= 2 sites."""
        roots: Dict[int, Tuple[_Unit, bool]] = {}

        def add(targets: List[_Unit], multi: bool) -> None:
            for t in targets:
                prev = roots.get(id(t))
                # a second spawn site makes the root self-concurrent
                roots[id(t)] = (t, multi if prev is None else True)

        for u in self.analysis._units:
            lt = self._local_types(u)

            def walk(node: ast.AST, in_loop: bool) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    child_loop = in_loop or isinstance(
                        child, (ast.For, ast.AsyncFor, ast.While))
                    if isinstance(child, ast.Call):
                        targets = self._spawn_targets(child, u, lt)
                        if targets:
                            add(targets, child_loop)
                    walk(child, child_loop)

            walk(u.node, False)

        # socketserver request handlers: a ThreadingTCPServer runs
        # Handler.handle on a fresh thread per accepted connection.
        # Handler classes nested inside functions are not call-graph
        # units, so root what their method bodies call instead —
        # uniquely-named methods (deny-listed protocol names excluded)
        # and same-module functions.
        for mod in self.analysis.mods.values():
            for node in ast.walk(mod.pm.tree):
                if not isinstance(node, ast.ClassDef) or not any(
                        (dotted_name(b) or "").rsplit(".", 1)[-1]
                        .endswith("RequestHandler") for b in node.bases):
                    continue
                for sub in node.body:
                    if not isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                        continue
                    for call in ast.walk(sub):
                        if not isinstance(call, ast.Call):
                            continue
                        f = call.func
                        if isinstance(f, ast.Attribute):
                            if f.attr in _FALLBACK_DENY:
                                continue
                            cands = self.analysis._method_index.get(
                                f.attr, [])
                            if len(cands) == 1:
                                add(cands, True)
                        elif isinstance(f, ast.Name):
                            u2 = mod.funcs.get(f.id)
                            if u2 is not None:
                                add([u2], True)
        return roots

    # -- access walk -----------------------------------------------------

    def _attr_access_owner(self, attr_node: ast.Attribute, unit: _Unit,
                           lt: Dict[str, _Class]):
        """(owner, is_self) for ``<base>.<attr>`` — owner is a _Class, a
        _Mod (module-global via alias), or None when unresolvable."""
        base = attr_node.value
        cls, mod = unit.cls, unit.mod
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                return cls, True
            t = lt.get(base.id)
            if t is not None:
                return t, False
            m2 = self.analysis._module_of_alias(mod, base.id)
            if m2 is not None and attr_node.attr in self._mod_globals.get(
                    m2.parts, ()):
                return m2, False
        elif isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and cls is not None:
            t = self.analysis._attr_type(cls, base.attr)
            if t is not None:
                return t, False
        return None, False

    def _walk_unit(self, unit: _Unit, record) -> None:
        lt = self._local_types(unit)
        mod = unit.mod
        globals_declared = {
            n for node in ast.walk(unit.node)
            if isinstance(node, ast.Global) for n in node.names
        }
        mod_names = self._mod_globals.get(mod.parts, set())

        def rec_attr(attr_node: ast.Attribute, lineno: int,
                     held: Tuple[str, ...], kind: str) -> None:
            owner, is_self = self._attr_access_owner(attr_node, unit, lt)
            if owner is None:
                # guarded-by stays name-based: the annotation is
                # authoritative wherever the attr name appears, even on
                # receivers the light typing cannot resolve
                if attr_node.attr in self.guarded and kind != "read":
                    key = dotted_name(attr_node) or attr_node.attr
                    record(_Access(key, None, attr_node.attr, unit,
                                   lineno, held, kind, False))
                return
            if self._is_exempt_attr(owner, attr_node.attr):
                return
            key = self._canon(owner, attr_node.attr)
            record(_Access(key, owner, attr_node.attr, unit, lineno, held,
                           kind, is_self))

        def rec_global(name: str, lineno: int, held: Tuple[str, ...],
                       kind: str) -> None:
            key = self._mod_tracked.get(mod.parts, {}).get(name) \
                or f"{mod.stem}.{name}"
            record(_Access(key, mod, name, unit, lineno, held, kind, False))

        def handle_target(t: ast.AST, kind: str, lineno: int,
                          held: Tuple[str, ...]) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    handle_target(e, kind, lineno, held)
                return
            attr = _base_attribute(t)
            if attr is not None:
                rec_attr(attr, lineno, held, kind)
                return
            nm = _base_name(t)
            if nm is None:
                return
            if isinstance(t, ast.Subscript):
                # item assignment mutates the container, no `global` needed
                if nm.id in mod_names:
                    rec_global(nm.id, lineno, held, kind)
            elif nm.id in mod_names and nm.id in globals_declared:
                rec_global(nm.id, lineno, held, kind)

        def resolve_with(expr: ast.AST) -> Optional[str]:
            key = self.analysis.resolve_lock_expr(expr, unit, lt)
            if key is not None:
                return key
            if isinstance(expr, ast.Attribute):
                return "?" + expr.attr
            if isinstance(expr, ast.Name):
                return "?" + expr.id
            return None

        def block(nodes, held: Tuple[str, ...]) -> None:
            for node in nodes:
                if node is None or isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    nh = held
                    for item in node.items:
                        block([item.context_expr], nh)
                        k = resolve_with(item.context_expr)
                        if k is not None and k not in nh:
                            nh = nh + (k,)
                    block(node.body, nh)
                    continue
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        handle_target(t, "write", node.lineno, held)
                elif isinstance(node, ast.AugAssign):
                    handle_target(node.target, "rmw", node.lineno, held)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    handle_target(node.target, "write", node.lineno, held)
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        handle_target(t, "del", node.lineno, held)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _MUTATING_METHODS \
                        and not self.analysis.resolve_call(node, unit, lt):
                    # a receiver whose class defines this method is a
                    # CALL (the graph walks into it), not a container
                    # mutation — `self.periodic_dispatcher.add(job)`
                    recv = node.func.value
                    if isinstance(recv, ast.Attribute):
                        rec_attr(recv, node.lineno, held, "mutate")
                    elif isinstance(recv, ast.Name) \
                            and recv.id in mod_names:
                        rec_global(recv.id, node.lineno, held, "mutate")
                elif isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load):
                    rec_attr(node, node.lineno, held, "read")
                elif isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in mod_names:
                    rec_global(node.id, node.lineno, held, "read")
                block(ast.iter_child_nodes(node), held)

        block(ast.iter_child_nodes(unit.node), ())

    # -- the analysis ----------------------------------------------------

    def _compute(self) -> List[Finding]:
        if self._findings is not None:
            return self._findings
        an = self.analysis
        an.analyze()
        self._prepass()

        # roots + reachability
        roots = self._scan_roots()
        ordered_roots = sorted(roots.values(), key=lambda rm: rm[0].qual)
        self.root_inventory = {u.qual: multi for u, multi in ordered_roots}
        unit_roots: Dict[int, Set[int]] = {}
        multi_idx: Set[int] = set()
        for idx, (r, multi) in enumerate(ordered_roots):
            if multi:
                multi_idx.add(idx)
            stack, seen = [r], {id(r)}
            while stack:
                u = stack.pop()
                unit_roots.setdefault(id(u), set()).add(idx)
                for targets, _ln, _held in u.calls:
                    for t in targets:
                        if id(t) not in seen:
                            seen.add(id(t))
                            stack.append(t)

        # all accesses
        accesses: List[_Access] = []
        for u in an._units:
            self._walk_unit(u, accesses.append)

        # sharing inference
        key_roots: Dict[str, Set[int]] = {}
        key_root_names: Dict[str, Set[str]] = {}
        for a in accesses:
            rs = unit_roots.get(id(a.unit))
            if not rs:
                continue
            key_roots.setdefault(a.key, set()).update(rs)
            names = key_root_names.setdefault(a.key, set())
            for i in rs:
                names.add(ordered_roots[i][0].qual)
        shared: Set[str] = set()
        for key, rs in key_roots.items():
            if len(rs) >= 2 or (rs and rs & multi_idx):
                shared.add(key)
        # guarded-by declarations are shared by fiat
        for a in accesses:
            if a.attr in self.guarded and a.key not in shared:
                shared.add(a.key)
        self.shared_keys = shared

        findings: List[Finding] = []
        used_race_ok: Set[Tuple[str, int]] = set()

        def suppressed(rel: str, lineno: int, pending: Finding) -> bool:
            reason = self._race_ok.get((rel, lineno))
            if reason is None:
                return False
            used_race_ok.add((rel, lineno))
            if not reason:
                findings.append(Finding(
                    RULE, rel, lineno,
                    "'# race-ok' suppression needs a reason "
                    "(e.g. '# race-ok: single writer, torn reads benign')"))
            return True

        def held_names(held: Tuple[str, ...]) -> Set[str]:
            out = set()
            for h in held:
                out.add(h[1:] if h.startswith("?") else h.rsplit(".", 1)[-1])
            return out

        for a in accesses:
            if a.kind == "read":
                continue
            rel = a.unit.mod.pm.rel
            lex_names = held_names(a.held)
            resolved_held = tuple(h for h in a.held if not h.startswith("?"))
            # 1) guarded-by annotations: authoritative, name-based, and
            #    enforced whether or not a root reaches the write
            if a.attr in self.guarded:
                is_decl_scope = a.is_self and a.unit.cls is not None and (
                    rel, a.unit.cls.name, a.attr) in self.declaring
                if a.is_self and not is_decl_scope:
                    continue  # an unrelated class's same-named attr
                if (rel, a.lineno) in self.decl_lines:
                    continue  # the annotated declaration itself
                lock = self.guarded[a.attr]
                ok = lock in lex_names
                if not ok and not isinstance(a.owner, _Mod):
                    lock_key = self.analysis._attr_lock_key(a.owner, lock) \
                        if isinstance(a.owner, _Class) else None
                    if lock_key is not None:
                        ok = self.analysis.notify_held(
                            a.unit, lock_key, resolved_held)
                if not ok:
                    f = Finding(
                        RULE, rel, a.lineno,
                        f"write to '{a.key}' (guarded-by {lock}) outside "
                        f"a 'with ....{lock}:' block")
                    if not suppressed(rel, a.lineno, f):
                        findings.append(f)
                continue
            # 2) inferred sharing: only for attrs of lock-owning classes
            if a.key not in shared:
                continue
            locks = self._owner_locks(a.owner)
            if not locks:
                continue  # lockless data object: runtime witness territory
            if a.is_self and self._ctor_only(a.unit):
                continue  # construction (incl. unpickle) happens-before
                # the object is published to other threads; covers
                # ctor-path helpers (__init__ -> _load_persistent)
            ok = any(k in resolved_held for k in locks.values()) \
                or any(n in lex_names for n in locks) \
                or any(self.analysis.notify_held(a.unit, k, resolved_held)
                       for k in locks.values())
            if ok:
                continue
            rnames = sorted(key_root_names.get(a.key, ()))
            rdesc = ", ".join(rnames[:3]) + (
                f" +{len(rnames) - 3} more" if len(rnames) > 3 else "")
            ldesc = " or ".join(sorted(set(locks.values())))
            f = Finding(
                RULE, rel, a.lineno,
                f"unguarded {a.kind} to shared state '{a.key}' in "
                f"{a.unit.qual} (reachable from concurrent roots: {rdesc}); "
                f"hold {ldesc}, use a *_locked helper, or annotate "
                f"'# race-ok: <reason>'")
            if not suppressed(rel, a.lineno, f):
                findings.append(f)

        # 3) the ratchet: a race-ok that suppresses nothing is stale
        for (rel, lineno), _reason in sorted(self._race_ok.items()):
            if (rel, lineno) not in used_race_ok:
                findings.append(Finding(
                    RULE, rel, lineno,
                    "stale '# race-ok' suppression: no shared-state "
                    "finding is suppressed on this line"))

        # one finding per (file, line, message): the walker can reach the
        # same write through e.g. tuple targets
        seen_f: Set[Tuple[str, int, str]] = set()
        deduped: List[Finding] = []
        for f in sorted(findings, key=lambda f: (f.file, f.line, f.message)):
            k = (f.file, f.line, f.message)
            if k not in seen_f:
                seen_f.add(k)
                deduped.append(f)
        self._findings = deduped
        return deduped

    def check(self, module: ParsedModule) -> List[Finding]:
        return [f for f in self._compute() if f.file == module.rel]


# -- the witness cross-check entry point ------------------------------------

_STATIC_CACHE: Dict[str, Set[str]] = {}


def build_static_shared(root: Optional[str] = None) -> Set[str]:
    """Whole-tree inferred-shared key set, for the race witness's
    teardown cross-check. ``root`` defaults to the installed
    ``nomad_tpu`` package; results are cached per root."""
    from .core import iter_py_files, parse_file

    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.abspath(root)
    cached = _STATIC_CACHE.get(root)
    if cached is not None:
        return cached
    checker = SharedStateDisciplineChecker()
    base = os.path.dirname(root)
    for path in iter_py_files([root]):
        rel = os.path.relpath(path, base).replace(os.sep, "/")
        pm, _err = parse_file(path, rel)
        if pm is not None:
            checker.collect(pm)
    checker._compute()
    keys = set(checker.shared_keys)
    _STATIC_CACHE[root] = keys
    return keys
