"""subprocess-discipline: spawned server processes are bounded and reaped.

The crash-recovery harness (``nomad_tpu/chaos/crash.py``) and its tests
spawn real server OS processes. A child process is a resource Python
will not collect: an un-reaped ``Popen`` is a zombie holding its data
dir, an unbounded ``wait()`` on an unkillable child wedges the whole
test run, and a ``subprocess.run`` without a timeout turns one stuck
server into a hung CI job. Three rules, enforced over the code that
spawns processes (the chaos package, tests, and bench drivers):

1. **Blocking one-shot helpers carry an explicit ``timeout=``** —
   ``subprocess.run`` / ``call`` / ``check_call`` / ``check_output``
   with no timeout blocks forever on a wedged child.
2. **``<proc>.wait()`` carries an explicit ``timeout=``** — an
   unbounded reap after SIGKILL still hangs when the child is stuck in
   uninterruptible sleep; bound it and let ``TimeoutExpired`` surface.
3. **Every ``Popen`` is owned** — either assigned to an attribute of a
   class that also defines a reap method (``terminate`` / ``kill`` /
   ``close`` / ``stop``, the :class:`~nomad_tpu.chaos.crash.ServerProcess`
   pattern), or created in a function whose ``finally`` reaps it
   (``terminate``/``kill``/``wait``). A bare local ``Popen`` leaks the
   child on the first exception between spawn and reap.

Scope: ``nomad_tpu/chaos/``, test files, and bench drivers — harness
code, where a leaked child outlives the scenario and poisons the next
one. Client task drivers (``client/drivers/``, logmon, plugin
transports) spawn workloads as their actual job and manage lifecycles
through their own handle/recover machinery; they are out of scope here.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import Finding, ParsedModule, dotted_name, import_aliases, resolve_call_name

RULE = "subprocess-discipline"

_ONESHOT = {
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
}
_POPEN = "subprocess.Popen"
_REAP_METHODS = ("terminate", "kill", "kill_hard", "close", "stop", "wait")
# receiver-name hints for rule 2: `.wait()` on something process-shaped
# (never on locks/events — their wait() is the one with different rules)
_PROC_HINTS = ("proc", "popen", "child", "pgm", "server_process")


def _norm(rel: str) -> str:
    return rel.replace("\\", "/")


def _is_test_file(rel: str) -> bool:
    rel = _norm(rel)
    base = rel.rsplit("/", 1)[-1]
    return "tests/" in rel or base.startswith("test_") or base == "conftest.py"


def _spawn_scope(rel: str) -> bool:
    """Files allowed to spawn processes (and held to rules 1-3)."""
    rel = _norm(rel)
    base = rel.rsplit("/", 1)[-1]
    return (
        "nomad_tpu/chaos/" in rel
        or rel.startswith("chaos/")
        or _is_test_file(rel)
        or base.startswith("bench")
    )


def _proc_receiver(func: ast.expr) -> bool:
    recv = dotted_name(func)
    if recv is None:
        return False
    recv = recv.lower()
    head = recv.rsplit(".", 2)
    owner = head[-2] if len(head) >= 2 else recv
    return any(h in owner for h in _PROC_HINTS) or owner == "p"


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg == "timeout" for kw in call.keywords)


class SubprocessDisciplineChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        if not _spawn_scope(module.rel):
            return []
        aliases = import_aliases(module.tree)
        findings: List[Finding] = []
        findings.extend(self._check_oneshot_timeouts(module, aliases))
        findings.extend(self._check_wait_timeouts(module))
        findings.extend(self._check_popen_owned(module, aliases))
        return findings

    # -- rule 1: one-shot helpers are bounded ----------------------------

    def _check_oneshot_timeouts(self, module: ParsedModule,
                                aliases: Dict[str, str]) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = resolve_call_name(node.func, aliases)
            if name in _ONESHOT and not _has_timeout_kw(node):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    f"'{name}' without timeout=: a wedged child blocks "
                    f"this call forever — pass an explicit timeout and "
                    f"handle TimeoutExpired",
                ))
        return findings

    # -- rule 2: reaps are bounded ---------------------------------------

    def _check_wait_timeouts(self, module: ParsedModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"
                    and _proc_receiver(node.func)):
                continue
            if not _has_timeout_kw(node):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "process .wait() without timeout=: even after SIGKILL "
                    "a child stuck in uninterruptible sleep hangs an "
                    "unbounded reap — pass timeout= and surface "
                    "TimeoutExpired",
                ))
        return findings

    # -- rule 3: every Popen is owned ------------------------------------

    def _check_popen_owned(self, module: ParsedModule,
                           aliases: Dict[str, str]) -> List[Finding]:
        findings: List[Finding] = []

        # classes that define a reap method: their methods may assign
        # Popen to self.<attr> (instance-managed lifecycle)
        reaping_classes: Set[int] = set()
        class_of_node: Dict[int, int] = {}
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if any(isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and m.name in _REAP_METHODS for m in cls.body):
                reaping_classes.add(id(cls))
            for sub in ast.walk(cls):
                class_of_node.setdefault(id(sub), id(cls))

        func_of_node: Dict[int, ast.AST] = {}
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    func_of_node.setdefault(id(sub), fn)

        def finally_reaps(fn: Optional[ast.AST]) -> bool:
            if fn is None:
                return False
            for t in ast.walk(fn):
                if not isinstance(t, ast.Try):
                    continue
                for stmt in t.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func, ast.Attribute) \
                                and sub.func.attr in _REAP_METHODS:
                            return True
            return False

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not (isinstance(call, ast.Call)
                    and resolve_call_name(call.func, aliases) == _POPEN):
                continue
            self_attr = any(
                isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
                and t.value.id == "self" for t in node.targets
            )
            if self_attr and class_of_node.get(id(node)) in reaping_classes:
                continue
            if finally_reaps(func_of_node.get(id(node))):
                continue
            findings.append(Finding(
                RULE, module.rel, node.lineno,
                "Popen not owned: assign it to an attribute of a class "
                "with a reap method (terminate/kill/close/stop), or reap "
                "it in this function's 'finally' — a bare local Popen "
                "leaks the child on the first exception",
            ))

        # a Popen used as a bare expression (not even assigned) is always
        # unreaped
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call) \
                    and resolve_call_name(node.value.func, aliases) == _POPEN:
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "Popen result discarded: the process can never be "
                    "reaped — keep the handle and reap it",
                ))
        return findings
