"""trace-span-discipline: span regions must be exception-safe.

The trace layer's invariants (worker ``current`` always restored, phase
spans always closed, lifecycle stamps never leaked open) all hang on one
structural property: a span factory's return value is a context manager
whose ``__exit__`` runs on EVERY exit path. That holds exactly when the
call site is

  - the context expression of a ``with`` statement
    (``with phases.track("rank"): ...``,
    ``with self._span("invoke_scheduler", eid): ...``), or
  - the sole argument of an ``ExitStack.enter_context(...)`` call
    (the stack's own ``with`` provides the try/finally).

Anything else — a bare statement call that discards the manager, storing
the manager in a variable for a manual ``__enter__()``/``__exit__()``
dance, passing it somewhere that may never enter it — leaves a path
where an exception (or an early ``return``) skips ``__exit__``: the
phase stays "open" forever, the watchdog reports a worker parked in a
span it left minutes ago, and ``coverage()`` double-counts.

Span factories are recognized syntactically: a call whose resolved
dotted name ends in ``phases.track`` (any alias — ``_phases.track``,
``nomad_tpu.utils.phases.track``), or an attribute call named ``_span``
(the Worker span helper's naming convention).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional

from .core import Finding, ParsedModule, import_aliases, resolve_call_name

RULE = "trace-span-discipline"


def _is_span_factory(call: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    """The display name of the span factory being called, or None."""
    if isinstance(call.func, ast.Attribute) and call.func.attr == "_span":
        return "._span"
    name = resolve_call_name(call.func, aliases)
    if name is None:
        return None
    parts = name.split(".")
    # relative imports (`from ..utils import phases as _phases`) are not
    # in the alias map, so match on the trailing segments: `<...>.track`
    # where the module segment is phases-like
    if len(parts) >= 2 and parts[-1] == "track" \
            and parts[-2].lstrip("_") == "phases":
        return name
    return None


class TraceSpanDisciplineChecker:
    rule = RULE

    def check(self, module: ParsedModule) -> List[Finding]:
        aliases = import_aliases(module.tree)

        # pass 1: collect the call nodes sitting in a legal position
        ok = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ok.add(id(item.context_expr))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "enter_context" \
                    and len(node.args) == 1 and not node.keywords:
                ok.add(id(node.args[0]))

        # pass 2: every span-factory call outside those positions leaks
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or id(node) in ok:
                continue
            name = _is_span_factory(node, aliases)
            if name is None:
                continue
            findings.append(Finding(
                RULE, module.rel, node.lineno,
                f"span factory '{name}' called outside a 'with' item or "
                f"enter_context(...): an exit path can skip __exit__ — "
                f"wrap it as 'with {name}(...):'",
            ))
        return findings
