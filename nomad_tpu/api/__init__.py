"""Standalone HTTP API SDK (reference api/ package — importable without the
rest of the framework; stdlib-only)."""

from .api import (
    APIError,
    Client,
    Config,
    QueryMeta,
    QueryOptions,
    WriteMeta,
    WriteOptions,
)

__all__ = [
    "APIError",
    "Client",
    "Config",
    "QueryMeta",
    "QueryOptions",
    "WriteMeta",
    "WriteOptions",
]
