"""Typed HTTP client SDK (reference api/api.go:371 Client and the per-noun
files api/jobs.go, api/nodes.go, api/allocations.go, api/evaluations.go,
api/deployments.go, api/acl.go, api/operator.go, api/agent.go, api/search.go).

The Go SDK is a standalone module importable without the rest of Nomad; this
package mirrors that: it depends only on the standard library (urllib) and
speaks the agent's Go-style wire JSON. Blocking queries work exactly like the
reference: pass ``QueryOptions(wait_index=...)`` and the request long-polls
until the server's index passes it, returning ``QueryMeta.last_index`` for the
next call.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class APIError(Exception):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"Unexpected response code: {code} ({message})")
        self.code = code
        self.message = message


@dataclass
class QueryOptions:
    namespace: str = ""
    region: str = ""
    prefix: str = ""
    auth_token: str = ""
    wait_index: int = 0
    wait_time: str = ""  # Go duration string, e.g. "5s"
    params: Dict[str, str] = field(default_factory=dict)


@dataclass
class WriteOptions:
    namespace: str = ""
    region: str = ""
    auth_token: str = ""


@dataclass
class QueryMeta:
    last_index: int = 0
    known_leader: bool = False
    request_time_ns: int = 0


@dataclass
class WriteMeta:
    last_index: int = 0


@dataclass
class Config:
    """Client configuration (reference api/api.go DefaultConfig)."""

    address: str = "http://127.0.0.1:4646"
    region: str = ""
    namespace: str = ""
    token: str = ""
    timeout: float = 65.0
    # mutual-TLS material for https:// addresses (reference api.go
    # TLSConfig; env NOMAD_CACERT / NOMAD_CLIENT_CERT / NOMAD_CLIENT_KEY)
    ca_cert: str = ""
    client_cert: str = ""
    client_key: str = ""
    # hostname verification is ON by default; cluster certs pinned to
    # "<role>.<region>.nomad" names need the explicit opt-out (the
    # reference CLI's -tls-skip-verify / api.TLSConfig.Insecure)
    tls_skip_verify: bool = False

    def ssl_context(self):
        if not self.address.startswith("https://"):
            return None
        cached = getattr(self, "_ssl_ctx", None)
        if cached is not None:
            return cached
        import ssl

        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.ca_cert:
            ctx.load_verify_locations(self.ca_cert)
        if self.client_cert and self.client_key:
            ctx.load_cert_chain(self.client_cert, self.client_key)
        if self.tls_skip_verify:
            ctx.check_hostname = False
        object.__setattr__(self, "_ssl_ctx", ctx)
        return ctx


class Client:
    """Entry point; exposes one sub-client per API noun (api.go:371)."""

    def __init__(self, config: Optional[Config] = None, **kw) -> None:
        self.config = config or Config(**kw)
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.alloc_fs = AllocFS(self)
        self.evaluations = Evaluations(self)
        self.deployments = Deployments(self)
        self.acl_policies = ACLPolicies(self)
        self.acl_tokens = ACLTokens(self)
        self.operator = Operator(self)
        self.agent = AgentAPI(self)
        self.system = System(self)
        self.status = Status(self)
        self.regions = Regions(self)
        self.search = Search(self)

    # -- plumbing ---------------------------------------------------------

    def _url(self, path: str, q: Optional[QueryOptions]) -> str:
        params: Dict[str, str] = {}
        ns = (q.namespace if q else "") or self.config.namespace
        if ns:
            params["namespace"] = ns
        region = (q.region if q else "") or self.config.region
        if region:
            params["region"] = region
        if q is not None:
            if q.prefix:
                params["prefix"] = q.prefix
            if q.wait_index:
                params["index"] = str(q.wait_index)
            if q.wait_time:
                params["wait"] = q.wait_time
            params.update(q.params)
        qs = urllib.parse.urlencode(params)
        return self.config.address + path + (f"?{qs}" if qs else "")

    def _do(
        self,
        method: str,
        path: str,
        body: Any = None,
        q: Optional[QueryOptions] = None,
        raw: bool = False,
    ) -> Tuple[Any, QueryMeta]:
        url = self._url(path, q)
        data = None
        if body is not None:
            data = body if isinstance(body, bytes) else json.dumps(body).encode()
        headers = {}
        token = (q.auth_token if q else "") or self.config.token
        if token:
            headers["X-Nomad-Token"] = token
        if data is not None:
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url, data=data, method=method, headers=headers)
        try:
            with urllib.request.urlopen(
                req, timeout=self.config.timeout, context=self.config.ssl_context()
            ) as resp:
                payload = resp.read()
                meta = QueryMeta(
                    last_index=int(resp.headers.get("X-Nomad-Index") or 0),
                    known_leader=resp.headers.get("X-Nomad-KnownLeader") == "true",
                )
                if raw:
                    return payload, meta
                text = payload.decode()
                return (json.loads(text) if text else None), meta
        except urllib.error.HTTPError as e:
            raise APIError(e.code, e.read().decode(errors="replace"))
        except urllib.error.URLError as e:
            raise APIError(0, str(e.reason))

    def get(self, path: str, q: Optional[QueryOptions] = None):
        return self._do("GET", path, None, q)

    def get_raw(self, path: str, q: Optional[QueryOptions] = None) -> bytes:
        """GET returning raw bytes (fs cat/readat/logs endpoints)."""
        payload, _ = self._do("GET", path, None, q, raw=True)
        return payload

    def put(self, path: str, body: Any = None, q: Optional[QueryOptions] = None):
        return self._do("PUT", path, body, q)

    def post(self, path: str, body: Any = None, q: Optional[QueryOptions] = None):
        return self._do("POST", path, body, q)

    def delete(self, path: str, q: Optional[QueryOptions] = None):
        return self._do("DELETE", path, None, q)


class _Sub:
    def __init__(self, client: Client) -> None:
        self.client = client


# ---------------------------------------------------------------------------
# Jobs (api/jobs.go)
# ---------------------------------------------------------------------------


class Jobs(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/jobs", q)

    def info(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}", q)

    def register(self, job: Dict[str, Any], q: Optional[QueryOptions] = None):
        return self.client.put("/v1/jobs", {"Job": job}, q)

    def deregister(self, job_id: str, purge: bool = False, q: Optional[QueryOptions] = None):
        q = q or QueryOptions()
        if purge:
            q.params["purge"] = "true"
        return self.client.delete(f"/v1/job/{job_id}", q)

    def parse_hcl(self, hcl: str, canonicalize: bool = True):
        out, _ = self.client.post(
            "/v1/jobs/parse", {"JobHCL": hcl, "Canonicalize": canonicalize}
        )
        return out

    def validate(self, job: Dict[str, Any], q: Optional[QueryOptions] = None):
        return self.client.put("/v1/validate/job", {"Job": job}, q)

    def plan(self, job: Dict[str, Any], diff: bool = True, q: Optional[QueryOptions] = None):
        return self.client.put(
            f"/v1/job/{job.get('ID', '')}/plan", {"Job": job, "Diff": diff}, q
        )

    def evaluate(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/job/{job_id}/evaluate", {}, q)

    def allocations(self, job_id: str, all_allocs: bool = False, q: Optional[QueryOptions] = None):
        q = q or QueryOptions()
        if all_allocs:
            q.params["all"] = "true"
        return self.client.get(f"/v1/job/{job_id}/allocations", q)

    def evaluations(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}/evaluations", q)

    def deployments(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}/deployments", q)

    def latest_deployment(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}/deployment", q)

    def summary(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}/summary", q)

    def versions(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/job/{job_id}/versions", q)

    def dispatch(
        self,
        job_id: str,
        meta: Optional[Dict[str, str]] = None,
        payload: bytes = b"",
        q: Optional[QueryOptions] = None,
    ):
        import base64

        body: Dict[str, Any] = {"Meta": meta or {}}
        if payload:
            body["Payload"] = base64.b64encode(payload).decode()
        return self.client.put(f"/v1/job/{job_id}/dispatch", body, q)

    def revert(self, job_id: str, version: int, q: Optional[QueryOptions] = None):
        return self.client.put(
            f"/v1/job/{job_id}/revert",
            {"JobID": job_id, "JobVersion": version},
            q,
        )

    def stable(self, job_id: str, version: int, stable: bool, q: Optional[QueryOptions] = None):
        return self.client.put(
            f"/v1/job/{job_id}/stable",
            {"JobID": job_id, "JobVersion": version, "Stable": stable},
            q,
        )

    def periodic_force(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/job/{job_id}/periodic/force", {}, q)


# ---------------------------------------------------------------------------
# Nodes (api/nodes.go)
# ---------------------------------------------------------------------------


class Nodes(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/nodes", q)

    def info(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/node/{node_id}", q)

    def stats(self, node_id: str = "", q: Optional[QueryOptions] = None):
        """Host stats (api/nodes.go Stats → /v1/client/stats); node_id
        makes a server agent proxy to that node."""
        q = q or QueryOptions()
        if node_id:
            q.params["node_id"] = node_id
        return self.client.get("/v1/client/stats", q)

    def allocations(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/node/{node_id}/allocations", q)

    def evaluate(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/node/{node_id}/evaluate", {}, q)

    def update_drain(
        self,
        node_id: str,
        spec: Optional[Dict[str, Any]],
        mark_eligible: bool = False,
        q: Optional[QueryOptions] = None,
    ):
        return self.client.put(
            f"/v1/node/{node_id}/drain",
            {"DrainSpec": spec, "MarkEligible": mark_eligible},
            q,
        )

    def toggle_eligibility(self, node_id: str, eligible: bool, q: Optional[QueryOptions] = None):
        return self.client.put(
            f"/v1/node/{node_id}/eligibility",
            {"Eligibility": "eligible" if eligible else "ineligible"},
            q,
        )

    def purge(self, node_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/node/{node_id}/purge", {}, q)


# ---------------------------------------------------------------------------
# Allocations / Evaluations / Deployments
# ---------------------------------------------------------------------------


class Allocations(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/allocations", q)

    def info(self, alloc_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/allocation/{alloc_id}", q)

    def stop(self, alloc_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/allocation/{alloc_id}/stop", {}, q)

    def stats(self, alloc_id: str, q: Optional[QueryOptions] = None):
        """Per-task resource usage (api/allocations.go Stats)."""
        return self.client.get(f"/v1/client/allocation/{alloc_id}/stats", q)

    def restart(self, alloc_id: str, task: str = "", q=None):
        """api/allocations.go Restart."""
        return self.client.put(
            f"/v1/client/allocation/{alloc_id}/restart", {"Task": task}, q
        )

    def signal(self, alloc_id: str, signal: str, task: str = "", q=None):
        """api/allocations.go Signal."""
        return self.client.put(
            f"/v1/client/allocation/{alloc_id}/signal",
            {"Signal": signal, "Task": task}, q,
        )

    def exec_task(self, alloc_id: str, task: str, cmd, timeout: float = 30.0, q=None):
        """One-shot exec (the reference's alloc-exec, non-interactive)."""
        q = q or QueryOptions()
        q.params["timeout"] = str(timeout)
        return self.client.post(
            f"/v1/client/allocation/{alloc_id}/exec",
            {"Task": task, "Cmd": list(cmd)}, q,
        )

    def exec_stream(self, alloc_id: str, task: str, command) -> "ExecStream":
        """INTERACTIVE exec over a websocket (api/allocations.go Exec /
        the reference's execStream): returns a session with stdin/stdout
        pumps and the remote exit code."""
        import json as json_mod

        cfg = self.client.config
        parsed = urllib.parse.urlsplit(cfg.address)
        default_port = 443 if parsed.scheme == "https" else 80
        host, port = parsed.hostname, parsed.port or default_port
        params = {"task": task, "command": json_mod.dumps(list(command))}
        path = (
            f"/v1/client/allocation/{alloc_id}/exec?"
            + urllib.parse.urlencode(params)
        )
        headers = {}
        if cfg.token:
            headers["X-Nomad-Token"] = cfg.token
        from ..agent.websocket import WebSocketClient

        ws = WebSocketClient(
            host, port, path, headers=headers, tls_context=cfg.ssl_context(),
        )
        return ExecStream(ws)


class ExecStream:
    """Client side of the interactive exec protocol: json frames with
    base64 stdio, terminated by an {"exit_code": N} frame."""

    def __init__(self, ws) -> None:
        self._ws = ws
        self.exit_code: Optional[int] = None

    def send_stdin(self, data: bytes) -> None:
        import base64
        import json as json_mod

        frame = {"stdin": {"data": base64.b64encode(data).decode()}}
        self._ws.send(json_mod.dumps(frame).encode(), opcode=0x1)

    def close_stdin(self) -> None:
        import json as json_mod

        self._ws.send(json_mod.dumps({"stdin": {"close": True}}).encode(), opcode=0x1)

    def read_output(self) -> Optional[bytes]:
        """Next stdout chunk, or None when the session ended (exit_code
        is set afterwards)."""
        import base64
        import json as json_mod

        while True:
            try:
                opcode, payload = self._ws.recv()
            except (ConnectionError, OSError):
                return None
            if opcode == 0x8:  # close
                return None
            try:
                frame = json_mod.loads(payload or b"{}")
            except ValueError:
                continue
            if "exit_code" in frame:
                self.exit_code = frame["exit_code"]
                return None
            data = (frame.get("stdout") or {}).get("data")
            if data:
                return base64.b64decode(data)

    def close(self) -> None:
        self._ws.close()


class AllocFS(_Sub):
    """Alloc filesystem/log access (api/fs.go AllocFS)."""

    def ls(self, alloc_id: str, path: str = "/", q: Optional[QueryOptions] = None):
        q = q or QueryOptions()
        q.params["path"] = path
        return self.client.get(f"/v1/client/fs/ls/{alloc_id}", q)

    def stat(self, alloc_id: str, path: str, q: Optional[QueryOptions] = None):
        q = q or QueryOptions()
        q.params["path"] = path
        return self.client.get(f"/v1/client/fs/stat/{alloc_id}", q)

    def cat(self, alloc_id: str, path: str, q: Optional[QueryOptions] = None) -> bytes:
        q = q or QueryOptions()
        q.params["path"] = path
        return self.client.get_raw(f"/v1/client/fs/cat/{alloc_id}", q)

    def read_at(self, alloc_id: str, path: str, offset: int, limit: int,
                q: Optional[QueryOptions] = None) -> bytes:
        q = q or QueryOptions()
        q.params.update({"path": path, "offset": str(offset), "limit": str(limit)})
        return self.client.get_raw(f"/v1/client/fs/readat/{alloc_id}", q)

    def logs(self, alloc_id: str, task: str, log_type: str = "stdout",
             offset: int = 0, origin: str = "start",
             q: Optional[QueryOptions] = None) -> bytes:
        data, _ = self.logs_at(alloc_id, task, log_type, offset, origin, q)
        return data

    def logs_at(self, alloc_id: str, task: str, log_type: str = "stdout",
                offset: int = 0, origin: str = "start",
                q: Optional[QueryOptions] = None):
        """(data, next_offset): the server returns the next stream offset
        in X-Nomad-Index so followers survive log rotation."""
        q = q or QueryOptions()
        q.params.update({
            "task": task, "type": log_type,
            "offset": str(offset), "origin": origin,
        })
        data, meta = self.client._do(
            "GET", f"/v1/client/fs/logs/{alloc_id}", None, q, raw=True
        )
        return data, meta.last_index

    def logs_follow(self, alloc_id: str, task: str, log_type: str = "stdout",
                    offset: int = 0, origin: str = "start",
                    q: Optional[QueryOptions] = None):
        """SERVER-PUSH log stream (follow=true): yields byte chunks as the
        task writes them; the generator ends when the caller closes it or
        the agent goes away."""
        q = q or QueryOptions()
        q.params.update({
            "task": task, "type": log_type, "offset": str(offset),
            "origin": origin, "follow": "true",
        })
        url = self.client._url(f"/v1/client/fs/logs/{alloc_id}", q)
        req = urllib.request.Request(url)
        if self.client.config.token:
            req.add_header("X-Nomad-Token", self.client.config.token)
        resp = urllib.request.urlopen(
            req, timeout=3600, context=self.client.config.ssl_context()
        )

        def gen():
            try:
                while True:
                    chunk = resp.read1(8192) if hasattr(resp, "read1") else resp.read(8192)
                    if not chunk:
                        return
                    yield chunk
            finally:
                resp.close()

        return gen()


class Evaluations(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/evaluations", q)

    def info(self, eval_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/evaluation/{eval_id}", q)

    def allocations(self, eval_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/evaluation/{eval_id}/allocations", q)


class Deployments(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/deployments", q)

    def info(self, deployment_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/deployment/{deployment_id}", q)

    def allocations(self, deployment_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/deployment/allocations/{deployment_id}", q)

    def promote(self, deployment_id: str, groups: Optional[List[str]] = None, q=None):
        body: Dict[str, Any] = {"DeploymentID": deployment_id}
        if groups:
            body["Groups"] = groups
        else:
            body["All"] = True
        return self.client.put(f"/v1/deployment/promote/{deployment_id}", body, q)

    def fail(self, deployment_id: str, q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/deployment/fail/{deployment_id}", {}, q)

    def pause(self, deployment_id: str, pause: bool, q: Optional[QueryOptions] = None):
        return self.client.put(
            f"/v1/deployment/pause/{deployment_id}",
            {"DeploymentID": deployment_id, "Pause": pause},
            q,
        )


# ---------------------------------------------------------------------------
# ACL (api/acl.go)
# ---------------------------------------------------------------------------


class ACLPolicies(_Sub):
    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/acl/policies", q)

    def info(self, name: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/acl/policy/{name}", q)

    def upsert(self, policy: Dict[str, Any], q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/acl/policy/{policy['Name']}", policy, q)

    def delete(self, name: str, q: Optional[QueryOptions] = None):
        return self.client.delete(f"/v1/acl/policy/{name}", q)


class ACLTokens(_Sub):
    def bootstrap(self, q: Optional[QueryOptions] = None):
        return self.client.put("/v1/acl/bootstrap", {}, q)

    def list(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/acl/tokens", q)

    def info(self, accessor_id: str, q: Optional[QueryOptions] = None):
        return self.client.get(f"/v1/acl/token/{accessor_id}", q)

    def self(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/acl/token/self", q)

    def create(self, token: Dict[str, Any], q: Optional[QueryOptions] = None):
        return self.client.put("/v1/acl/token", token, q)

    def update(self, token: Dict[str, Any], q: Optional[QueryOptions] = None):
        return self.client.put(f"/v1/acl/token/{token['AccessorID']}", token, q)

    def delete(self, accessor_id: str, q: Optional[QueryOptions] = None):
        return self.client.delete(f"/v1/acl/token/{accessor_id}", q)


# ---------------------------------------------------------------------------
# Operator / Agent / System / Status / Regions / Search
# ---------------------------------------------------------------------------


class Operator(_Sub):
    def scheduler_get_configuration(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/operator/scheduler/configuration", q)

    def scheduler_set_configuration(self, config: Dict[str, Any], q=None):
        return self.client.put("/v1/operator/scheduler/configuration", config, q)

    def raft_get_configuration(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/operator/raft/configuration", q)

    def autopilot_get_configuration(self, q: Optional[QueryOptions] = None):
        return self.client.get("/v1/operator/autopilot/configuration", q)

    def autopilot_set_configuration(self, config: Dict[str, Any], q=None):
        return self.client.put("/v1/operator/autopilot/configuration", config, q)

    def raft_remove_peer(self, peer_id: str, q: Optional[QueryOptions] = None):
        """Reference api/operator.go RaftRemovePeerByID."""
        from urllib.parse import quote

        return self.client.delete(
            f"/v1/operator/raft/peer?id={quote(peer_id, safe='')}", q
        )


class AgentAPI(_Sub):
    def self(self):
        out, _ = self.client.get("/v1/agent/self")
        return out

    def health(self):
        out, _ = self.client.get("/v1/agent/health")
        return out

    def members(self):
        out, _ = self.client.get("/v1/agent/members")
        return out

    def servers(self):
        out, _ = self.client.get("/v1/agent/servers")
        return out

    def metrics(self):
        out, _ = self.client.get("/v1/metrics")
        return out

    def monitor(self, log_level: str = "info", seq: int = 0):
        """One log-tail poll (api/agent.go Monitor's non-follow shape)."""
        out, _ = self.client.get(
            f"/v1/agent/monitor?log_level={log_level}&seq={seq}"
        )
        return out

    def monitor_follow(self, log_level: str = "info"):
        """SERVER-PUSH agent log stream (/v1/agent/monitor?follow=true):
        yields byte chunks until closed (api/agent.go Monitor)."""
        url = self.client._url(
            "/v1/agent/monitor",
            QueryOptions(params={"log_level": log_level, "follow": "true"}),
        )
        req = urllib.request.Request(url)
        if self.client.config.token:
            req.add_header("X-Nomad-Token", self.client.config.token)
        resp = urllib.request.urlopen(
            req, timeout=3600, context=self.client.config.ssl_context()
        )

        def gen():
            try:
                while True:
                    chunk = resp.read1(65536)
                    if not chunk:
                        return
                    yield chunk
            finally:
                resp.close()

        return gen()

    def join(self, addresses):
        """api/agent.go Join: runtime gossip join."""
        from urllib.parse import quote

        qs = "&".join(f"address={quote(a, safe='')}" for a in addresses)
        out, _ = self.client.put(f"/v1/agent/join?{qs}", {})
        return out

    def force_leave(self, node: str):
        from urllib.parse import quote

        out, _ = self.client.put(
            f"/v1/agent/force-leave?node={quote(node, safe='')}", {}
        )
        return out

    def keyring_list(self):
        out, _ = self.client.get("/v1/agent/keyring/list")
        return out

    def keyring_op(self, op: str, key: str):
        """op: install | use | remove."""
        out, _ = self.client.put(f"/v1/agent/keyring/{op}", {"Key": key})
        return out

    def client_gc(self):
        out, _ = self.client.put("/v1/client/gc", {})
        return out


class System(_Sub):
    def garbage_collect(self):
        return self.client.put("/v1/system/gc", {})

    def reconcile_summaries(self):
        return self.client.put("/v1/system/reconcile/summaries", {})


class Status(_Sub):
    def leader(self):
        out, _ = self.client.get("/v1/status/leader")
        return out

    def peers(self):
        out, _ = self.client.get("/v1/status/peers")
        return out


class Regions(_Sub):
    def list(self):
        out, _ = self.client.get("/v1/regions")
        return sorted(out or [])


class Search(_Sub):
    def prefix_search(self, prefix: str, context: str = "all", q=None):
        out, _ = self.client.post(
            "/v1/search", {"Prefix": prefix, "Context": context}, q
        )
        return out
