"""nomad-chaos: churn/chaos trace-replay harness with fault injection.

Three pieces (see each module's docstring for the contract):

- :mod:`.injector` — seeded fault-injection registry. Production modules
  call ``fire(point)`` at named injection points; strict no-ops unless a
  :class:`ChaosInjector` armed the point.
- :mod:`.trace` — deterministic churn schedules (``generate_trace(seed)``):
  registrations, stops, rollouts, drains, heartbeat expiries, fault
  windows, a mid-run leader kill.
- :mod:`.replay` + :mod:`.slo` — :class:`ChurnReplay` plays a trace
  against a live in-proc cluster; :class:`SLOGate` turns the run's trace
  gauges, throughput, and state-store invariant sweep into pass/fail.
"""
from .injector import MODES, POINTS, ChaosFault, ChaosInjector, active, fire

# Production modules import ``..chaos.injector`` for the fire() hook, and
# replay imports the server back — so everything past the injector loads
# lazily (PEP 562) to keep that edge acyclic and the hook import cheap.
_LAZY = {
    "ChurnReplay": ("replay", "ChurnReplay"),
    "CrashReplay": ("crash", "CrashReplay"),
    "ServerProcess": ("crash", "ServerProcess"),
    "invariant_sweep": ("replay", "invariant_sweep"),
    "invariant_sweep_allocs": ("replay", "invariant_sweep_allocs"),
    "SLOGate": ("slo", "SLOGate"),
    "SLOThresholds": ("slo", "SLOThresholds"),
    "ChaosEvent": ("trace", "ChaosEvent"),
    "generate_trace": ("trace", "generate_trace"),
    "trace_kind_counts": ("trace", "trace_kind_counts"),
    "trace_to_jsonable": ("trace", "trace_to_jsonable"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f".{mod_name}", __name__)
    value = getattr(mod, attr)
    globals()[name] = value
    return value

__all__ = [
    "POINTS",
    "MODES",
    "ChaosFault",
    "ChaosInjector",
    "active",
    "fire",
    "ChaosEvent",
    "generate_trace",
    "trace_kind_counts",
    "trace_to_jsonable",
    "ChurnReplay",
    "CrashReplay",
    "ServerProcess",
    "invariant_sweep",
    "invariant_sweep_allocs",
    "SLOGate",
    "SLOThresholds",
]
