"""Crash-recovery chaos: real-process SIGKILL failover for wire raft.

Where :class:`~nomad_tpu.chaos.replay.ChurnReplay` *simulates* leader
loss with an in-proc leadership transfer, :class:`CrashReplay` spawns a
real N-server wire-raft cluster as separate OS processes (one
``data_dir`` each — durable log, term/vote meta, snapshot; see
:mod:`.crash_server`), drives the churn trace at the leader over RPC,
and realizes ``leader_kill`` as ``SIGKILL -9`` of the leader process
mid-wave. Recovery is then measured, not assumed:

- **time_to_new_leader_ms** — kill to a survivor reporting ``leader``
  at a HIGHER term (polled per-replica with ``no_forward=True``);
- **time_to_first_commit_ms** — kill to the first write committed
  through the new leader;
- **rejoin via InstallSnapshot** — after the trace, the new leader
  snapshots under load (compacting its log past the killed node's
  durable tail — forcing the compacted-log path), the killed process
  restarts from its ``data_dir`` and must catch up; the harness asserts
  ``snapshots_installed >= 1`` and applied-index convergence;
- the surviving cluster passes the same invariant sweep as the in-proc
  replay, with per-replica alloc counts fetched over RPC.

Timings publish as ``nomad.chaos.failover.*`` gauges via
:mod:`nomad_tpu.trace.failover` and are bounded by
:class:`~nomad_tpu.chaos.slo.SLOGate`'s failover thresholds.

Process-boundary limits (validated at construction): injector fault
windows are per-process and cannot arm across the boundary, canaried
rollouts need the in-proc deployment nurse, and compile warmup would
spawn a JAX storm per subprocess — crash traces carry none of these.
"""
from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..rpc import transport as rpc_transport
from ..rpc.transport import RPCClient, RPCError
from ..trace import context as xtrace
from ..trace import failover
from ..trace.flight import FlightRecorder
from .replay import _RETRYABLE, ChurnReplay
from .trace import ChaosEvent

_READY_TIMEOUT_S = 45.0
_REAP_TIMEOUT_S = 10.0
_ELECTION_TIMEOUT_S = 30.0


def _free_port() -> int:
    """Ask the kernel for a free loopback port, release it for the child.

    The small bind race between release and the child's bind is accepted:
    crash clusters run on loopback in test/bench context, and the fixed
    port map is what lets a killed node restart at the same address."""
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
    finally:
        s.close()


class ServerProcess:
    """One crash-server OS process plus its RPC client.

    Owns the spawn / SIGKILL / graceful-terminate / restart lifecycle.
    Every spawn is reaped with a bounded ``wait`` (the
    ``subprocess-discipline`` lint rule) — an unkillable child raises
    instead of silently orphaning a nomad process."""

    def __init__(
        self,
        node_id: str,
        port: int,
        peers: Dict[str, Tuple[str, int]],
        data_dir: str,
        extra_args: Sequence[str] = (),
    ) -> None:
        self.node_id = node_id
        self.port = port
        self.peers = dict(peers)   # other members, excluding self
        self.data_dir = data_dir
        self.extra_args = tuple(extra_args)
        self.proc: Optional[subprocess.Popen] = None
        self._client: Optional[RPCClient] = None
        self._logf = None

    def spawn(self) -> None:
        os.makedirs(self.data_dir, exist_ok=True)
        peers_arg = ",".join(
            f"{pid}={host}:{port}"
            for pid, (host, port) in sorted(self.peers.items())
        )
        cmd = [
            sys.executable, "-m", "nomad_tpu.chaos.crash_server",
            "--node-id", self.node_id,
            "--rpc-port", str(self.port),
            "--peers", peers_arg,
            "--data-dir", self.data_dir,
            *self.extra_args,
        ]
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        self._logf = open(os.path.join(self.data_dir, "server.log"), "ab")
        self.proc = subprocess.Popen(
            cmd, stdout=self._logf, stderr=subprocess.STDOUT, env=env,
        )

    def wait_ready(self, timeout: float = _READY_TIMEOUT_S) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"{self.node_id} exited rc={self.proc.returncode} "
                    f"during startup; tail: {self._log_tail()}"
                )
            try:
                if self.call("Status.ping", no_forward=True,
                             timeout=1.0) == "pong":
                    return
            except (RPCError, OSError):
                time.sleep(0.1)
        raise RuntimeError(
            f"{self.node_id} not ready after {timeout}s; "
            f"tail: {self._log_tail()}"
        )

    def _log_tail(self, n: int = 5) -> str:
        try:
            with open(os.path.join(self.data_dir, "server.log"), "rb") as f:
                return b"\n".join(
                    f.read().splitlines()[-n:]
                ).decode("utf-8", "replace")
        except OSError:
            return "<no log>"

    def client(self) -> RPCClient:
        if self._client is None:
            self._client = RPCClient("127.0.0.1", self.port, timeout=10.0)
        return self._client

    def call(self, method: str, *args, **kwargs):
        return self.client().call(method, *args, **kwargs)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def _drop_client(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None

    def kill_hard(self) -> None:
        """SIGKILL -9: no shutdown path runs; the durable state is
        whatever already reached the disk."""
        if self.proc is None:
            return
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.wait(timeout=_REAP_TIMEOUT_S)
        self._drop_client()

    def terminate(self) -> None:
        """Graceful SIGTERM, escalating to SIGKILL on timeout. Always
        reaps (bounded) and closes the log handle."""
        try:
            if self.proc is not None and self.proc.poll() is None:
                self.proc.terminate()
                try:
                    self.proc.wait(timeout=_REAP_TIMEOUT_S)
                except subprocess.TimeoutExpired:
                    self.proc.kill()
                    self.proc.wait(timeout=_REAP_TIMEOUT_S)
        finally:
            self._drop_client()
            if self._logf is not None:
                self._logf.close()
                self._logf = None

    def restart(self) -> None:
        """Re-spawn on the same port over the same data_dir (the
        durable-restart path: meta + log tail + snapshot reload)."""
        if self.alive():
            raise RuntimeError(f"{self.node_id} is still running")
        if self._logf is not None:
            self._logf.close()
            self._logf = None
        self._drop_client()
        self.spawn()


class RemoteState:
    """Read-side facade over the leader RPC surface, shaped like the
    slice of ``StateStore`` the replay driver actually reads."""

    def __init__(self, call) -> None:
        self._call = call

    def job_by_id(self, namespace: str, job_id: str):
        return self._call("Job.GetJob", namespace, job_id)

    def allocs_by_job(self, namespace: str, job_id: str, any_version: bool = True):
        return self._call("Job.Allocations", namespace, job_id)

    def allocs(self):
        return self._call("Alloc.List")


class RemoteLeader:
    """The ``Server`` methods ChurnReplay drives, over the wire."""

    def __init__(self, proc: ServerProcess) -> None:
        self.proc = proc
        self.name = proc.node_id
        self.fsm_state = RemoteState(proc.call)

    def register_node(self, node):
        return self.proc.call("Node.Register", node)

    def heartbeat(self, node_id: str):
        return self.proc.call("Node.Heartbeat", node_id)

    def register_job(self, job):
        return self.proc.call("Job.Register", job)

    def deregister_job(self, namespace: str, job_id: str, purge: bool = False):
        return self.proc.call("Job.Deregister", namespace, job_id, purge)

    def evaluate_job(self, namespace: str, job_id: str):
        return self.proc.call("Job.Evaluate", namespace, job_id)

    def update_node_drain(self, node_id: str, drain):
        return self.proc.call("Node.UpdateDrain", node_id, drain)


class CrashReplay(ChurnReplay):
    """Churn replay against a real multi-process wire-raft cluster.

    Construction kwargs beyond :class:`ChurnReplay` (whose ``config``,
    in-proc server objects and warmup do not apply here):

    - ``base_dir``: parent directory for per-node data dirs (a temp dir
      is created and removed when omitted);
    - ``server_args``: extra ``crash_server`` CLI flags, e.g.
      ``("--num-schedulers", "1")``;
    - ``restart_killed``: restart SIGKILLed servers after the trace and
      require snapshot-install catch-up (default True).
    """

    def __init__(
        self,
        seed: int = 0,
        trace: Optional[List[ChaosEvent]] = None,
        n_servers: int = 3,
        n_nodes: int = 50,
        settle_timeout_s: float = 60.0,
        trace_kwargs: Optional[dict] = None,
        base_dir: Optional[str] = None,
        server_args: Sequence[str] = (),
        restart_killed: bool = True,
    ) -> None:
        kw = dict(trace_kwargs or {})
        # injector windows are per-process and cannot cross the boundary
        kw.setdefault("n_fault_windows", 0)
        super().__init__(
            seed=seed, trace=trace, n_servers=n_servers, n_nodes=n_nodes,
            settle_timeout_s=settle_timeout_s, trace_kwargs=kw,
        )
        bad = sorted({ev.kind for ev in self.trace
                      if ev.kind in ("arm_fault", "disarm_fault")})
        if bad:
            raise ValueError(
                f"crash traces cannot carry {bad}: the fault injector is "
                f"per-process and the servers are separate processes"
            )
        if any(ev.kind == "rollout" and ev.args.get("canary")
               for ev in self.trace):
            raise ValueError(
                "canaried rollouts need the in-proc deployment nurse; "
                "use ChurnReplay for canary scenarios"
            )
        self._nurse_enabled = False
        # the capacity monitor reads in-proc leader state; the replicas
        # here are separate processes
        self._capacity_monitor_enabled = False
        self.procs: Dict[str, ServerProcess] = {}
        self._leader_proc: Optional[ServerProcess] = None
        self._killed: List[str] = []
        self.restart_killed = bool(restart_killed)
        self.server_args = tuple(server_args)
        self._owns_base = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="nomad-crash-")
        self.failover_info: Dict[str, object] = {}
        # parent-side flight recorder: the replicas are separate
        # processes, so the harness samples them over RPC (RaftStats +
        # BrokerStats per replica, no_forward) — the frame ring is the
        # failover's black box: which term each replica saw, when the
        # broker drained, when the killed node went dark
        self.harness_flight: Optional[FlightRecorder] = None
        # nomad-xtrace collector: incremental Trace.Export drains on the
        # flight-probe cadence, per-replica seq cursors so a re-poll
        # never double-counts and ring eviction only loses what the
        # collector was too slow to read
        self._trace_cursors: Dict[str, int] = {}
        self._collected_spans: Dict[str, List[Dict[str, object]]] = {}
        self._replica_rpc: Dict[str, Dict[str, object]] = {}
        self._trace_dropped: Dict[str, int] = {}
        self._collect_lock = threading.Lock()
        self._pump_rr = 0

    # -- cluster plumbing overrides ---------------------------------------

    def _start_cluster(self) -> None:
        ids = [f"crash-s{i}" for i in range(self.n_servers)]
        addr = {nid: ("127.0.0.1", _free_port()) for nid in ids}
        for nid in ids:
            peers = {other: a for other, a in addr.items() if other != nid}
            sp = ServerProcess(
                nid, addr[nid][1], peers,
                os.path.join(self.base_dir, nid),
                extra_args=self.server_args,
            )
            self.procs[nid] = sp
            sp.spawn()
        for sp in self.procs.values():
            sp.wait_ready()
        failover.reset()
        self.harness_flight = FlightRecorder(interval_s=1.0, retain=512)
        for nid, sp in self.procs.items():
            self.harness_flight.add_probe(
                f"replica:{nid}", self._mk_replica_probe(sp))
        # span collection rides the same cadence: each tick drains every
        # live replica's ring incrementally, so a later SIGKILL loses at
        # most one tick's worth of spans
        self.harness_flight.add_probe("xtrace", self._drain_traces)
        self.harness_flight.arm()

    def _mk_replica_probe(self, sp: ServerProcess):
        def probe() -> Dict[str, object]:
            if not sp.alive():
                return {"alive": False}
            # a mid-failover replica answers slowly or not at all; the
            # 1s RPC bound keeps the tick loop live and the recorder
            # stores the raised error as the frame's value
            raft = sp.call("Operator.RaftStats", no_forward=True, timeout=1.0)
            broker = sp.call("Eval.BrokerStats", no_forward=True, timeout=1.0)
            return {"alive": True, "raft": raft, "broker": broker}
        return probe

    def _flight_stats(self) -> Dict[str, object]:
        fl = self.harness_flight
        if fl is None:
            return {}
        return {"harness": dict(armed=fl.armed, **fl.overhead())}

    # -- nomad-xtrace collection ------------------------------------------

    def _drain_traces(self) -> Dict[str, object]:
        """One incremental collection pass: drain every live replica's
        span ring past this collector's cursor, plus its per-method RPC
        table. Doubles as a flight probe (the returned brief lands in
        the frame ring)."""
        with self._collect_lock:
            for nid, sp in self.procs.items():
                if not sp.alive():
                    continue
                try:
                    out = sp.call(
                        "Trace.Export", self._trace_cursors.get(nid, 0),
                        no_forward=True, timeout=2.0,
                    )
                except (RPCError, OSError):
                    continue
                spans = out.get("spans") or []
                if spans:
                    self._collected_spans.setdefault(nid, []).extend(spans)
                self._trace_cursors[nid] = int(
                    out.get("next_seq", self._trace_cursors.get(nid, 0)))
                self._trace_dropped[nid] = int(out.get("dropped", 0))
                self._replica_rpc[nid] = out.get("rpc") or {}
            return {
                "collected": sum(
                    len(v) for v in self._collected_spans.values()),
                "dropped": dict(self._trace_dropped),
            }

    def _span_sets(self) -> List[List[Dict[str, object]]]:
        """Final drain, then every replica's accumulated spans plus the
        driver's own ring (the RemoteLeader client spans live there)."""
        self._drain_traces()
        with self._collect_lock:
            sets = [list(xtrace.export()["spans"])]
            sets.extend(list(v) for v in self._collected_spans.values())
        return sets

    def _rpc_result(self) -> Dict[str, object]:
        """Cluster-wide per-method table: every replica's wire-form
        table merged (histogram buckets add; percentiles recomputed from
        the merged histogram), plus the per-replica views."""
        with self._collect_lock:
            per_replica = {
                nid: table for nid, table in sorted(self._replica_rpc.items())
            }
        return {
            "cluster": rpc_transport.merge_rpc_tables(per_replica.values()),
            "replicas": {
                nid: {
                    m: {k: v for k, v in row.items() if k != "latency_hist"}
                    for m, row in table.items()
                }
                for nid, table in per_replica.items()
            },
        }

    def _pump_leader(self) -> RemoteLeader:
        """Route heartbeats through a rotating live FOLLOWER: the write
        forwards follower → leader at layer 7 (reference rpc.go
        forward()), so the run's steady background traffic exercises —
        and the stitched ledger measures — the real ``forward_hop``
        path, without putting the eval critical path behind an extra
        hop."""
        lp = self._leader_proc
        followers = [sp for sp in self.procs.values()
                     if sp.alive() and sp is not lp]
        if followers:
            self._pump_rr += 1
            return RemoteLeader(followers[self._pump_rr % len(followers)])
        return self._leader(timeout=1.0)

    def _find_leader_proc(self, timeout: float = 5.0,
                          min_term: int = 0) -> ServerProcess:
        """Poll every LIVE replica's raft stats locally (no_forward —
        leader forwarding would answer for the wrong node) until one
        reports leadership at term > min_term."""
        deadline = time.monotonic() + timeout
        while True:
            for sp in self.procs.values():
                if not sp.alive():
                    continue
                try:
                    st = sp.call("Operator.RaftStats", no_forward=True,
                                 timeout=1.0)
                except (RPCError, OSError):
                    continue
                if st.get("state") == "leader" and st.get("term", 0) > min_term:
                    self._leader_proc = sp
                    return sp
            if time.monotonic() > deadline:
                raise RuntimeError("no leader within timeout")
            time.sleep(0.05)

    def _leader(self, timeout: float = 5.0) -> RemoteLeader:
        lp = self._leader_proc
        if lp is not None and lp.alive():
            try:
                st = lp.call("Operator.RaftStats", no_forward=True,
                             timeout=1.0)
                if st.get("state") == "leader":
                    return RemoteLeader(lp)
            except (RPCError, OSError):
                pass
            self._leader_proc = None
        return RemoteLeader(self._find_leader_proc(timeout=timeout))

    def _leader_state(self):
        return self._leader().fsm_state

    def _broker_stats(self) -> Dict[str, int]:
        return self._leader().proc.call("Eval.BrokerStats")

    def _kill_leader(self) -> None:
        if self._killed:
            return   # at most one real kill per run; retries are no-ops
        lp = self._find_leader_proc()
        try:
            pre = lp.call("Operator.RaftStats", no_forward=True, timeout=1.0)
        except (RPCError, OSError):
            pre = {}
        old_term = int(pre.get("term", 0))
        t0 = time.monotonic()
        lp.kill_hard()
        self._killed.append(lp.node_id)
        self._leader_proc = None
        self.leader_kills += 1
        try:
            new_leader = self._find_leader_proc(
                timeout=_ELECTION_TIMEOUT_S, min_term=old_term)
        except RuntimeError:
            self.errors.append(  # race-ok: GIL-atomic append; harness list, read after threads settle
                f"failover: no new leader within {_ELECTION_TIMEOUT_S}s")
            return
        t_leader_ms = (time.monotonic() - t0) * 1000.0
        # first post-failover commit: a real write through the new leader
        # (re-evaluating a known job goes through raft_apply)
        t_commit_ms = None
        probe = next(iter(self._expected), None)
        if probe is not None:
            deadline = t0 + _ELECTION_TIMEOUT_S
            leader = RemoteLeader(new_leader)
            while time.monotonic() < deadline:
                try:
                    leader.evaluate_job(*probe)
                    t_commit_ms = (time.monotonic() - t0) * 1000.0
                    break
                except (RPCError, OSError):
                    time.sleep(0.05)
        self.failover_info = failover.record(
            killed=lp.node_id,
            new_leader=new_leader.node_id,
            old_term=old_term,
            time_to_new_leader_ms=round(t_leader_ms, 1),
            time_to_first_commit_ms=(
                round(t_commit_ms, 1) if t_commit_ms is not None else None),
        )

    def _post_trace(self) -> None:
        """Force the compacted-log path, then bring the corpse back.

        Snapshotting the NEW leader while the killed node is still down
        compacts the leader's log past the killed node's durable tail,
        so catch-up cannot ride AppendEntries — it must go through
        InstallSnapshot, the path this harness exists to exercise."""
        if not self._killed or not self.restart_killed:
            return
        snap_index = 0
        for _ in range(40):
            try:
                snap_index = int(
                    self._leader().proc.call("Operator.SnapshotSave"))
                break
            except _RETRYABLE:
                time.sleep(0.25)
        t0 = time.monotonic()
        for nid in self._killed:
            sp = self.procs[nid]
            try:
                sp.restart()
                sp.wait_ready()
            except (RuntimeError, OSError) as e:
                self.errors.append(f"restart {nid}: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
                return
        rejoined = False
        installs = 0
        deadline = time.monotonic() + self.settle_timeout_s
        while time.monotonic() < deadline:
            try:
                stats = [
                    self.procs[nid].call("Operator.RaftStats",
                                         no_forward=True, timeout=1.0)
                    for nid in self._killed
                ]
            except (RPCError, OSError):
                time.sleep(0.1)
                continue
            installs = sum(int(s.get("snapshots_installed", 0))
                           for s in stats)
            if snap_index > 0 and all(
                int(s.get("applied_index", 0)) >= snap_index for s in stats
            ):
                rejoined = True
                break
            time.sleep(0.1)
        self.failover_info = failover.note(
            snapshot_index=snap_index,
            snapshot_installs=installs,
            rejoined=rejoined,
            restart_catchup_ms=(
                round((time.monotonic() - t0) * 1000.0, 1)
                if rejoined else None),
        )
        if not rejoined:
            self.errors.append(  # race-ok: GIL-atomic append; harness list, read after threads settle
                f"restarted {self._killed} did not catch up to snapshot "
                f"index {snap_index} (installs={installs})"
            )

    def _replica_run_counts(self) -> Dict[str, Optional[int]]:
        from ..structs.structs import ALLOC_DESIRED_RUN

        # wait (bounded) for applied-index convergence first: a replica
        # a few heartbeats behind is lag, not divergence
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            applied = []
            for sp in self.procs.values():
                if not sp.alive():
                    continue
                try:
                    st = sp.call("Operator.RaftStats", no_forward=True,
                                 timeout=1.0)
                    applied.append(int(st.get("applied_index", -1)))
                except (RPCError, OSError):
                    applied.append(-1)
            if len(set(applied)) <= 1 and (not applied or applied[0] >= 0):
                break
            time.sleep(0.1)

        counts: Dict[str, Optional[int]] = {}
        for nid, sp in sorted(self.procs.items()):
            if not sp.alive():
                counts[nid] = None   # permanently dead: excluded
                continue
            try:
                allocs = sp.call("Alloc.List", no_forward=True, timeout=15.0)
            except (RPCError, OSError) as e:
                self.errors.append(f"replica count {nid}: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
                counts[nid] = None
                continue
            counts[nid] = sum(
                1 for a in allocs if a.desired_status == ALLOC_DESIRED_RUN
            )
        return counts

    def _extra_result(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "failover": dict(self.failover_info),
            "killed_servers": list(self._killed),
        }
        if self.harness_flight is not None:
            # last few frames: per-replica raft/broker state leading into
            # measurement (the kill + re-election are visible here)
            out["flight_tail"] = self.harness_flight.frames(recent=4)
        return out

    def _set_service_preemption(self) -> None:
        from ..structs.structs import PreemptionConfig, SchedulerConfiguration

        lp = self._leader().proc
        _, cfg = lp.call("Operator.SchedulerGetConfiguration")
        if cfg is None:
            cfg = SchedulerConfiguration()
        if cfg.preemption_config is None:
            cfg.preemption_config = PreemptionConfig()
        cfg.preemption_config.service_scheduler_enabled = True
        lp.call("Operator.SchedulerSetConfiguration", cfg)

    def _shutdown(self) -> None:
        if self.harness_flight is not None:
            self.harness_flight.disarm()
        super()._shutdown()   # stops the heartbeat pump (servers list is empty)
        for sp in self.procs.values():
            try:
                sp.terminate()
            except Exception as e:  # noqa: BLE001 — reap every process
                self.errors.append(f"shutdown {sp.node_id}: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
        if self._owns_base:
            shutil.rmtree(self.base_dir, ignore_errors=True)
