"""Standalone wire-raft server process for the crash-recovery harness.

``python -m nomad_tpu.chaos.crash_server --node-id s0 --rpc-port 7101
--peers s1=127.0.0.1:7102,s2=127.0.0.1:7103 --data-dir /tmp/s0`` boots
one full server — RPC transport on a FIXED port, ``WireRaft`` with
durable log/meta/snapshot under ``data_dir``, ``Server`` runtime, the
whole endpoint surface — and then blocks until SIGTERM (clean shutdown)
or SIGKILL (the point of the exercise: no shutdown path runs, recovery
must come from what already hit the disk).

Fixed ports matter: the harness preallocates the port map so a killed
node restarts at the SAME address and its peers' replicator connections
re-target without gossip. The scheduler runs the host (``binpack``)
path — one JAX compile storm per subprocess would dwarf every timing
this harness measures, and kernel parity has its own suite.

Prints ``READY <node-id> <host>:<port>`` on stdout once serving.
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import Dict, Tuple


def parse_peers(spec: str) -> Dict[str, Tuple[str, int]]:
    """``id=host:port,id=host:port`` → peer map."""
    peers: Dict[str, Tuple[str, int]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        pid, addr = part.split("=", 1)
        host, port = addr.rsplit(":", 1)
        peers[pid] = (host, int(port))
    return peers


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nomad_tpu.chaos.crash_server")
    p.add_argument("--node-id", required=True)
    p.add_argument("--rpc-port", type=int, required=True)
    p.add_argument("--peers", default="",
                   help="other cluster members as id=host:port,...")
    p.add_argument("--data-dir", required=True)
    p.add_argument("--election-min", type=float, default=0.3)
    p.add_argument("--election-max", type=float, default=0.6)
    p.add_argument("--raft-heartbeat", type=float, default=0.06)
    p.add_argument("--num-schedulers", type=int, default=2)
    # node TTLs sit well above the election gap so a failover never
    # spuriously expires the fleet mid-measurement
    p.add_argument("--heartbeat-min-ttl", type=float, default=4.0)
    p.add_argument("--heartbeat-max-ttl", type=float, default=6.0)
    args = p.parse_args(argv)

    from ..rpc.endpoints import bind_server
    from ..rpc.transport import RPCServer
    from ..server.server import Server, ServerConfig
    from ..server.wire_raft import WireRaft, WireRaftConfig
    from ..trace import context as xtrace

    # nomad-xtrace: stamp this replica's node id on every span it
    # records, and spill spans to the data dir (append + flush per span)
    # so a SIGKILL loses nothing already written — the collector's
    # Trace.Export drain is the fast path, the spill is the black box
    import os

    xtrace.set_process(args.node_id)
    xtrace.configure_spill(os.path.join(args.data_dir, "spans.jsonl"))

    peers = parse_peers(args.peers)
    rpc = RPCServer(host="127.0.0.1", port=args.rpc_port)
    raft = WireRaft(
        rpc, peers,
        WireRaftConfig(
            node_id=args.node_id,
            election_timeout_min=args.election_min,
            election_timeout_max=args.election_max,
            heartbeat_interval=args.raft_heartbeat,
            rpc_timeout=0.5,
            apply_timeout=10.0,
        ),
        data_dir=args.data_dir,
    )
    config = ServerConfig(
        num_schedulers=args.num_schedulers,
        heartbeat_min_ttl=args.heartbeat_min_ttl,
        heartbeat_max_ttl=args.heartbeat_max_ttl,
        eval_gc_interval=3600.0,
        scheduler_algorithm="binpack",
    )
    server = Server(config, raft=raft, name=args.node_id)
    bind_server(server, rpc)

    # transparent write forwarding: followers answer reads locally and
    # forward writes to whoever raft says leads (static port map, so the
    # address is computable without gossip)
    addr_map: Dict[str, Tuple[str, int]] = dict(peers)
    addr_map[args.node_id] = ("127.0.0.1", args.rpc_port)
    rpc.is_leader = raft.is_leader
    stop = threading.Event()

    def leader_addr_loop() -> None:
        while not stop.wait(0.1):
            lid = raft.leader_id
            rpc.leader_addr = addr_map.get(lid) if lid else None

    def on_sigterm(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, on_sigterm)
    rpc.start()
    server.start()
    raft.start()
    threading.Thread(target=leader_addr_loop, name="leader-addr",
                     daemon=True).start()
    host, port = rpc.addr
    print(f"READY {args.node_id} {host}:{port}", flush=True)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.stop()
        raft.close()
        rpc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
