"""Seeded fault-injection registry: named points, strict no-ops unless armed.

Production modules carry exactly one chaos hook shape — a call to this
module's ``fire(point)`` at the site where a fault would enter the real
system (the ``fault-injection-discipline`` lint rule rejects any other
chaos conditioning in production code). When nothing is armed, ``fire``
is a single global read and a return: the production cost of having the
hooks compiled in is one dict-free branch per call site.

The points mirror the failure surfaces the churn harness shakes:

==================  ========================================================
``device_dispatch``  ``tpu/batcher.DeviceBatcher.run`` — a raised fault
                     forces the engine's host-iterator fallback for that
                     eval; a delay models a slow/hung device round trip.
``plan_apply``       ``server/plan_apply.Planner.evaluate_plan`` — the
                     per-payload isolation in ``_evaluate_and_fold`` turns
                     the fault into that plan's future error (async waves
                     nack through the applier's ``apply_error`` path).
``broker_ack``       ``server/eval_broker.EvalBroker.ack`` — a lost ack:
                     the delivery stays unacked until the nack timer
                     redelivers it.
``raft_apply``       ``server/server.Server.raft_apply`` — a failed log
                     append, same blast radius as losing leadership
                     mid-write; every caller already survives it.
``heartbeat``        ``server/heartbeat.HeartbeatTimers.reset_heartbeat_timer``
                     — a dropped heartbeat; enough of them in a row and
                     the TTL expires, marking the node down.
``unblock_enqueue``  ``server/blocked_evals.BlockedEvals._flush_pending_locked``
                     — a fault on the coalesced unblock-storm re-enqueue:
                     the staged batch parks and retries on a bounded
                     backoff timer instead of reaching the broker.
``watch_notify``     ``watch/hub.WatchHub.notify`` — a dropped/delayed
                     post-apply watch notification: parked blocking
                     queries lose at most one flush window of wakeups and
                     degrade to their ``max_query_time`` deadline
                     re-query; the apply path that notified is untouched.
==================  ========================================================

Determinism: each armed point draws from its own ``random.Random`` seeded
from ``(seed, point)``, so a fixed seed yields a fixed fire/skip DECISION
SEQUENCE per point. (Cross-thread arrival order is the caller's problem;
the replayable artifact of a chaos run is the event trace, not the
per-fire interleaving.)

Arming discipline (also lint-enforced): every ``arm`` in consumer code
must have a matching ``disarm``/``disarm_all`` in a ``finally`` — an
injector that outlives its test run poisons everything after it.
"""
from __future__ import annotations

import threading
import time
from random import Random
from typing import Dict, Optional
from ..utils.lock_witness import witness_lock

POINTS = (
    "device_dispatch",
    "plan_apply",
    "broker_ack",
    "raft_apply",
    "heartbeat",
    "unblock_enqueue",
    "watch_notify",
)

MODES = ("fail", "delay")


class ChaosFault(RuntimeError):
    """A deliberately injected fault (never raised unless a point is armed)."""


class _PointSpec:
    __slots__ = ("mode", "prob", "rng", "max_fires", "delay_s",
                 "fires", "skips")

    def __init__(self, mode: str, prob: float, rng: Random,
                 max_fires: Optional[int], delay_s: float) -> None:
        self.mode = mode
        self.prob = prob
        self.rng = rng
        self.max_fires = max_fires
        self.delay_s = delay_s
        self.fires = 0
        self.skips = 0


class ChaosInjector:
    """One armed registry at a time (module-global ``_ACTIVE``); points
    arm/disarm independently. All spec state is guarded by ``_lock``;
    delays sleep outside it so a slow point never serializes the rest."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = int(seed)
        self._lock = witness_lock("injector.ChaosInjector._lock")
        self._specs: Dict[str, _PointSpec] = {}

    # -- arming ----------------------------------------------------------

    def arm(self, point: str, mode: str = "fail", prob: float = 1.0,
            max_fires: Optional[int] = None, delay_s: float = 0.0) -> None:
        """Arm ``point``: each subsequent ``fire(point)`` draws against
        ``prob``; a hit raises ChaosFault (mode="fail") or sleeps
        ``delay_s`` (mode="delay"), at most ``max_fires`` times."""
        if point not in POINTS:
            raise ValueError(f"unknown injection point {point!r}; "
                             f"known: {', '.join(POINTS)}")
        if mode not in MODES:
            raise ValueError(f"unknown chaos mode {mode!r}; known: "
                             f"{', '.join(MODES)}")
        rng = Random(f"{self.seed}:{point}")
        with self._lock:
            self._specs[point] = _PointSpec(
                mode, float(prob), rng,
                None if max_fires is None else int(max_fires),
                float(delay_s),
            )
        _set_active(self)

    def disarm(self, point: str) -> None:
        with self._lock:
            self._specs.pop(point, None)
            empty = not self._specs
        if empty:
            _clear_active(self)

    def disarm_all(self) -> None:
        with self._lock:
            self._specs.clear()
        _clear_active(self)

    def armed_points(self) -> tuple:
        with self._lock:
            return tuple(sorted(self._specs))

    # -- firing ----------------------------------------------------------

    def _fire(self, point: str, ctx: dict) -> None:
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return
            if spec.max_fires is not None and spec.fires >= spec.max_fires:
                spec.skips += 1
                return
            if spec.prob < 1.0 and spec.rng.random() >= spec.prob:
                spec.skips += 1
                return
            spec.fires += 1
            mode, delay_s = spec.mode, spec.delay_s
        if mode == "delay":
            time.sleep(delay_s)
            return
        raise ChaosFault(f"injected fault at {point}"
                         + (f" ({ctx})" if ctx else ""))

    # -- observability ---------------------------------------------------

    def fires(self, point: str) -> int:
        with self._lock:
            spec = self._specs.get(point)
            return spec.fires if spec is not None else 0

    def stats(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {
                point: {
                    "mode": spec.mode,
                    "prob": spec.prob,
                    "fires": spec.fires,
                    "skips": spec.skips,
                }
                for point, spec in sorted(self._specs.items())
            }


# -- the production-facing hook ---------------------------------------------
#
# _ACTIVE is None almost always; production call sites pay one global read.
# Exactly one injector can be active — a second injector arming while
# another holds the slot is a harness bug and raises immediately.

_ACTIVE: Optional[ChaosInjector] = None
_active_lock = threading.Lock()


def _set_active(inj: ChaosInjector) -> None:
    global _ACTIVE
    with _active_lock:
        if _ACTIVE is not None and _ACTIVE is not inj:
            raise RuntimeError(
                "another ChaosInjector is already armed; disarm it first"
            )
        _ACTIVE = inj


def _clear_active(inj: ChaosInjector) -> None:
    global _ACTIVE
    with _active_lock:
        if _ACTIVE is inj:
            _ACTIVE = None


def active() -> Optional[ChaosInjector]:
    return _ACTIVE


def fire(point: str, **ctx) -> None:
    """The ONE hook production modules call. Strict no-op unless an
    injector armed this point; may raise ChaosFault or sleep when it did."""
    inj = _ACTIVE
    if inj is None:
        return
    inj._fire(point, ctx)
