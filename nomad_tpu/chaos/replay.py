"""Churn trace-replay driver: run a live cluster through a chaos trace.

``ChurnReplay`` boots an in-proc cluster (shared ``InProcRaft``, N
servers, mock nodes with real heartbeat TTL timers and a background
heartbeat pump), then plays a :mod:`nomad_tpu.chaos.trace` schedule
against the current leader in real time: registrations, stops,
rollouts (destructive or canaried), high-priority arrivals,
preemption-pressure waves, drains, heartbeat mutes (TTL expiry), fault
windows armed on the :mod:`~nomad_tpu.chaos.injector` registry, and a
mid-run leader kill (``raft.transfer_leadership`` — the in-proc
equivalent of SIGKILLing the leader: abrupt, mid-write, with the broker
flushed and the new leader restoring evals and heartbeats).

Every event application has bounded retries with backoff — injected
faults (``ChaosFault``), leadership races (``NotLeaderError``), and RPC
weather are expected, not errors. After the last event the driver
quiesces: disarms everything (in a ``finally``), restores muted/drained
nodes, and waits for the cluster to converge before running the
post-run state-store invariant sweep that the SLO gate consumes.

Cluster plumbing is factored into overridable hooks (``_start_cluster``,
``_leader``, ``_leader_state``, ``_broker_stats``, ``_kill_leader``,
``_post_trace``, ``_replica_run_counts``, ``_shutdown``) so
:class:`nomad_tpu.chaos.crash.CrashReplay` can drive the same trace
against a REAL multi-process wire-raft cluster where the leader kill is
a SIGKILL -9.
"""
from __future__ import annotations

import copy
import threading
import time
from collections import Counter
from typing import Dict, List, Optional, Set, Tuple

from .. import mock
from ..rpc import transport as rpc_transport
from ..rpc.transport import RPCError
from ..server.raft import InProcRaft, NotLeaderError
from ..server.server import Server, ServerConfig
from ..trace import attribution, lifecycle, stitch
from ..trace import capacity as capacity_trace
from ..trace import context as xtrace
from .injector import ChaosFault, ChaosInjector
from .trace import ChaosEvent, generate_trace, trace_kind_counts

# bounded per-event retry: flapping faults degrade an event to "late",
# never to a hot loop or a wedged replay. ConnectionError is an OSError
# subclass, so RPC weather against a real cluster is covered too.
_EVENT_RETRIES = 6
_EVENT_BACKOFF_S = 0.05
_RETRYABLE = (ChaosFault, NotLeaderError, RuntimeError, KeyError,
              RPCError, OSError)


def invariant_sweep_allocs(
    allocs: List,
    expected: Dict[Tuple[str, str], int],
    stopped: Set[Tuple[str, str]],
) -> Dict[str, object]:
    """Post-run sweep over an alloc list: zero lost / duplicated allocs.

    - *duplicated*: an alloc id present twice, or two desired-run allocs
      holding the same (job, name) slot — the OCC/redispatch machinery
      double-placed an index.
    - *lost*: a live job whose desired-run alloc count is below its
      task-group count — churn ate a placement and nothing rescheduled it.
    - *orphaned*: desired-run allocs belonging to a stopped job.

    Takes a plain alloc list (not a state store) so the same sweep runs
    against remote replicas fetched over RPC by the crash harness.
    """
    from ..structs.structs import ALLOC_DESIRED_RUN

    violations: List[str] = []

    id_counts = Counter(a.id for a in allocs)
    dup_ids = {aid: n for aid, n in id_counts.items() if n > 1}
    for aid, n in sorted(dup_ids.items()):
        violations.append(f"alloc id {aid} appears {n} times")

    run_by_job: Dict[Tuple[str, str], List] = {}
    for a in allocs:
        if a.desired_status == ALLOC_DESIRED_RUN:
            run_by_job.setdefault((a.namespace, a.job_id), []).append(a)

    lost = 0
    dup_slots = 0
    for key, want in sorted(expected.items()):
        have = run_by_job.get(key, [])
        if len(have) < want:
            lost += want - len(have)
            violations.append(
                f"job {key[1]}: {len(have)}/{want} desired-run allocs"
            )
        name_counts = Counter(a.name for a in have)
        for name, n in sorted(name_counts.items()):
            if n > 1:
                dup_slots += n - 1
                violations.append(f"slot {name} held by {n} run allocs")

    orphaned = 0
    for key in sorted(stopped):
        n = len(run_by_job.get(key, []))
        if n:
            orphaned += n
            violations.append(f"stopped job {key[1]} still has {n} run allocs")

    return {
        "lost": lost,
        "duplicated": len(dup_ids) + dup_slots,
        "orphaned": orphaned,
        "converged": not violations,
        "violations": violations[:20],
    }


def invariant_sweep(
    state,
    expected: Dict[Tuple[str, str], int],
    stopped: Set[Tuple[str, str]],
) -> Dict[str, object]:
    """State-store form of :func:`invariant_sweep_allocs`."""
    return invariant_sweep_allocs(state.allocs(), expected, stopped)


class ChurnReplay:
    """Replay a chaos trace against a fresh in-proc cluster.

    ``run()`` returns the result dict :class:`nomad_tpu.chaos.slo.SLOGate`
    evaluates: lifecycle trace summary, measured placement throughput,
    the invariant sweep, per-point fault fire counts, and replay
    bookkeeping (events applied, degraded events, leader kills).
    """

    def __init__(
        self,
        seed: int = 0,
        trace: Optional[List[ChaosEvent]] = None,
        n_servers: int = 3,
        n_nodes: int = 100,
        config: Optional[ServerConfig] = None,
        time_scale: float = 1.0,
        settle_timeout_s: float = 30.0,
        trace_kwargs: Optional[dict] = None,
        warmup_counts: Tuple[int, ...] = (),
        autoscale: bool = False,
        lock_witness: bool = False,
        race_witness: bool = False,
    ) -> None:
        self.seed = int(seed)
        kw = dict(trace_kwargs or {})
        kw.setdefault("n_nodes", n_nodes)
        self.trace = trace if trace is not None else generate_trace(self.seed, **kw)
        self.n_servers = n_servers
        self.n_nodes = n_nodes
        self.config = config or ServerConfig(
            heartbeat_min_ttl=1.5,
            heartbeat_max_ttl=2.5,
            eval_gc_interval=3600.0,
        )
        self.time_scale = float(time_scale)
        self.settle_timeout_s = float(settle_timeout_s)
        self.warmup_counts = tuple(warmup_counts)

        self.servers: List[Server] = []
        self.node_ids: List[str] = []
        self.injector = ChaosInjector(seed=self.seed)
        # nomad-lockdep: arm the runtime lock witness for the whole run
        # and cross-check witnessed order edges against the static graph
        self.lock_witness = bool(lock_witness)
        # nomad-race: arm the Eraser lockset witness too — any tracked
        # shared field whose candidate lockset empties under churn fails
        # the run, and runtime-witnessed sharing is cross-checked against
        # the static inferred-shared set
        self.race_witness = bool(race_witness)

        self._muted: Set[str] = set()
        self._mute_lock = threading.Lock()
        self._pump_stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        self._nurse_thread: Optional[threading.Thread] = None
        # the nurse needs in-proc state access; the crash subclass (which
        # forbids canaried rollouts anyway) turns it off
        self._nurse_enabled = True

        # capacity-pressure bookkeeping: a sampler thread tracks blocked
        # depth peaks and placement flatlines (in-proc state access; the
        # crash subclass turns it off), and `autoscale=True` wires every
        # server's leader autoscaler to register fresh mock nodes
        self.autoscale = bool(autoscale)
        self._capacity_monitor_enabled = True
        self._monitor_thread: Optional[threading.Thread] = None
        self._peak_blocked = 0
        self._max_flatline_s = 0.0
        self._autoscaled_nodes = 0

        # convergence bookkeeping fed to the invariant sweep
        self._expected: Dict[Tuple[str, str], int] = {}
        self._stopped: Set[Tuple[str, str]] = set()
        self._drained: Set[str] = set()
        self._preempt_fillers: Dict[int, Tuple[str, str]] = {}
        self._preemption_enabled = False

        self.events_applied = 0
        self.events_degraded = 0   # exhausted retries; logged, not fatal
        self.leader_kills = 0
        self._boot_allocs = 0
        self.errors: List[str] = []
        self.fault_fires: Dict[str, int] = {}

    # -- cluster plumbing (the hooks CrashReplay overrides) ---------------

    def _start_cluster(self) -> None:
        raft = InProcRaft()
        for i in range(self.n_servers):
            self.servers.append(  # race-ok: bootstrap runs before the pump/nurse threads start
                Server(self.config, raft=raft, name=f"chaos-s{i + 1}")
            )
        if self.autoscale:
            # every server gets the node provider — whichever holds
            # leadership runs the (leadership-armed) loop
            for s in self.servers:
                s.autoscaler.scale_up_fn = self._autoscale_up
        for s in self.servers:
            s.start()

    def _autoscale_up(self, n: int) -> int:
        """Autoscaler node provider: register ``n`` fresh mock nodes on
        the current leader (each registration fires the capacity-change
        trigger, storming parked evals back out) and enroll them in the
        heartbeat pump so they stay READY."""
        leader = self._leader(timeout=2.0)
        added = 0
        for _ in range(int(n)):
            node = mock.node()
            leader.register_node(node)
            self.node_ids.append(node.id)  # race-ok: GIL-atomic append; replay thread is the only mutator
            added += 1
        self._autoscaled_nodes += added
        return added

    def _leader(self, timeout: float = 5.0) -> Server:
        deadline = time.monotonic() + timeout
        while True:
            for s in self.servers:
                if s.is_leader:
                    return s
            if time.monotonic() > deadline:
                raise RuntimeError("no leader within timeout")
            time.sleep(0.01)

    def _pump_leader(self):
        """Server the heartbeat pump drives. The crash harness routes
        this through a rotating live FOLLOWER so heartbeats traverse
        layer-7 leader forwarding — the traffic that populates
        ``forward_hop`` in the stitched ledger."""
        return self._leader(timeout=1.0)

    def _leader_state(self):
        """Read surface for the leader's FSM (a StateStore, or the crash
        harness's RPC-backed facade)."""
        return self._leader().fsm.state

    def _broker_stats(self) -> Dict[str, int]:
        return self._leader().eval_broker.stats()

    def _kill_leader(self) -> None:
        leader = self._leader()
        raft = leader.raft
        peers = [s.peer for s in self.servers if s is not leader]
        if peers:
            raft.transfer_leadership(peers[0])
            self.leader_kills += 1

    def _post_trace(self) -> None:
        """Hook between the last trace event and settle (the crash
        harness restarts the killed server here)."""

    def _replica_run_counts(self) -> Dict[str, Optional[int]]:
        return {
            s.name: s.fsm.state.count_allocs_desired_run()
            for s in self.servers
        }

    def _flight_stats(self) -> Dict[str, object]:
        """Per-server flight-recorder health (armed only on the leader;
        the crash harness's out-of-proc replicas have no in-proc
        recorder and report nothing here)."""
        out: Dict[str, object] = {}
        for s in self.servers:
            fl = getattr(s, "flight", None)
            if fl is not None:
                out[getattr(s, "name", "?")] = dict(
                    armed=fl.armed, **fl.overhead())
        return out

    def _span_sets(self) -> List[List[Dict[str, object]]]:
        """Per-process span sets for stitching. The in-proc harness has
        exactly one process (its own ring); the crash harness returns
        every replica's Trace.Export drain plus the driver's ring."""
        return [list(xtrace.export()["spans"])]

    def _rpc_result(self) -> Dict[str, object]:
        """Per-method RPC table. The in-proc harness reports the driver
        process's table (empty when ServerProxy short-circuits the
        wire); the crash harness merges every replica's."""
        return {"cluster": rpc_transport.rpc_stats(), "replicas": {}}

    def _stitched_result(self) -> Dict[str, object]:
        """Stitched cross-process trace sample + bottleneck ledger: the
        nomad-xtrace view of the run. Full trees are too big for a
        result dict, so this carries the ranked component report, clock
        offsets, and ONE formatted sample tree (the span-richest
        trace)."""
        st = stitch.stitch(self._span_sets())
        spans = st.pop("spans")
        report = attribution.stitched_report(spans)
        sample = ""
        if st["traces"]:
            richest = max(st["traces"],
                          key=lambda t: (t["spans"], t["trace_id"]))
            sample = stitch.format_tree(richest)
        return {
            "processes": st["processes"],
            "clock_offsets_ms": st["clock_offsets_ms"],
            "span_count": st["span_count"],
            "trace_count": st["trace_count"],
            "orphan_spans": sum(t["orphans"] for t in st["traces"]),
            "report": report,
            "sample_trace": sample,
        }

    def _extra_result(self) -> Dict[str, object]:
        """Harness-specific additions merged into the run() result."""
        return {}

    def _shutdown(self) -> None:
        self._pump_stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=2.0)
        if self._nurse_thread is not None:
            self._nurse_thread.join(timeout=2.0)
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=2.0)
        for s in self.servers:
            s.stop()

    # -- background pumps --------------------------------------------------

    def _pump_heartbeats(self) -> None:
        """Background client stand-in: heartbeat every live node well
        inside its TTL. Muted nodes are skipped (that IS the TTL-expiry
        fault); injected heartbeat faults surface here as ChaosFault and
        are simply dropped heartbeats."""
        interval = max(0.05, self.config.heartbeat_min_ttl / 3.0)
        while not self._pump_stop.wait(interval):
            try:
                leader = self._pump_leader()
            except RuntimeError:
                continue
            with self._mute_lock:
                muted = set(self._muted)
            # snapshot: capacity_release / autoscaler threads append
            for node_id in list(self.node_ids):
                if node_id in muted:
                    continue
                try:
                    leader.heartbeat(node_id)
                except _RETRYABLE:
                    continue
                except Exception as e:  # noqa: BLE001 — pump must survive
                    self.errors.append(f"heartbeat pump: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle

    def _nurse_deployments(self) -> None:
        """Client-health stand-in: no real clients run here, so the
        allochealth hook (client/allochealth in the reference) is pumped
        by the driver — active deployments get their unreported allocs
        marked healthy, and canaried deployments are promoted once every
        placed canary reports healthy, letting canaried rollouts run to
        completion instead of stalling the sweep."""
        while not self._pump_stop.wait(0.2):
            try:
                self._pump_deployments_once()
            except _RETRYABLE:
                continue
            except Exception as e:  # noqa: BLE001 — nurse must survive
                self.errors.append(f"deployment nurse: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle

    def _pump_deployments_once(self) -> None:
        from ..structs.structs import (
            ALLOC_CLIENT_RUNNING,
            ALLOC_DESIRED_RUN,
            AllocDeploymentStatus,
        )

        leader = self._leader(timeout=1.0)
        state = leader.fsm.state
        for d in state.deployments():
            if not d.active():
                continue
            updates = []
            for a in state.allocs_by_job(d.namespace, d.job_id, True):
                if (
                    a.deployment_id != d.id
                    or a.desired_status != ALLOC_DESIRED_RUN
                    or (a.deployment_status is not None
                        and a.deployment_status.healthy is not None)
                ):
                    continue
                u = a.copy_skip_job()
                u.client_status = ALLOC_CLIENT_RUNNING
                u.deployment_status = AllocDeploymentStatus(
                    healthy=True, timestamp_ns=time.time_ns(),
                    canary=(a.deployment_status.canary
                            if a.deployment_status else False),
                )
                updates.append(u)
            if updates:
                leader.update_allocs_from_client(updates)
            d2 = state.deployment_by_id(d.id)
            if d2 is None or not d2.active() or not d2.requires_promotion():
                continue
            canaries = [
                cid for tg in d2.task_groups.values()
                for cid in (tg.placed_canaries or [])
            ]

            def healthy(cid: str) -> bool:
                a = state.alloc_by_id(cid)
                return bool(
                    a is not None and a.deployment_status is not None
                    and a.deployment_status.healthy
                )

            if canaries and all(healthy(c) for c in canaries):
                try:
                    leader.deployment_watcher.promote(d2.id)
                except (KeyError, ValueError):
                    pass  # promoted or failed concurrently

    def _boot(self) -> None:
        self._start_cluster()
        leader = self._leader()
        for _ in range(self.n_nodes):
            n = mock.node()
            self.node_ids.append(n.id)  # race-ok: bootstrap runs before the pump/nurse threads start
            leader.register_node(n)
        self._warmup(leader)
        # gauges measure the churn run, not boot/warmup
        lifecycle.reset()
        capacity_trace.reset()
        xtrace.reset()
        xtrace.set_process("chaos-driver")
        rpc_transport.reset_rpc_stats()
        self._pump_thread = threading.Thread(
            target=self._pump_heartbeats, name="chaos-heartbeat-pump",
            daemon=True,
        )
        self._pump_thread.start()
        if self._nurse_enabled:
            self._nurse_thread = threading.Thread(
                target=self._nurse_deployments, name="chaos-deploy-nurse",
                daemon=True,
            )
            self._nurse_thread.start()
        if self._capacity_monitor_enabled:
            self._monitor_thread = threading.Thread(
                target=self._watch_capacity, name="chaos-capacity-monitor",
                daemon=True,
            )
            self._monitor_thread.start()

    def _watch_capacity(self) -> None:
        """Capacity-pressure sampler: blocked-depth high-water mark, and
        the longest stretch where blocked evals remained but NOTHING
        placed — the convoy signature the storm SLO bounds (placement
        rate must never flatline while work is parked and capacity is
        arriving)."""
        last_allocs = -1
        last_progress_t = time.monotonic()
        while not self._pump_stop.wait(0.1):
            try:
                leader = self._leader(timeout=1.0)
                blocked = leader.blocked_evals.stats().get("total_blocked", 0)
                capacity_trace.note_blocked_depth(blocked)
                if blocked > self._peak_blocked:
                    self._peak_blocked = blocked
                n = leader.fsm.state.count_allocs_desired_run()
                now = time.monotonic()
                if n != last_allocs or blocked == 0:
                    last_allocs = n
                    last_progress_t = now
                elif now - last_progress_t > self._max_flatline_s:
                    self._max_flatline_s = now - last_progress_t
            except Exception:  # noqa: BLE001 — monitor must survive churn
                continue

    def _warmup(self, leader: Server) -> None:
        """Pre-trace compile warmup: place (then purge) one throwaway job
        per requested task-group count, so the device engine's padded
        compile buckets for the trace's eval shapes are built OUTSIDE the
        measured window (per-process first dispatch costs seconds — the
        same reason bench_system warms its shapes)."""
        from ..structs.structs import ALLOC_DESIRED_RUN

        for i, count in enumerate(self.warmup_counts):
            job = self._make_job(f"chaos-warmup-{i}", count, 100, 64, 50)
            leader.register_job(job)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                run = [
                    a for a in leader.fsm.state.allocs_by_job(
                        job.namespace, job.id, True)
                    if a.desired_status == ALLOC_DESIRED_RUN
                ]
                if len(run) >= count:
                    break
                time.sleep(0.05)
            leader.deregister_job(job.namespace, job.id, purge=True)
        if self.warmup_counts:
            leader.drain_evals(timeout=30.0)
        # warmup rows stay in the store (GC is off): exclude them from
        # the run's placement-throughput numerator
        self._boot_allocs = len(self._leader_state().allocs())

    # -- event application -----------------------------------------------

    def _make_job(self, job_id: str, count: int, cpu: int, memory_mb: int,
                  priority: int):
        job = mock.job()
        job.id = job_id
        job.name = job_id
        job.priority = priority
        tg = job.task_groups[0]
        tg.count = count
        res = tg.tasks[0].resources
        res.cpu = cpu
        res.memory_mb = memory_mb
        res.networks = []   # churn jobs don't contend on ports
        return job

    def _set_service_preemption(self) -> None:
        from ..structs.structs import PreemptionConfig, SchedulerConfiguration

        leader = self._leader()
        _, cfg = leader.fsm.state.scheduler_config()
        cfg = copy.deepcopy(cfg) if cfg is not None else SchedulerConfiguration()
        if cfg.preemption_config is None:
            cfg.preemption_config = PreemptionConfig()
        cfg.preemption_config.service_scheduler_enabled = True
        leader.raft_apply("scheduler-config", cfg)

    def _enable_service_preemption(self) -> None:
        # service-scheduler preemption is off by default (matching the
        # reference); a pressure wave flips it once, through raft, so
        # every replica agrees
        if self._preemption_enabled:
            return
        self._set_service_preemption()
        self._preemption_enabled = True

    def _apply_event(self, ev: ChaosEvent) -> None:
        a = ev.args
        if ev.kind == "register_job" or ev.kind == "hipri_job":
            prio = a.get("priority", 80 if ev.kind == "hipri_job" else 50)
            job = self._make_job(a["job_id"], a["count"], a["cpu"],
                                 a["memory_mb"], prio)
            self._leader().register_job(job)
            self._expected[(job.namespace, job.id)] = a["count"]
            self._stopped.discard((job.namespace, job.id))
        elif ev.kind == "stop_job":
            leader = self._leader()
            key = None
            for k in self._expected:
                if k[1] == a["job_id"]:
                    key = k
                    break
            if key is None:
                return   # registration degraded earlier; nothing to stop
            leader.deregister_job(key[0], key[1], purge=False)
            self._expected.pop(key, None)
            self._stopped.add(key)
        elif ev.kind == "rollout":
            leader = self._leader()
            for (ns, jid), count in list(self._expected.items()):
                if jid != a["job_id"]:
                    continue
                stored = self._leader_state().job_by_id(ns, jid)
                if stored is None:
                    return
                job = copy.deepcopy(stored)
                job.task_groups[0].tasks[0].resources.cpu = a["cpu"]
                canary = int(a.get("canary", 0))
                if canary:
                    # canaried deployment update: stage `canary` new-
                    # version allocs; the deployment nurse reports their
                    # health and promotes, unleashing the rolling
                    # replacement (reference update block + deploymentwatcher)
                    from ..structs.structs import UpdateStrategy

                    update = UpdateStrategy(
                        max_parallel=max(1, count), canary=canary)
                    job.update = update
                    job.task_groups[0].update = update
                leader.register_job(job)
                return
        elif ev.kind == "preempt_pressure":
            self._enable_service_preemption()
            wave = int(a.get("wave", 0))
            fill = self._make_job(
                f"preempt-fill-{wave}", a["filler_count"], a["filler_cpu"],
                a.get("memory_mb", 64), priority=10)
            self._leader().register_job(fill)
            # fillers are pressure, not fleet: under saturation they are
            # LEGITIMATELY part-placed then evicted by the hipri burst,
            # so they never enter _expected; release moves them to the
            # stopped set, where leftovers DO count (as orphans)
            self._preempt_fillers[wave] = (fill.namespace, fill.id)
        elif ev.kind == "preempt_release":
            wave = int(a.get("wave", 0))
            key = self._preempt_fillers.pop(wave, None)
            if key is None:
                return   # pressure event degraded earlier
            self._leader().deregister_job(key[0], key[1], purge=False)
            self._stopped.add(key)
        elif ev.kind == "saturate":
            # a burst of real fleet jobs past free capacity: placements
            # fail, evals park in BlockedEvals. They enter _expected —
            # the sweep requires them placed once capacity arrives
            wave = int(a.get("wave", 0))
            leader = self._leader()
            for i in range(int(a["job_count"])):
                job = self._make_job(
                    f"sat-{wave}-{i}", a["count"], a["cpu"],
                    a["memory_mb"], priority=40)
                leader.register_job(job)
                self._expected[(job.namespace, job.id)] = a["count"]
                self._stopped.discard((job.namespace, job.id))
        elif ev.kind == "capacity_release":
            # node-registration burst: each lands in the FSM and fires
            # the capacity-change trigger — the unblock storm
            leader = self._leader()
            for _ in range(int(a.get("node_count", 0))):
                node = mock.node()
                leader.register_node(node)
                self.node_ids.append(node.id)  # race-ok: GIL-atomic append; replay thread is the only mutator
        elif ev.kind == "drain_node":
            node_id = self.node_ids[a["node_idx"] % len(self.node_ids)]
            self._leader().update_node_drain(node_id, True)
            self._drained.add(node_id)
        elif ev.kind == "undrain_node":
            node_id = self.node_ids[a["node_idx"] % len(self.node_ids)]
            self._leader().update_node_drain(node_id, None)
            self._drained.discard(node_id)
        elif ev.kind == "mute_node":
            node_id = self.node_ids[a["node_idx"] % len(self.node_ids)]
            with self._mute_lock:
                self._muted.add(node_id)
        elif ev.kind == "unmute_node":
            node_id = self.node_ids[a["node_idx"] % len(self.node_ids)]
            with self._mute_lock:
                self._muted.discard(node_id)
        elif ev.kind == "arm_fault":
            self.injector.arm(
                a["point"], mode=a.get("mode", "fail"),
                prob=a.get("prob", 1.0), delay_s=a.get("delay_s", 0.0),
                max_fires=a.get("max_fires"),
            )
        elif ev.kind == "disarm_fault":
            point = a["point"]
            self.fault_fires[point] = (
                self.fault_fires.get(point, 0) + self.injector.fires(point)
            )
            self.injector.disarm(point)
        elif ev.kind == "leader_kill":
            self._kill_leader()
        else:
            raise ValueError(f"unknown trace event kind {ev.kind!r}")

    def _apply_with_retries(self, ev: ChaosEvent) -> None:
        delay = _EVENT_BACKOFF_S
        for attempt in range(_EVENT_RETRIES):
            try:
                self._apply_event(ev)
                self.events_applied += 1
                return
            except _RETRYABLE as e:
                if attempt == _EVENT_RETRIES - 1:
                    self.events_degraded += 1
                    self.errors.append(f"{ev.kind}@{ev.t:.2f}: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
                    return
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    # -- quiesce + measurement --------------------------------------------

    def _live_jobs_converged(self, state) -> bool:
        from ..structs.structs import ALLOC_DESIRED_RUN

        for (ns, jid), want in self._expected.items():
            run = [
                x for x in state.allocs_by_job(ns, jid, True)
                if x.desired_status == ALLOC_DESIRED_RUN
            ]
            if len(run) != want or len({x.name for x in run}) != want:
                return False
        return True

    def _settle(self) -> bool:
        """Restore every disturbance, then wait for convergence."""
        with self._mute_lock:
            self._muted.clear()
        for node_id in list(self._drained):
            try:
                self._leader().update_node_drain(node_id, None)
            except Exception as e:  # noqa: BLE001
                self.errors.append(f"undrain {node_id}: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
        self._drained.clear()

        deadline = time.monotonic() + self.settle_timeout_s
        nudge_at = time.monotonic() + self.settle_timeout_s / 2.0
        nudged = False
        while time.monotonic() < deadline:
            try:
                stats = self._broker_stats()
                broker_idle = (
                    stats["total_ready"] == 0
                    and stats["total_unacked"] == 0
                    and stats["total_waiting"] == 0
                )
                if broker_idle and self._live_jobs_converged(
                        self._leader_state()):
                    return True
            except _RETRYABLE as e:
                self.errors.append(f"settle probe: {e!r}")  # race-ok: GIL-atomic append; harness list, read after threads settle
                time.sleep(0.2)
                continue
            # drain/migrate health gating has no real clients here: one
            # re-evaluation nudge per straggler halfway through the window
            if not nudged and time.monotonic() >= nudge_at:
                nudged = True
                leader = self._leader()
                for (ns, jid) in list(self._expected):
                    try:
                        leader.evaluate_job(ns, jid)
                    except Exception:  # noqa: BLE001 — stopped mid-nudge
                        pass
            time.sleep(0.05)
        return False

    def _measure(self, settled: bool, t0: float, t_run: float) -> Dict[str, object]:
        """Read the cluster while it is still up (before _shutdown)."""
        from ..structs.structs import ALLOC_DESIRED_RUN

        duration = time.monotonic() - t0
        # throughput over the churn window itself (boot + compile warmup
        # excluded — they are setup, not the workload under measurement)
        run_duration = time.monotonic() - t_run
        allocs = self._leader_state().allocs()
        inv = invariant_sweep_allocs(allocs, self._expected, self._stopped)
        if not settled:
            inv["converged"] = False
            inv["violations"] = (["settle timeout"] + inv["violations"])[:20]

        # replica consistency: every FSM saw the same applied log (a
        # permanently-dead replica reports None and is excluded)
        counts = self._replica_run_counts()
        live = [c for c in counts.values() if c is not None]
        if len(set(live)) > 1:
            inv["converged"] = False
            inv["violations"].append(f"replica divergence: {counts}")

        # allocs() retains stopped/superseded rows until GC (disabled for
        # the run), so its length approximates placements ever created;
        # boot-time warmup rows are excluded
        total_allocs = max(0, len(allocs) - self._boot_allocs)
        result = {
            "seed": self.seed,
            "duration_s": round(duration, 3),
            "trace_events": len(self.trace),
            "trace_kinds": trace_kind_counts(self.trace),
            "events_applied": self.events_applied,
            "events_degraded": self.events_degraded,
            "leader_kills": self.leader_kills,
            "fault_fires": dict(sorted(self.fault_fires.items())),
            "total_allocs": total_allocs,
            "desired_run_allocs": sum(
                1 for a in allocs if a.desired_status == ALLOC_DESIRED_RUN
            ),
            "replica_run_counts": counts,
            "throughput_allocs_per_s": round(total_allocs / run_duration, 2)
            if run_duration > 0 else None,
            "trace_summary": lifecycle.summary(),
            # wave-level critical-path ledger over the churn window: the
            # ranked decomposition names the stage the wall went to, and
            # its coverage self-check is SLO-gateable
            # (attribution_coverage_min)
            "bottleneck_report": attribution.bottleneck_report(),
            # nomad-xtrace: per-method RPC table + stitched trace sample
            "rpc": self._rpc_result(),
            "stitched": self._stitched_result(),
            "flight": self._flight_stats(),
            "capacity": self._capacity_result(),
            "invariants": inv,
            "errors": self.errors[:20],
        }
        result.update(self._extra_result())
        return result

    def _capacity_result(self) -> Dict[str, object]:
        """Storm ledger: unblock-to-place percentiles and batch stats
        from the capacity trace module, joined with the monitor's peak /
        flatline bookkeeping and the end-of-run drain check."""
        cap = capacity_trace.summary()
        peak = max(self._peak_blocked, int(cap.get("peak_blocked") or 0))
        final_blocked = None
        blocked_stats = None
        auto: Dict[str, object] = {}
        for s in self.servers:
            tracker = getattr(s, "blocked_evals", None)
            if tracker is None:
                continue
            if getattr(s, "is_leader", False):
                blocked_stats = tracker.stats()
            scaler = getattr(s, "autoscaler", None)
            if scaler is not None and (scaler.stats().get("ticks")
                                       or self.autoscale):
                auto[getattr(s, "name", "?")] = scaler.stats()
        if blocked_stats is not None:
            final_blocked = blocked_stats.get("total_blocked", 0)
            cap["blocked_stats"] = blocked_stats
        cap.update({
            "peak_blocked": peak,
            "final_blocked": final_blocked,
            "blocked_drain_frac": (
                round(final_blocked / peak, 4)
                if peak and final_blocked is not None else None
            ),
            "max_flatline_s_while_blocked": round(self._max_flatline_s, 2),
            "autoscaled_nodes": self._autoscaled_nodes,
            "autoscaler": auto,
        })
        return cap

    def run(self) -> Dict[str, object]:
        t0 = time.monotonic()
        witness = None
        if self.lock_witness:
            from ..utils import lock_witness as _lw
            # armed BEFORE _boot so every factory-created lock in the
            # servers under churn is instrumented
            witness = _lw.arm()
        race = None
        if self.race_witness:
            from ..rpc import transport as _transport
            from ..trace import lifecycle as _lc
            from ..utils import race_witness as _rw
            # after any explicit lock-witness arm, so auto-arm bookkeeping
            # stays correct; module stat tables are re-minted AFTER arming
            # so they come out of the tracked factories
            race = _rw.arm()
            _lc.reset()
            _transport.reset_rpc_stats()
        try:
            self._boot()
            t_run = time.monotonic()
            start = t_run
            for ev in self.trace:
                target = start + ev.t * self.time_scale
                sleep_for = target - time.monotonic()
                if sleep_for > 0:
                    time.sleep(sleep_for)
                self._apply_with_retries(ev)
            # roll any still-armed fire counts into the tally before the
            # finally-disarm wipes them
            for point, st in self.injector.stats().items():
                self.fault_fires[point] = (
                    self.fault_fires.get(point, 0) + st["fires"]
                )
            self._post_trace()
            settled = self._settle()
            # measurement happens while the cluster is live: the crash
            # harness's replicas are separate processes that stop
            # answering RPC once _shutdown reaps them
            result = self._measure(settled, t0, t_run)
            if witness is not None:
                from ..analysis.lock_order import build_static_graph
                result["lock_witness"] = {
                    **witness.stats(),
                    "missing_from_static": [
                        list(e) for e in witness.cross_check(
                            build_static_graph())
                    ],
                }
            if race is not None:
                from ..analysis.shared_state import build_static_shared
                result["race_witness"] = {
                    **race.stats(),
                    "missing_from_static": sorted(
                        race.cross_check(build_static_shared())),
                }
            return result
        finally:
            if race is not None:
                from ..utils import race_witness as _rw
                _rw.disarm()
            if witness is not None:
                from ..utils import lock_witness as _lw
                _lw.disarm()
            self.injector.disarm_all()
            self._shutdown()
