"""SLO gate: pass/fail evaluation of a chaos-replay run.

The gate reads the same surfaces production observability exposes — the
nomad-trace lifecycle summary (``nomad.trace.eval_ms.p99``,
``slowest_inflight_ms``), the replay driver's measured placement
throughput, and the post-run state-store invariant sweep — and reduces
them to a list of named checks plus a single ``passed`` bit. A chaos
run without a gate is an anecdote; with one it is a regression test.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class SLOThresholds:
    """Bounds a chaos run must stay inside to pass.

    ``None`` disables a check (it reports as skipped, not passed —
    the artifact still shows the observed value).
    """
    eval_ms_p99_max: Optional[float] = 2000.0
    slowest_inflight_ms_max: Optional[float] = 10_000.0
    throughput_min_allocs_per_s: Optional[float] = 10.0
    require_zero_lost: bool = True
    require_zero_duplicated: bool = True
    require_converged: bool = True
    # failover MTTR bounds (crash-recovery runs; the result's "failover"
    # block comes from nomad_tpu.trace.failover via CrashReplay)
    failover_new_leader_ms_max: Optional[float] = None
    failover_first_commit_ms_max: Optional[float] = None
    require_rejoin: bool = False
    # minimum critical-path attribution coverage (the result's
    # "bottleneck_report" block from nomad_tpu.trace.attribution): below
    # this the instrumentation lost track of where the wall went and the
    # run's bottleneck claim is untrustworthy
    attribution_coverage_min: Optional[float] = None
    # same floor for the STITCHED cross-process ledger (the result's
    # "stitched" block from nomad_tpu.trace.stitch + attribution via
    # the crash harness's Trace.Export collector)
    stitched_attribution_coverage_min: Optional[float] = None
    # capacity-pressure bounds (the result's "capacity" block from
    # nomad_tpu.trace.capacity via ChurnReplay): the saturated-regime
    # gates — evals must actually have parked (peak_min), placement must
    # follow capacity fast (p99), the storm must not convoy the pipeline
    # (flatline), the blocked depth must drain by trace end, and the
    # unblock path must demonstrably batch (mean batch size)
    blocked_peak_min: Optional[int] = None
    unblock_to_place_p99_ms_max: Optional[float] = None
    storm_flatline_s_max: Optional[float] = None
    blocked_drain_frac_max: Optional[float] = None
    unblock_batch_mean_min: Optional[float] = None

    def to_dict(self) -> Dict[str, object]:
        return {
            "eval_ms_p99_max": self.eval_ms_p99_max,
            "slowest_inflight_ms_max": self.slowest_inflight_ms_max,
            "throughput_min_allocs_per_s": self.throughput_min_allocs_per_s,
            "require_zero_lost": self.require_zero_lost,
            "require_zero_duplicated": self.require_zero_duplicated,
            "require_converged": self.require_converged,
            "failover_new_leader_ms_max": self.failover_new_leader_ms_max,
            "failover_first_commit_ms_max": self.failover_first_commit_ms_max,
            "require_rejoin": self.require_rejoin,
            "attribution_coverage_min": self.attribution_coverage_min,
            "stitched_attribution_coverage_min":
                self.stitched_attribution_coverage_min,
            "blocked_peak_min": self.blocked_peak_min,
            "unblock_to_place_p99_ms_max": self.unblock_to_place_p99_ms_max,
            "storm_flatline_s_max": self.storm_flatline_s_max,
            "blocked_drain_frac_max": self.blocked_drain_frac_max,
            "unblock_batch_mean_min": self.unblock_batch_mean_min,
        }


class SLOGate:
    """Evaluate a replay result dict against thresholds.

    Expects the shape ``ChurnReplay.run`` produces:

    - ``trace_summary``: lifecycle ``summary()`` dict (``eval_ms_p99``,
      ``slowest_inflight_ms``, ...)
    - ``throughput_allocs_per_s``: allocs ever created / replay seconds
    - ``invariants``: the sweep dict (``lost``, ``duplicated``,
      ``orphaned``, ``converged``, ``violations`` list)
    """

    def __init__(self, thresholds: Optional[SLOThresholds] = None) -> None:
        self.thresholds = thresholds or SLOThresholds()

    def evaluate(self, result: Dict[str, object]) -> Dict[str, object]:
        th = self.thresholds
        summary = result.get("trace_summary") or {}
        inv = result.get("invariants") or {}
        checks: List[Dict[str, object]] = []

        def check(name: str, observed, bound, ok: Optional[bool]) -> None:
            checks.append({
                "name": name,
                "observed": observed,
                "bound": bound,
                "passed": ok,      # None == skipped (no bound configured)
            })

        p99 = summary.get("eval_ms_p99")
        if th.eval_ms_p99_max is None:
            check("eval_ms_p99", p99, None, None)
        else:
            check("eval_ms_p99", p99, th.eval_ms_p99_max,
                  p99 is not None and p99 <= th.eval_ms_p99_max)

        slowest = summary.get("slowest_inflight_ms")
        if th.slowest_inflight_ms_max is None:
            check("slowest_inflight_ms", slowest, None, None)
        else:
            # no in-flight work at read time reads as 0/None: that passes
            check("slowest_inflight_ms", slowest, th.slowest_inflight_ms_max,
                  slowest is None or slowest <= th.slowest_inflight_ms_max)

        tput = result.get("throughput_allocs_per_s")
        if th.throughput_min_allocs_per_s is None:
            check("placement_throughput", tput, None, None)
        else:
            check("placement_throughput", tput, th.throughput_min_allocs_per_s,
                  tput is not None and tput >= th.throughput_min_allocs_per_s)

        if th.require_zero_lost:
            lost = inv.get("lost")
            check("zero_lost_allocations", lost, 0, lost == 0)
        if th.require_zero_duplicated:
            dup = inv.get("duplicated")
            check("zero_duplicated_allocations", dup, 0, dup == 0)
        if th.require_converged:
            conv = inv.get("converged")
            check("converged", conv, True, bool(conv))

        fo = result.get("failover") or {}
        if th.failover_new_leader_ms_max is not None:
            v = fo.get("time_to_new_leader_ms")
            check("failover_time_to_new_leader_ms", v,
                  th.failover_new_leader_ms_max,
                  v is not None and v <= th.failover_new_leader_ms_max)
        if th.failover_first_commit_ms_max is not None:
            v = fo.get("time_to_first_commit_ms")
            check("failover_time_to_first_commit_ms", v,
                  th.failover_first_commit_ms_max,
                  v is not None and v <= th.failover_first_commit_ms_max)
        if th.require_rejoin:
            rejoined = fo.get("rejoined")
            check("killed_server_rejoined", rejoined, True, bool(rejoined))

        if th.attribution_coverage_min is not None:
            rep = result.get("bottleneck_report") or {}
            cov = rep.get("coverage")
            check("attribution_coverage", cov, th.attribution_coverage_min,
                  cov is not None and cov >= th.attribution_coverage_min)
        if th.stitched_attribution_coverage_min is not None:
            rep = (result.get("stitched") or {}).get("report") or {}
            cov = rep.get("coverage")
            check("stitched_attribution_coverage", cov,
                  th.stitched_attribution_coverage_min,
                  cov is not None
                  and cov >= th.stitched_attribution_coverage_min)

        cap = result.get("capacity") or {}
        if th.blocked_peak_min is not None:
            v = cap.get("peak_blocked")
            check("blocked_peak", v, th.blocked_peak_min,
                  v is not None and v >= th.blocked_peak_min)
        if th.unblock_to_place_p99_ms_max is not None:
            v = cap.get("unblock_to_place_ms_p99")
            check("unblock_to_place_ms_p99", v, th.unblock_to_place_p99_ms_max,
                  v is not None and v <= th.unblock_to_place_p99_ms_max)
        if th.storm_flatline_s_max is not None:
            v = cap.get("max_flatline_s_while_blocked")
            check("storm_flatline_s", v, th.storm_flatline_s_max,
                  v is not None and v <= th.storm_flatline_s_max)
        if th.blocked_drain_frac_max is not None:
            # final blocked depth as a fraction of peak; None peak means
            # the run never saturated, which blocked_peak_min calls out —
            # an unsaturated run trivially drained
            v = cap.get("blocked_drain_frac")
            check("blocked_drain_frac", v, th.blocked_drain_frac_max,
                  v is None or v <= th.blocked_drain_frac_max)
        if th.unblock_batch_mean_min is not None:
            v = cap.get("unblock_batch_size_mean")
            check("unblock_batch_size_mean", v, th.unblock_batch_mean_min,
                  v is not None and v >= th.unblock_batch_mean_min)

        passed = all(c["passed"] is not False for c in checks)
        return {
            "passed": passed,
            "checks": checks,
            "thresholds": th.to_dict(),
        }
