"""Deterministic churn/chaos traces: timestamped events, reproducible by seed.

A trace is a sorted list of ``ChaosEvent`` records — the full schedule of
everything the replay driver will do to the cluster: job registrations
and stops, destructive rollouts, high-priority arrivals, node drains and
restores, heartbeat mutes (TTL expiries), fault-window arms/disarms, and
at most one mid-run leader kill. ``generate_trace(seed)`` is a pure
function of its arguments (``random.Random(seed)`` only, no wall clock),
so the same seed always yields the same event trace — the property
``tests/test_chaos.py::test_trace_deterministic_by_seed`` pins.

Shape invariants the generator maintains (so a replay can settle):

- every ``drain_node`` has a matching ``undrain_node`` before the
  recovery tail;
- every ``mute_node`` (heartbeat expiry) has a matching ``unmute_node``;
- every ``arm_fault`` has a matching ``disarm_fault``;
- all disruption ends by ``recovery_frac * duration_s`` — the tail is
  clean air for the cluster to converge in before the SLO gate reads it.

Event kinds and their args:

====================  =====================================================
``register_job``      job_id, count, cpu, memory_mb, priority
``stop_job``          job_id (deregister, purge=False)
``rollout``           job_id, cpu [, canary] (resource-bump update; with
                      ``canary`` set it is a CANARIED deployment update —
                      that many canary allocs stage first and the rollout
                      only proceeds on promotion; without it the update is
                      destructive and replaces every alloc at once)
``hipri_job``         job_id, count, cpu, memory_mb [, priority]
                      (priority-80 arrival by default)
``drain_node``        node_idx
``undrain_node``      node_idx
``mute_node``         node_idx (stop heartbeating it: TTL expires, node
                      marked down, allocs lost + rescheduled)
``unmute_node``       node_idx (resume heartbeats: node returns READY)
``arm_fault``         point, mode, prob, delay_s, max_fires
``disarm_fault``      point
``preempt_pressure``  wave, filler_count, filler_cpu, memory_mb —
                      low-priority saturation: enable service-scheduler
                      preemption and register a priority-10 filler job
                      sized to soak node capacity (the generator follows
                      it with a priority-90 ``hipri_job`` burst that must
                      place by evicting fillers)
``preempt_release``   wave — deregister that wave's filler job (paired
                      before the recovery tail so the sweep converges)
``saturate``          wave, job_count, count, cpu, memory_mb — submit
                      job_count real jobs in one burst, sized well past
                      free capacity: placements fail and their evals
                      park in BlockedEvals (the saturated regime). The
                      jobs are fleet, not pressure — the sweep requires
                      them placed once capacity arrives
``capacity_release``  wave, node_count — register node_count fresh READY
                      nodes in one burst; every registration fires the
                      capacity-change trigger, so the parked evals
                      re-enqueue as an unblock storm through the
                      coalesced batch path
``leader_kill``       (none) — abrupt leader loss mid-run. In-proc replay
                      realizes it as a leadership transfer; the crash
                      harness as a real SIGKILL -9 of the leader process
====================  =====================================================
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional


@dataclass(frozen=True)
class ChaosEvent:
    t: float           # seconds from replay start
    kind: str
    args: dict = field(default_factory=dict)

    def to_dict(self) -> Dict[str, object]:
        return {"t": round(self.t, 4), "kind": self.kind, "args": dict(self.args)}


def trace_to_jsonable(trace: List[ChaosEvent]) -> List[Dict[str, object]]:
    return [ev.to_dict() for ev in trace]


def trace_kind_counts(trace: List[ChaosEvent]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for ev in trace:
        out[ev.kind] = out.get(ev.kind, 0) + 1
    return dict(sorted(out.items()))


# fault windows the generator draws from: (point, mode, prob, delay_s)
_FAULT_MENU = (
    ("device_dispatch", "fail", 0.5, 0.0),
    ("device_dispatch", "delay", 0.5, 0.05),
    ("plan_apply", "fail", 0.3, 0.0),
    ("broker_ack", "fail", 0.25, 0.0),
    ("raft_apply", "fail", 0.05, 0.0),
    ("heartbeat", "fail", 0.5, 0.0),
)


def generate_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    n_nodes: int = 100,
    n_jobs: int = 20,
    tg_count: int = 8,
    stop_frac: float = 0.25,
    rollout_frac: float = 0.2,
    n_drains: int = 2,
    n_expiries: int = 2,
    n_hipri: int = 1,
    n_fault_windows: int = 3,
    leader_kill: bool = True,
    recovery_frac: float = 0.8,
    cpu: int = 200,
    memory_mb: int = 128,
    canary_frac: float = 0.0,
    n_preempt_waves: int = 0,
    n_saturate_waves: int = 0,
    saturate_jobs: int = 8,
    release_nodes: int = 0,
) -> List[ChaosEvent]:
    """Build a seeded churn schedule over ``duration_s`` trace-seconds.

    Phases: an initial registration wave over the first 20% of the
    window, overlapping churn (stops+replacements, rollouts, drains,
    TTL expiries, high-priority arrivals, fault windows, preemption
    waves, the leader kill) through ``recovery_frac``, then a clean
    recovery tail.

    ``canary_frac`` of the rollouts become canaried deployment updates;
    ``n_preempt_waves`` adds paired preempt_pressure/preempt_release
    waves (each with a hipri burst between them); ``n_saturate_waves``
    adds paired saturate/capacity_release waves (``saturate_jobs`` jobs
    past capacity, then ``release_nodes`` fresh nodes — the unblock
    storm). All default off, and when off the generator's rng
    consumption is unchanged — existing seeds keep producing
    byte-identical traces.
    """
    rng = Random(seed)
    events: List[ChaosEvent] = []
    recover_by = duration_s * recovery_frac

    def jitter(lo: float, hi: float) -> float:
        return lo + rng.random() * (hi - lo)

    # -- initial wave: the steady-state fleet --------------------------
    job_ids: List[str] = []
    for i in range(n_jobs):
        jid = f"churn-{i}"
        job_ids.append(jid)
        events.append(ChaosEvent(
            jitter(0.0, duration_s * 0.2), "register_job",
            {"job_id": jid, "count": tg_count, "cpu": cpu,
             "memory_mb": memory_mb, "priority": 50},
        ))

    churn_lo, churn_hi = duration_s * 0.2, recover_by

    # -- stop + replacement churn --------------------------------------
    n_stops = int(n_jobs * stop_frac)
    stopped = rng.sample(job_ids, n_stops) if n_stops else []
    for si, jid in enumerate(stopped):
        t = jitter(churn_lo, churn_hi * 0.9)
        events.append(ChaosEvent(t, "stop_job", {"job_id": jid}))
        # replacement keeps fleet load roughly level
        events.append(ChaosEvent(
            min(t + jitter(0.3, 1.5), recover_by), "register_job",
            {"job_id": f"churn-r{si}", "count": tg_count, "cpu": cpu,
             "memory_mb": memory_mb, "priority": 50},
        ))

    # -- rollouts (destructive, plus an optional canaried head) --------
    rollable = [j for j in job_ids if j not in stopped]
    rolled = rng.sample(rollable, min(len(rollable), int(n_jobs * rollout_frac)))
    n_canary = int(round(len(rolled) * canary_frac)) if canary_frac > 0 else 0
    for ri, jid in enumerate(rolled):
        args = {"job_id": jid, "cpu": cpu + 50}
        if ri < n_canary:
            # canaried rollouts need time for stage -> health -> promote
            # -> roll before the recovery tail, so bound them earlier
            args["canary"] = max(1, tg_count // 4)
            t = jitter(churn_lo, churn_hi * 0.7)
        else:
            t = jitter(churn_lo, churn_hi)
        events.append(ChaosEvent(t, "rollout", args))

    # -- high-priority arrivals ----------------------------------------
    for i in range(n_hipri):
        events.append(ChaosEvent(
            jitter(churn_lo, churn_hi), "hipri_job",
            {"job_id": f"hipri-{i}", "count": max(2, tg_count // 2),
             "cpu": cpu * 2, "memory_mb": memory_mb * 2},
        ))

    # -- node drains (paired restore) ----------------------------------
    drain_pool = list(range(n_nodes))
    rng.shuffle(drain_pool)
    for i in range(min(n_drains, len(drain_pool))):
        idx = drain_pool[i]
        t = jitter(churn_lo, churn_hi * 0.85)
        events.append(ChaosEvent(t, "drain_node", {"node_idx": idx}))
        events.append(ChaosEvent(
            min(t + jitter(0.5, 2.0), recover_by),
            "undrain_node", {"node_idx": idx},
        ))

    # -- heartbeat TTL expiries (paired resume) ------------------------
    for i in range(min(n_expiries, max(0, len(drain_pool) - n_drains))):
        idx = drain_pool[n_drains + i]
        t = jitter(churn_lo, churn_hi * 0.8)
        events.append(ChaosEvent(t, "mute_node", {"node_idx": idx}))
        events.append(ChaosEvent(
            min(t + jitter(1.0, 3.0), recover_by),
            "unmute_node", {"node_idx": idx},
        ))

    # -- fault windows (paired disarm) ---------------------------------
    menu = list(_FAULT_MENU)
    for i in range(n_fault_windows):
        point, mode, prob, delay_s = menu[i % len(menu)] if i < len(menu) \
            else rng.choice(menu)
        t = jitter(churn_lo, churn_hi * 0.8)
        events.append(ChaosEvent(t, "arm_fault", {
            "point": point, "mode": mode, "prob": prob,
            "delay_s": delay_s, "max_fires": None,
        }))
        events.append(ChaosEvent(
            min(t + jitter(1.0, 3.0), recover_by),
            "disarm_fault", {"point": point},
        ))

    # -- preemption-pressure waves (paired release) --------------------
    # each wave: low-priority fillers soak capacity, a priority-90 burst
    # arrives into the saturated cluster (placing it requires the service
    # scheduler to evict fillers), then the fillers are released before
    # the recovery tail so the sweep converges
    for i in range(n_preempt_waves):
        t = jitter(churn_lo, churn_hi * 0.7)
        events.append(ChaosEvent(t, "preempt_pressure", {
            "wave": i,
            "filler_count": max(4, tg_count),
            "filler_cpu": cpu * 3,
            "memory_mb": memory_mb,
        }))
        events.append(ChaosEvent(
            min(t + jitter(0.8, 1.5), recover_by), "hipri_job",
            {"job_id": f"preempt-hi-{i}", "count": max(2, tg_count // 2),
             "cpu": cpu * 2, "memory_mb": memory_mb, "priority": 90},
        ))
        events.append(ChaosEvent(
            min(t + jitter(2.5, 4.0), recover_by),
            "preempt_release", {"wave": i},
        ))

    # -- saturation waves (paired capacity release) --------------------
    # each wave: a burst of real jobs well past free capacity parks its
    # evals in BlockedEvals; the paired node-registration burst lands
    # before the recovery tail and storms them back out through the
    # coalesced unblock path (an armed autoscaler covers any remainder)
    for i in range(n_saturate_waves):
        t = jitter(churn_lo, churn_hi * 0.55)
        events.append(ChaosEvent(t, "saturate", {
            "wave": i,
            "job_count": saturate_jobs,
            "count": tg_count,
            "cpu": cpu,
            "memory_mb": memory_mb,
        }))
        events.append(ChaosEvent(
            min(t + jitter(1.5, 3.0), recover_by * 0.9),
            "capacity_release", {"wave": i, "node_count": release_nodes},
        ))

    # -- the leader kill -----------------------------------------------
    if leader_kill:
        events.append(ChaosEvent(
            jitter(duration_s * 0.4, duration_s * 0.6), "leader_kill", {},
        ))

    # stable order: time, then kind/args for deterministic ties
    events.sort(key=lambda ev: (ev.t, ev.kind, sorted(ev.args.items())))
    return events
