"""The `nomad` CLI (reference command/ package)."""

from .main import main

__all__ = ["main"]
