"""-json / -t output formatting for status commands.

Mirrors reference ``command/data_format.go``: ``DataFormat("json", ...)``
pretty-prints the API payload with 4-space indentation;
``DataFormat("template", tmpl)`` renders a Go text/template over it.
This implementation covers the template subset operators actually script
against the CLI with (the patterns in the reference's docs and tests):

  - ``{{.Field.Sub}}``   dotted field access on the API JSON shape
  - ``{{.}}``            the current value
  - ``{{range .X}}...{{end}}``  iteration (over lists or map values),
    rebinding ``.`` to each element; nests arbitrarily
  - ``{{if .X}}...{{else}}...{{end}}``  truthiness guard
  - ``{{"..."}}``        string literals (``{{"\\n"}}`` newlines)
  - ``{{len .X}}``       length

Unsupported constructs raise a formatting error (exit 1) rather than
printing wrong data — matching the reference's behavior of surfacing
template errors verbatim.
"""
from __future__ import annotations

import json
import re
from typing import Any, List, Tuple


class FormatError(Exception):
    pass


def format_data(use_json: bool, tmpl: str, data: Any) -> str:
    """The Format() helper every status command shares
    (data_format.go:76): -json and -t are mutually exclusive; -json
    matches the reference's 4-space-indent codec config."""
    if use_json and tmpl:
        raise FormatError("json format does not support template option.")
    if use_json:
        return json.dumps(data, indent=4, sort_keys=True)
    if tmpl:
        return render_template(tmpl, data)
    raise FormatError("no format specified")


# ---------------------------------------------------------------------------
# Go text/template subset
# ---------------------------------------------------------------------------

_ACTION = re.compile(r"\{\{(.*?)\}\}", re.DOTALL)

# AST nodes: ("text", str) | ("expr", str) | ("range", str, body)
#          | ("if", str, body, else_body)


def _parse(tmpl: str) -> List[tuple]:
    tokens: List[tuple] = []
    pos = 0
    for m in _ACTION.finditer(tmpl):
        if m.start() > pos:
            tokens.append(("text", tmpl[pos:m.start()]))
        tokens.append(("action", m.group(1).strip()))
        pos = m.end()
    if pos < len(tmpl):
        tokens.append(("text", tmpl[pos:]))

    def build(i: int, closers: Tuple[str, ...]) -> Tuple[List[tuple], int, str]:
        nodes: List[tuple] = []
        while i < len(tokens):
            kind, val = tokens[i]
            if kind == "text":
                nodes.append(("text", val))
                i += 1
                continue
            word = val.split(None, 1)[0] if val else ""
            if word in closers:
                return nodes, i, word
            if word == "range":
                body, i, closer = build(i + 1, ("end",))
                nodes.append(("range", val[len("range"):].strip(), body))
                i += 1
            elif word == "if":
                body, i, closer = build(i + 1, ("else", "end"))
                else_body: List[tuple] = []
                if closer == "else":
                    else_body, i, _ = build(i + 1, ("end",))
                nodes.append(("if", val[len("if"):].strip(), body, else_body))
                i += 1
            elif word in ("end", "else"):
                raise FormatError(f"template: unexpected {{{{{word}}}}}")
            else:
                nodes.append(("expr", val))
                i += 1
        if closers:
            raise FormatError("template: unclosed block (missing {{end}})")
        return nodes, i, ""

    nodes, _, _ = build(0, ())
    return nodes


# Backslash escape sequences in template string literals. Only these are
# rewritten; every other character passes through verbatim — a blanket
# unicode_escape decode of the whole literal mojibake'd non-ASCII text
# (each UTF-8 byte of "café" decoded as its own latin-1 codepoint).
_ESCAPE_SEQ = re.compile(
    r"\\(?:u[0-9a-fA-F]{4}|U[0-9a-fA-F]{8}|x[0-9a-fA-F]{2}|[0-7]{1,3}|.)",
    re.DOTALL,
)


def _unescape_literal(raw: str) -> str:
    def repl(m: "re.Match[str]") -> str:
        seq = m.group(0)
        try:
            # unicode_escape is safe HERE: the match is pure ASCII
            return seq.encode("ascii").decode("unicode_escape")
        except UnicodeEncodeError:
            raise FormatError(f"template: bad escape sequence {seq!r}")

    return _ESCAPE_SEQ.sub(repl, raw)


def _resolve(expr: str, scope: Any) -> Any:
    expr = expr.strip()
    if expr == ".":
        return scope
    if len(expr) >= 2 and expr[0] == '"' and expr[-1] == '"':
        return _unescape_literal(expr[1:-1])
    if expr.startswith("len "):
        v = _resolve(expr[4:], scope)
        try:
            return len(v)
        except TypeError:
            raise FormatError(f"template: len of non-collection {expr!r}")
    if expr.startswith("."):
        cur = scope
        for part in expr[1:].split("."):
            if not part:
                continue
            if isinstance(cur, dict):
                cur = cur.get(part)
            elif cur is None:
                return None
            else:
                cur = getattr(cur, part, None)
        return cur
    raise FormatError(f"template: unsupported expression {expr!r}")


def _stringify(v: Any) -> str:
    if v is None:
        return "<no value>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (dict, list)):
        return json.dumps(v, sort_keys=True)
    return str(v)


def _render(nodes: List[tuple], scope: Any, out: List[str]) -> None:
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "expr":
            out.append(_stringify(_resolve(node[1], scope)))
        elif kind == "range":
            coll = _resolve(node[1], scope)
            if coll is None:
                continue
            items = list(coll.values()) if isinstance(coll, dict) else list(coll)
            for item in items:
                _render(node[2], item, out)
        elif kind == "if":
            v = _resolve(node[1], scope)
            _render(node[2] if v else node[3], scope, out)


def render_template(tmpl: str, data: Any) -> str:
    out: List[str] = []
    _render(_parse(tmpl), data, out)
    return "".join(out)
