"""CLI output helpers (reference command/helpers.go formatList/formatKV via
ryanuber/columnize)."""

from __future__ import annotations

import time
from typing import Iterable, List, Sequence


def columns(rows: Sequence[Sequence[object]], header: bool = True) -> str:
    """Align columns two-spaces apart, like columnize's default."""
    if not rows:
        return ""
    cells = [[("" if c is None else str(c)) for c in row] for row in rows]
    ncols = max(len(r) for r in cells)
    widths = [0] * ncols
    for row in cells:
        for i, c in enumerate(row):
            widths[i] = max(widths[i], len(c))
    out = []
    for row in cells:
        line = "  ".join(
            c.ljust(widths[i]) if i < len(row) - 1 else c for i, c in enumerate(row)
        )
        out.append(line.rstrip())
    return "\n".join(out)


def kv(pairs: Iterable[Sequence[object]]) -> str:
    """'Key = Value' blocks (reference formatKV)."""
    items = [(str(k), "" if v is None else str(v)) for k, v in pairs]
    if not items:
        return ""
    w = max(len(k) for k, _ in items)
    return "\n".join(f"{k.ljust(w)} = {v}" for k, v in items)


def short_id(full: str, length: int = 8) -> str:
    return (full or "")[:length]


def ago(ns: int) -> str:
    """Nanosecond timestamp -> '3m5s ago' (reference prettyTimeDiff)."""
    if not ns:
        return "<none>"
    secs = int(time.time() - ns / 1e9)
    if secs < 0:
        secs = 0
    return f"{duration(secs)} ago"


def duration(secs: int) -> str:
    if secs < 60:
        return f"{secs}s"
    if secs < 3600:
        return f"{secs // 60}m{secs % 60}s"
    if secs < 86400:
        return f"{secs // 3600}h{(secs % 3600) // 60}m"
    return f"{secs // 86400}d{(secs % 86400) // 3600}h"
