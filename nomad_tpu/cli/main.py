"""The `nomad` CLI (reference command/commands.go:57 registry + command/*.go).

Usage: python -m nomad_tpu.cli <command> [sub] [flags] [args]

Global flags (reference command/meta.go FlagSet): -address, -region,
-namespace, -token — with NOMAD_ADDR / NOMAD_REGION / NOMAD_NAMESPACE /
NOMAD_TOKEN environment fallbacks.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..api import APIError, Client, Config, QueryOptions
from .fmt import ago, columns, kv, short_id
from .monitor import monitor_eval


class CLIError(Exception):
    pass


class Ctx:
    """Parsed global flags + lazy API client."""

    def __init__(self) -> None:
        self.address = os.environ.get("NOMAD_ADDR", "http://127.0.0.1:4646")
        self.region = os.environ.get("NOMAD_REGION", "")
        self.namespace = os.environ.get("NOMAD_NAMESPACE", "")
        self.token = os.environ.get("NOMAD_TOKEN", "")
        self.ca_cert = os.environ.get("NOMAD_CACERT", "")
        self.client_cert = os.environ.get("NOMAD_CLIENT_CERT", "")
        self.client_key = os.environ.get("NOMAD_CLIENT_KEY", "")
        self.tls_skip_verify = os.environ.get(
            "NOMAD_TLS_SKIP_VERIFY", ""
        ).lower() in ("1", "true", "yes")
        self.out: Callable[[str], None] = print
        self._client: Optional[Client] = None

    @property
    def client(self) -> Client:
        if self._client is None:
            self._client = Client(
                Config(
                    address=self.address,
                    region=self.region,
                    namespace=self.namespace,
                    token=self.token,
                    ca_cert=self.ca_cert,
                    client_cert=self.client_cert,
                    client_key=self.client_key,
                    tls_skip_verify=self.tls_skip_verify,
                )
            )
        return self._client


def _split_flags(args: List[str]) -> Tuple[Dict[str, str], List[str]]:
    """Nomad-style single-dash flags: -flag, -flag=value, -flag value."""
    flags: Dict[str, str] = {}
    rest: List[str] = []

    def put(name: str, val: str) -> None:
        # repeatable flags accumulate comma-separated instead of the
        # last occurrence silently clobbering earlier ones
        if name in _REPEATABLE_FLAGS and name in flags:
            flags[name] = flags[name] + "," + val
        else:
            flags[name] = val

    i = 0
    while i < len(args):
        a = args[i]
        if a.startswith("-") and len(a) > 1 and not a[1].isdigit():
            name = a.lstrip("-")
            if "=" in name:
                name, _, val = name.partition("=")
                put(name, val)
            elif i + 1 < len(args) and not args[i + 1].startswith("-") and _wants_value(name):
                put(name, args[i + 1])
                i += 1
            else:
                flags[name] = "true"
        else:
            rest.append(a)
        i += 1
    return flags, rest


_REPEATABLE_FLAGS = {"host-volume", "meta", "retry-join", "servers", "config"}


_VALUE_FLAGS = {
    "address", "region", "namespace", "token", "job", "output", "type",
    "deadline", "meta", "payload", "name", "policy", "rules",
    "description", "bind", "http-port", "config", "version", "limit",
    "per-page", "node-class", "datacenter", "task", "dc", "s",
    "ca-file", "cert-file", "key-file", "n",
    "rpc-port", "serf-port", "retry-join", "bootstrap-expect", "data-dir",
    "servers", "encrypt", "authoritative-region", "replication-token",
    "host-volume", "peer-id", "group", "log-level", "install", "use",
    "remove", "min-quorum", "t",
}


def _wants_value(name: str) -> bool:
    return name in _VALUE_FLAGS


def _apply_global_flags(ctx: Ctx, flags: Dict[str, str]) -> None:
    if "address" in flags:
        ctx.address = flags["address"]
    if "region" in flags:
        ctx.region = flags["region"]
    if "namespace" in flags:
        ctx.namespace = flags["namespace"]
    if "token" in flags:
        ctx.token = flags["token"]


def _truthy(flags: Dict[str, str], name: str) -> bool:
    return flags.get(name, "").lower() in ("true", "1", "yes")


def _formatted(ctx: Ctx, flags: Dict[str, str], data) -> bool:
    """Shared -json / -t short-circuit for status commands (reference
    command/data_format.go:76 Format, used by ~all status commands):
    True when machine-readable output was emitted and the command should
    skip its human rendering."""
    use_json = _truthy(flags, "json")
    tmpl = flags.get("t", "")
    if not use_json and not tmpl:
        return False
    from .data_format import FormatError, format_data

    try:
        ctx.out(format_data(use_json, tmpl, data))
    except FormatError as e:
        raise CLIError(str(e))
    return True


# ---------------------------------------------------------------------------
# agent
# ---------------------------------------------------------------------------


def _parse_host_volumes(spec: str) -> Dict[str, str]:
    """-host-volume name=path[,name=path...]; malformed pairs are errors,
    not silent drops (a vanished volume fails placements obscurely)."""
    out: Dict[str, str] = {}
    for pair in spec.split(","):
        if not pair:
            continue
        if "=" not in pair:
            raise SystemExit(
                f"-host-volume expects name=path, got {pair!r}")
        name, _, path = pair.partition("=")
        out[name] = path
    return out


def cmd_agent(ctx: Ctx, args: List[str]) -> int:
    flags, _ = _split_flags(args)
    from ..agent import Agent, AgentConfig

    # precedence (reference command/agent/command.go readConfig):
    # built-in defaults < -config files/dirs (in order) < CLI flags
    cfg = AgentConfig()
    config_sources = [p for p in flags.get("config", "").split(",") if p]
    file_data = {}
    if config_sources:
        from ..agent.config_file import (
            ConfigError,
            apply_file_config,
            load_config_sources,
        )

        try:
            file_data = load_config_sources(config_sources)
            cfg = apply_file_config(cfg, file_data)
        except ConfigError as e:
            raise CLIError(str(e))

    dev = _truthy(flags, "dev")
    if dev:
        cfg.dev_mode = True
        cfg.server_enabled = True
        cfg.client_enabled = True
    if not config_sources:
        # legacy flags-only semantics: -client alone = client-only agent
        cfg.server_enabled = _truthy(flags, "server") or dev or not _truthy(flags, "client")
        cfg.client_enabled = _truthy(flags, "client") or dev
    else:
        if _truthy(flags, "server"):
            cfg.server_enabled = True
        if _truthy(flags, "client"):
            cfg.client_enabled = True
    if "name" in flags:
        cfg.name = flags["name"]
    if "region" in flags:
        cfg.region = flags["region"]
    if "dc" in flags:
        cfg.datacenter = flags["dc"]
    if "bind" in flags:
        cfg.http_bind = cfg.rpc_bind = cfg.serf_bind = flags["bind"]
    if "http-port" in flags:
        cfg.http_port = int(flags["http-port"])
    elif "http" not in (file_data.get("ports") or {}):
        # neither flag nor file chose a port: the reference default.
        # An explicit ports { http = 0 } means ephemeral and is honored.
        cfg.http_port = 4646
    if "rpc-port" in flags:
        cfg.rpc_port = int(flags["rpc-port"])
    if "serf-port" in flags:
        cfg.serf_port = int(flags["serf-port"])
    if "retry-join" in flags:
        cfg.retry_join = [a for a in flags["retry-join"].split(",") if a]
    if "bootstrap-expect" in flags:
        cfg.bootstrap_expect = int(flags["bootstrap-expect"])
    if _truthy(flags, "wire-raft"):
        cfg.wire_raft = True
    if "data-dir" in flags:
        cfg.data_dir = flags["data-dir"]
    if "node-class" in flags:
        cfg.node_class = flags["node-class"]
    if "host-volume" in flags:
        cfg.host_volumes = _parse_host_volumes(flags["host-volume"])
    if "servers" in flags:
        cfg.servers = [a for a in flags["servers"].split(",") if a]
    if _truthy(flags, "acl-enabled"):
        cfg.acl_enabled = True
    if _truthy(flags, "enable-debug"):
        cfg.enable_debug = True
    if _truthy(flags, "no-gossip"):
        cfg.gossip_enabled = False
    if "ca-file" in flags:
        cfg.tls_ca_file = flags["ca-file"]
    if "cert-file" in flags:
        cfg.tls_cert_file = flags["cert-file"]
    if "key-file" in flags:
        cfg.tls_key_file = flags["key-file"]
    if _truthy(flags, "tls-http"):
        cfg.tls_http = True
    if "encrypt" in flags:
        cfg.encrypt = flags["encrypt"]
    if "authoritative-region" in flags:
        cfg.authoritative_region = flags["authoritative-region"]
    if "replication-token" in flags:
        cfg.replication_token = flags["replication-token"]

    agent = Agent(cfg)
    agent.start()
    for src in config_sources:
        ctx.out(f"==> Loaded configuration from {src}")
    ctx.out(f"==> Nomad agent started! HTTP at {agent.http_addr}")
    ctx.out("==> Nomad agent configuration:")
    ctx.out(kv([
        ("Client", agent.client is not None),
        ("Server", agent.server is not None),
        ("ACL", cfg.acl_enabled),
        ("Region", "global"),
    ]))
    stop = {"flag": False}

    def _sig(*_):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        ctx.out("==> Caught signal, gracefully shutting down")
        agent.shutdown()
    return 0


def cmd_agent_info(ctx: Ctx, args: List[str]) -> int:
    info = ctx.client.agent.self()
    ctx.out(json.dumps(info, indent=2, sort_keys=True))
    return 0


def cmd_monitor(ctx: Ctx, args: List[str]) -> int:
    """nomad monitor [-log-level <level>] [-no-follow] — stream the
    agent's logs (reference command/monitor.go over /v1/agent/monitor)."""
    flags, _ = _split_flags(args)
    level = flags.get("log-level", "info")
    if _truthy(flags, "no-follow"):
        out = ctx.client.agent.monitor(log_level=level)
        for line in out.get("Lines") or []:
            ctx.out(line.rstrip("\n"))
        return 0
    pending = b""
    try:
        sys.stdout.flush()
        for chunk in ctx.client.agent.monitor_follow(log_level=level):
            pending += chunk
            complete, sep, pending = pending.rpartition(b"\n")
            if sep:
                ctx.out(complete.decode(errors="replace"))
                sys.stdout.flush()
    except KeyboardInterrupt:
        pass
    return 0


# ---------------------------------------------------------------------------
# job family
# ---------------------------------------------------------------------------


def _read_jobfile(ctx: Ctx, path: str) -> dict:
    if path == "-":
        src = sys.stdin.read()
    else:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
    if path.endswith(".json"):
        doc = json.loads(src)
        return doc.get("Job", doc)
    return ctx.client.jobs.parse_hcl(src, canonicalize=True)


def cmd_job_run(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job run [-detach] <jobfile>")
    job = _read_jobfile(ctx, rest[0])
    out, _ = ctx.client.jobs.register(job)
    eval_id = out.get("EvalID", "")
    if not eval_id:
        ctx.out(f'Job registration successful (no evaluation: periodic or parameterized)')
        return 0
    if _truthy(flags, "detach"):
        ctx.out(f"Job registration successful")
        ctx.out(f"Evaluation ID: {eval_id}")
        return 0
    return monitor_eval(ctx.client, eval_id, ctx.out, verbose=_truthy(flags, "verbose"))


def cmd_job_plan(ctx: Ctx, args: List[str]) -> int:
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job plan <jobfile>")
    job = _read_jobfile(ctx, rest[0])
    plan, _ = ctx.client.jobs.plan(job, diff=True)
    diff = plan.get("Diff") or {}
    ctx.out(f"+/- Job: \"{job.get('ID','')}\" ({diff.get('Type','None')})")
    for tg in plan.get("Annotations", {}).get("DesiredTGUpdates", {}).items():
        name, upd = tg
        parts = [
            f"{k.lower()}: {v}"
            for k, v in sorted(upd.items())
            if isinstance(v, int) and v
        ]
        ctx.out(f"    group \"{name}\": " + (", ".join(parts) or "no changes"))
    failures = plan.get("FailedTGAllocs") or {}
    if failures:
        ctx.out("==> WARNING: failed to place all allocations:")
        for tg in failures:
            ctx.out(f"    group {tg!r}")
    ctx.out(f"Job Modify Index: {plan.get('JobModifyIndex', 0)}")
    return 1 if failures else 0


def cmd_job_status(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    c = ctx.client
    if not rest:
        jobs, _ = c.jobs.list()
        if _formatted(ctx, flags, jobs or []):
            return 0
        if not jobs:
            ctx.out("No running jobs")
            return 0
        rows = [["ID", "Type", "Priority", "Status", "Submit Date"]]
        for j in jobs:
            rows.append([j["ID"], j["Type"], j["Priority"], j["Status"], ""])
        ctx.out(columns(rows))
        return 0
    job_id = rest[0]
    job, _ = c.jobs.info(job_id)
    if _formatted(ctx, flags, job):
        return 0
    summary, _ = c.jobs.summary(job_id)
    ctx.out(kv([
        ("ID", job["ID"]),
        ("Name", job["Name"]),
        ("Submit Date", ""),
        ("Type", job["Type"]),
        ("Priority", job["Priority"]),
        ("Datacenters", ",".join(job.get("Datacenters") or [])),
        ("Namespace", job.get("Namespace", "default")),
        ("Status", job["Status"]),
        ("Periodic", bool(job.get("Periodic"))),
        ("Parameterized", bool(job.get("ParameterizedJob"))),
    ]))
    ctx.out("\nSummary")
    rows = [["Task Group", "Queued", "Starting", "Running", "Failed", "Complete", "Lost"]]
    for tg, s in sorted((summary.get("Summary") or {}).items()):
        rows.append([
            tg, s.get("Queued", 0), s.get("Starting", 0), s.get("Running", 0),
            s.get("Failed", 0), s.get("Complete", 0), s.get("Lost", 0),
        ])
    ctx.out(columns(rows))
    allocs, _ = c.jobs.allocations(job_id)
    if allocs:
        ctx.out("\nAllocations")
        rows = [["ID", "Node ID", "Task Group", "Version", "Desired", "Status", "Created"]]
        for a in allocs:
            rows.append([
                short_id(a["ID"]), short_id(a.get("NodeID", "")), a.get("TaskGroup", ""),
                a.get("JobVersion", 0), a.get("DesiredStatus", ""),
                a.get("ClientStatus", ""), ago(a.get("CreateTime", 0)),
            ])
        ctx.out(columns(rows))
    return 0


def cmd_job_stop(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job stop [-purge] [-detach] <job>")
    out, _ = ctx.client.jobs.deregister(rest[0], purge=_truthy(flags, "purge"))
    eval_id = out.get("EvalID", "")
    if _truthy(flags, "detach") or not eval_id:
        ctx.out(f"Evaluation ID: {eval_id}")
        return 0
    return monitor_eval(ctx.client, eval_id, ctx.out)


def cmd_job_history(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job history <job>")
    versions, _ = ctx.client.jobs.versions(rest[0])
    if _formatted(ctx, flags, versions or []):
        return 0
    for v in versions or []:
        ctx.out(kv([
            ("Version", v.get("Version", 0)),
            ("Stable", v.get("Stable", False)),
            ("Status", v.get("Status", "")),
        ]))
        ctx.out("")
    return 0


def cmd_job_revert(ctx: Ctx, args: List[str]) -> int:
    _, rest = _split_flags(args)
    if len(rest) < 2:
        raise CLIError("usage: nomad job revert <job> <version>")
    out, _ = ctx.client.jobs.revert(rest[0], int(rest[1]))
    return monitor_eval(ctx.client, out.get("EvalID", ""), ctx.out)


def cmd_job_dispatch(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job dispatch [-meta k=v] [-payload file] <job>")
    meta = {}
    if "meta" in flags:
        k, _, v = flags["meta"].partition("=")
        meta[k] = v
    payload = b""
    if "payload" in flags:
        with open(flags["payload"], "rb") as f:
            payload = f.read()
    out, _ = ctx.client.jobs.dispatch(rest[0], meta=meta, payload=payload)
    ctx.out(f"Dispatched Job ID: {out.get('DispatchedJobID','')}")
    ctx.out(f"Evaluation ID: {short_id(out.get('EvalID',''))}")
    return 0


def cmd_job_inspect(ctx: Ctx, args: List[str]) -> int:
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job inspect <job>")
    job, _ = ctx.client.jobs.info(rest[0])
    ctx.out(json.dumps({"Job": job}, indent=2, sort_keys=True))
    return 0


def cmd_job_validate(ctx: Ctx, args: List[str]) -> int:
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job validate <jobfile>")
    job = _read_jobfile(ctx, rest[0])
    res, _ = ctx.client.jobs.validate(job)
    errs = res.get("ValidationErrors") or []
    if errs:
        for e in errs:
            ctx.out(f"  * {e}")
        return 1
    ctx.out("Job validation successful")
    return 0


_EXAMPLE_JOBSPEC = '''\
# Minimal example job (reference command/job_init.go example.nomad).
# Run it with: nomad job run example.nomad
job "example" {
  datacenters = ["dc1"]
  type        = "service"

  group "cache" {
    count = 1

    restart {
      attempts = 2
      interval = "30m"
      delay    = "15s"
      mode     = "fail"
    }

    ephemeral_disk {
      size = 300
    }

    task "redis" {
      driver = "raw_exec"

      config {
        command = "/bin/sh"
        args    = ["-c", "sleep 600"]
      }

      resources {
        cpu    = 500
        memory = 256
      }
    }
  }
}
'''


def cmd_job_init(ctx: Ctx, args: List[str]) -> int:
    """Reference command/job_init.go: write an example jobspec."""
    flags, rest = _split_flags(args)
    filename = rest[0] if rest else "example.nomad"
    if os.path.exists(filename):
        ctx.out(f"Job file '{filename}' already exists")
        return 1
    with open(filename, "w") as f:
        f.write(_EXAMPLE_JOBSPEC)
    ctx.out(f"Example job file written to {filename}")
    return 0


def cmd_job_eval(ctx: Ctx, args: List[str]) -> int:
    """Reference command/job_eval.go: force a new evaluation."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job eval [-detach] <job>")
    out, _ = ctx.client.jobs.evaluate(rest[0])
    eval_id = out.get("EvalID", "")
    if _truthy(flags, "detach") or not eval_id:
        ctx.out(f"Evaluation ID: {eval_id}")
        return 0
    return monitor_eval(ctx.client, eval_id, ctx.out)


def cmd_job_deployments(ctx: Ctx, args: List[str]) -> int:
    """Reference command/job_deployments.go."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job deployments <job>")
    deps, _ = ctx.client.jobs.deployments(rest[0])
    if _formatted(ctx, flags, deps or []):
        return 0
    if not deps:
        ctx.out("No deployments found")
        return 0
    rows = [["ID", "Job Version", "Status", "Description"]]
    for d in deps:
        rows.append([
            short_id(d["ID"]), d.get("JobVersion", 0), d.get("Status", ""),
            d.get("StatusDescription", ""),
        ])
    ctx.out(columns(rows))
    return 0


def cmd_job_promote(ctx: Ctx, args: List[str]) -> int:
    """Reference command/job_promote.go: promote the job's latest
    deployment's canaries."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job promote [-group g] <job>")
    deps, _ = ctx.client.jobs.deployments(rest[0])
    active = [
        d for d in deps or []
        if d.get("Status") in ("running", "pending", "paused")
    ]
    if not active:
        ctx.out(f"No active deployment for job {rest[0]!r}")
        return 1
    latest = max(active, key=lambda d: d.get("CreateIndex", 0))
    groups = flags["group"].split(",") if "group" in flags else None
    out, _ = ctx.client.deployments.promote(latest["ID"], groups=groups)
    eval_id = out.get("EvalID", "")
    if eval_id and not _truthy(flags, "detach"):
        return monitor_eval(ctx.client, eval_id, ctx.out)
    ctx.out(f"Deployment {short_id(latest['ID'])} promoted")
    return 0


def cmd_job_periodic_force(ctx: Ctx, args: List[str]) -> int:
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad job periodic force <job>")
    out, _ = ctx.client.jobs.periodic_force(rest[0])
    ctx.out(f"Evaluation ID: {out.get('EvalID','')}")
    return 0


def cmd_job(ctx: Ctx, args: List[str]) -> int:
    subs = {
        "run": cmd_job_run,
        "plan": cmd_job_plan,
        "status": cmd_job_status,
        "stop": cmd_job_stop,
        "history": cmd_job_history,
        "revert": cmd_job_revert,
        "dispatch": cmd_job_dispatch,
        "inspect": cmd_job_inspect,
        "validate": cmd_job_validate,
        "init": cmd_job_init,
        "eval": cmd_job_eval,
        "deployments": cmd_job_deployments,
        "promote": cmd_job_promote,
        "periodic": lambda c, a: cmd_job_periodic_force(c, a[1:]) if a and a[0] == "force" else _usage(c, "job periodic force <job>"),
    }
    return _dispatch(ctx, args, subs, "job")


# ---------------------------------------------------------------------------
# node family
# ---------------------------------------------------------------------------


def cmd_node_status(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    c = ctx.client
    if not rest:
        nodes, _ = c.nodes.list()
        if _formatted(ctx, flags, nodes or []):
            return 0
        rows = [["ID", "DC", "Name", "Class", "Drain", "Eligibility", "Status"]]
        for n in nodes or []:
            rows.append([
                short_id(n["ID"]), n.get("Datacenter", ""), n.get("Name", ""),
                n.get("NodeClass", ""), n.get("Drain", False),
                n.get("SchedulingEligibility", ""), n.get("Status", ""),
            ])
        ctx.out(columns(rows))
        return 0
    node, _ = c.nodes.info(_resolve_node(ctx, rest[0]))
    if _formatted(ctx, flags, node):
        return 0
    ctx.out(kv([
        ("ID", node["ID"]),
        ("Name", node.get("Name", "")),
        ("Class", node.get("NodeClass", "")),
        ("DC", node.get("Datacenter", "")),
        ("Drain", node.get("Drain", False)),
        ("Eligibility", node.get("SchedulingEligibility", "")),
        ("Status", node.get("Status", "")),
    ]))
    allocs, _ = c.nodes.allocations(node["ID"])
    if allocs:
        ctx.out("\nAllocations")
        rows = [["ID", "Job ID", "Task Group", "Desired", "Status"]]
        for a in allocs:
            rows.append([
                short_id(a["ID"]), a.get("JobID", ""), a.get("TaskGroup", ""),
                a.get("DesiredStatus", ""), a.get("ClientStatus", ""),
            ])
        ctx.out(columns(rows))
    return 0


def _resolve_node(ctx: Ctx, prefix: str) -> str:
    nodes, _ = ctx.client.nodes.list()
    matches = [n["ID"] for n in nodes or [] if n["ID"].startswith(prefix)]
    if len(matches) == 1:
        return matches[0]
    if not matches:
        raise CLIError(f"No node(s) with prefix {prefix!r} found")
    raise CLIError(f"Prefix {prefix!r} matched multiple nodes")


def cmd_node_drain(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest or not (_truthy(flags, "enable") or _truthy(flags, "disable")):
        raise CLIError("usage: nomad node drain [-enable|-disable] [-deadline dur] <node>")
    node_id = _resolve_node(ctx, rest[0])
    spec = None
    if _truthy(flags, "enable"):
        deadline_ns = 3600 * 10**9  # DefaultDrainDeadline (1h)
        if "deadline" in flags:
            from ..jobspec import parse_duration_ns

            deadline_ns = parse_duration_ns(flags["deadline"])
        spec = {
            "Deadline": deadline_ns,
            "IgnoreSystemJobs": _truthy(flags, "ignore-system"),
        }
    ctx.client.nodes.update_drain(node_id, spec, mark_eligible=_truthy(flags, "disable"))
    state = "enabled" if spec else "disabled"
    ctx.out(f"Node \"{short_id(node_id)}\" drain strategy {state}")
    return 0


def cmd_node_eligibility(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest or not (_truthy(flags, "enable") or _truthy(flags, "disable")):
        raise CLIError("usage: nomad node eligibility [-enable|-disable] <node>")
    node_id = _resolve_node(ctx, rest[0])
    eligible = _truthy(flags, "enable")
    ctx.client.nodes.toggle_eligibility(node_id, eligible)
    ctx.out(
        f"Node \"{short_id(node_id)}\" scheduling eligibility set: "
        + ("eligible for scheduling" if eligible else "ineligible for scheduling")
    )
    return 0


def cmd_node(ctx: Ctx, args: List[str]) -> int:
    return _dispatch(ctx, args, {
        "status": cmd_node_status,
        "drain": cmd_node_drain,
        "eligibility": cmd_node_eligibility,
    }, "node")


# ---------------------------------------------------------------------------
# alloc / eval / deployment
# ---------------------------------------------------------------------------


def _find_alloc(ctx: Ctx, prefix: str) -> dict:
    allocs, _ = ctx.client.allocations.list(QueryOptions(prefix=prefix))
    matches = [a for a in allocs or [] if a["ID"].startswith(prefix)]
    if len(matches) != 1:
        raise CLIError(f"prefix {prefix!r} matched {len(matches)} allocations")
    return matches[0]


def cmd_alloc_logs(ctx: Ctx, args: List[str]) -> int:
    """nomad alloc logs [-stderr] [-f] [-n <lines>] [-task <name>] <alloc-id>
    (reference command/alloc_logs.go; -f polls the offset API)."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError(
            "usage: nomad alloc logs [-stderr] [-f] [-n <lines>] [-task <name>] <alloc-id>"
        )
    match = _find_alloc(ctx, rest[0])
    task = flags.get("task") or (rest[1] if len(rest) > 1 else "")
    if not task:
        alloc, _ = ctx.client.allocations.info(match["ID"])
        tasks = sorted((alloc.get("TaskStates") or {}).keys())
        if len(tasks) != 1:
            raise CLIError(
                "allocation has multiple tasks, pass -task (have: %s)" % ", ".join(tasks)
            )
        task = tasks[0]
    log_type = "stderr" if "stderr" in flags else "stdout"
    if "n" in flags:
        try:
            n = int(flags["n"])
        except ValueError:
            raise CLIError("-n takes a line count")
        # fetch a window back from the END so -n tails the real tail, not
        # the first MB of a big log
        data, offset = ctx.client.alloc_fs.logs_at(
            match["ID"], task, log_type, offset=1 << 20, origin="end"
        )
        if n <= 0:
            lines = []
        else:
            lines = data.split(b"\n")
            if lines and lines[-1] == b"":
                lines.pop()
            lines = lines[-n:]
        if lines:
            ctx.out(b"\n".join(lines).decode(errors="replace"))
    else:
        data, offset = ctx.client.alloc_fs.logs_at(match["ID"], task, log_type)
        if data.rstrip(b"\n"):
            ctx.out(data.rstrip(b"\n").decode(errors="replace"))
    if not _truthy(flags, "f"):
        return 0
    # follow: SERVER-PUSH stream (follow=true keeps the response open and
    # the agent pushes bytes as the task writes — no client polling);
    # buffer partial lines so mid-line and mid-UTF-8 chunk boundaries
    # don't mangle output
    pending = b""
    try:
        sys.stdout.flush()
        for chunk in ctx.client.alloc_fs.logs_follow(
            match["ID"], task, log_type, offset=offset
        ):
            pending += chunk
            complete, sep, pending = pending.rpartition(b"\n")
            if sep:
                ctx.out(complete.decode(errors="replace"))
                sys.stdout.flush()  # follow mode must stream when piped
    except KeyboardInterrupt:
        pass
    except OSError:
        pass  # stream ended (agent idle-capped or went away)
    if pending:
        ctx.out(pending.decode(errors="replace"))
    return 0


def cmd_alloc_fs(ctx: Ctx, args: List[str]) -> int:
    """nomad alloc fs <alloc-id> [path] (reference command/alloc_fs.go):
    directory → listing, file → contents."""
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad alloc fs <alloc-id> [path]")
    match = _find_alloc(ctx, rest[0])
    path = rest[1] if len(rest) > 1 else "/"
    stat, _ = ctx.client.alloc_fs.stat(match["ID"], path)
    if stat.get("IsDir"):
        entries, _ = ctx.client.alloc_fs.ls(match["ID"], path)
        rows = [["Mode", "Size", "Name"]]
        for e in entries or []:
            name = e["Name"] + ("/" if e["IsDir"] else "")
            rows.append([e.get("FileMode", ""), str(e.get("Size", 0)), name])
        ctx.out(columns(rows))
    else:
        ctx.out(ctx.client.alloc_fs.cat(match["ID"], path).decode(errors="replace").rstrip("\n"))
    return 0


def cmd_alloc_restart(ctx: Ctx, args: List[str]) -> int:
    """nomad alloc restart <alloc-id> [task] (command/alloc_restart.go)."""
    _, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad alloc restart <alloc-id> [task]")
    match = _find_alloc(ctx, rest[0])
    ctx.client.allocations.restart(match["ID"], rest[1] if len(rest) > 1 else "")
    ctx.out(f'Allocation "{short_id(match["ID"])}" restarted')
    return 0


def cmd_alloc_signal(ctx: Ctx, args: List[str]) -> int:
    """nomad alloc signal [-s SIGNAL] <alloc-id> [task]."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad alloc signal [-s <signal>] <alloc-id> [task]")
    match = _find_alloc(ctx, rest[0])
    sig = flags.get("s", "SIGKILL")
    ctx.client.allocations.signal(match["ID"], sig,
                                  rest[1] if len(rest) > 1 else "")
    ctx.out(f'Signalled allocation "{short_id(match["ID"])}" with {sig}')
    return 0


def cmd_alloc_exec(ctx: Ctx, args: List[str]) -> int:
    """nomad alloc exec -task <name> <alloc-id> <cmd>... (one-shot).

    Flag parsing stops at the alloc id: everything after is the command
    verbatim (the command's own flags like ``sh -c`` must survive)."""
    flags: Dict[str, str] = {}
    i = 0
    while i < len(args) and args[i].startswith("-"):
        name = args[i].lstrip("-")
        if "=" in name:
            k, _, v = name.partition("=")
            flags[k] = v
            i += 1
        elif name in ("i", "interactive"):  # boolean flags
            flags[name] = "true"
            i += 1
        elif i + 1 < len(args):
            flags[name] = args[i + 1]
            i += 2
        else:
            raise CLIError(f"flag -{name} needs a value")
    rest = args[i:]
    if len(rest) < 2:
        raise CLIError(
            "usage: nomad alloc exec [-i] [-task <name>] <alloc-id> <cmd>..."
        )
    match = _find_alloc(ctx, rest[0])
    task = flags.get("task", "")
    if not task:
        alloc, _ = ctx.client.allocations.info(match["ID"])
        tasks = sorted((alloc.get("TaskStates") or {}).keys())
        if len(tasks) != 1:
            raise CLIError("pass -task (have: %s)" % ", ".join(tasks))
        task = tasks[0]
    if "i" in flags or "interactive" in flags:
        # INTERACTIVE: websocket session bridging this terminal's stdio to
        # the task (reference command/alloc_exec.go over execStream)
        import threading

        stream = ctx.client.allocations.exec_stream(match["ID"], task, rest[1:])

        def pump_stdin() -> None:
            try:
                while True:
                    line = sys.stdin.buffer.readline()
                    if not line:
                        stream.close_stdin()
                        return
                    stream.send_stdin(line)
            except (OSError, ValueError):
                pass

        t = threading.Thread(target=pump_stdin, daemon=True)
        t.start()
        try:
            while True:
                chunk = stream.read_output()
                if chunk is None:
                    break
                sys.stdout.buffer.write(chunk)
                sys.stdout.buffer.flush()
        except KeyboardInterrupt:
            pass
        finally:
            stream.close()
        return int(stream.exit_code or 0)
    out, _ = ctx.client.allocations.exec_task(match["ID"], task, rest[1:])
    if out.get("Output"):
        ctx.out(out["Output"].rstrip("\n"))
    return int(out.get("ExitCode", 0))


def cmd_alloc_stop(ctx: Ctx, args: List[str]) -> int:
    """Reference command/alloc_stop.go: stop + reschedule one alloc."""
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad alloc stop [-detach] <alloc-id>")
    alloc = _find_alloc(ctx, rest[0])
    out, _ = ctx.client.allocations.stop(alloc["ID"])
    eval_id = out.get("EvalID", "")
    if _truthy(flags, "detach") or not eval_id:
        ctx.out(f"Evaluation ID: {eval_id}")
        return 0
    return monitor_eval(ctx.client, eval_id, ctx.out)


def cmd_alloc_status(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad alloc status [-json] [-t <tmpl>] <alloc-id>")
    allocs, _ = ctx.client.allocations.list(QueryOptions(prefix=rest[0]))
    matches = [a for a in allocs or [] if a["ID"].startswith(rest[0])]
    if len(matches) != 1:
        raise CLIError(f"prefix {rest[0]!r} matched {len(matches)} allocations")
    alloc, _ = ctx.client.allocations.info(matches[0]["ID"])
    if _formatted(ctx, flags, alloc):
        return 0
    ctx.out(kv([
        ("ID", alloc["ID"]),
        ("Eval ID", short_id(alloc.get("EvalID", ""))),
        ("Name", alloc.get("Name", "")),
        ("Node ID", short_id(alloc.get("NodeID", ""))),
        ("Job ID", alloc.get("JobID", "")),
        ("Job Version", alloc.get("JobVersion", 0)),
        ("Client Status", alloc.get("ClientStatus", "")),
        ("Desired Status", alloc.get("DesiredStatus", "")),
        ("Created", ago(alloc.get("CreateTime", 0))),
    ]))
    states = alloc.get("TaskStates") or {}
    for task, st in sorted(states.items()):
        ctx.out(f"\nTask \"{task}\" is \"{st.get('State','')}\"")
        events = st.get("Events") or []
        if events:
            rows = [["Time", "Type", "Description"]]
            for e in events:
                rows.append([ago(e.get("Time", 0)), e.get("Type", ""), e.get("DisplayMessage", e.get("Message", ""))])
            ctx.out(columns(rows))
    metrics = alloc.get("Metrics") or {}
    if metrics.get("NodesEvaluated") is not None:
        ctx.out("\nPlacement Metrics")
        ctx.out(kv([
            ("Nodes Evaluated", metrics.get("NodesEvaluated", 0)),
            ("Nodes Filtered", metrics.get("NodesFiltered", 0)),
            ("Nodes Exhausted", metrics.get("NodesExhausted", 0)),
        ]))
    return 0


def cmd_eval_status(ctx: Ctx, args: List[str]) -> int:
    flags, rest = _split_flags(args)
    if not rest:
        raise CLIError("usage: nomad eval status [-json] [-t <tmpl>] <eval-id>")
    evals, _ = ctx.client.evaluations.list(QueryOptions(prefix=rest[0]))
    matches = [e for e in evals or [] if e["ID"].startswith(rest[0])]
    if len(matches) != 1:
        raise CLIError(f"prefix {rest[0]!r} matched {len(matches)} evaluations")
    ev, _ = ctx.client.evaluations.info(matches[0]["ID"])
    if _formatted(ctx, flags, ev):
        return 0
    ctx.out(kv([
        ("ID", ev["ID"]),
        ("Status", ev.get("Status", "")),
        ("Type", ev.get("Type", "")),
        ("TriggeredBy", ev.get("TriggeredBy", "")),
        ("Job ID", ev.get("JobID", "")),
        ("Priority", ev.get("Priority", 0)),
        ("Placement Failures", bool(ev.get("FailedTGAllocs"))),
    ]))
    return 0


def cmd_deployment(ctx: Ctx, args: List[str]) -> int:
    def dlist(ctx, a):
        flags, _rest = _split_flags(a)
        deps, _ = ctx.client.deployments.list()
        if _formatted(ctx, flags, deps or []):
            return 0
        rows = [["ID", "Job ID", "Job Version", "Status", "Description"]]
        for d in deps or []:
            rows.append([
                short_id(d["ID"]), d.get("JobID", ""), d.get("JobVersion", 0),
                d.get("Status", ""), d.get("StatusDescription", ""),
            ])
        ctx.out(columns(rows))
        return 0

    def _resolve(ctx, prefix: str) -> str:
        deps, _ = ctx.client.deployments.list()
        matches = [d for d in deps or [] if d["ID"].startswith(prefix)]
        if len(matches) != 1:
            raise CLIError(f"prefix matched {len(matches)} deployments")
        return matches[0]["ID"]

    def dstatus(ctx, a):
        flags, rest = _split_flags(a)
        if not rest:
            raise CLIError("usage: nomad deployment status [-json] [-t <tmpl>] <id>")
        d, _ = ctx.client.deployments.info(_resolve(ctx, rest[0]))
        if _formatted(ctx, flags, d):
            return 0
        ctx.out(kv([
            ("ID", d["ID"]),
            ("Job ID", d.get("JobID", "")),
            ("Job Version", d.get("JobVersion", 0)),
            ("Status", d.get("Status", "")),
            ("Description", d.get("StatusDescription", "")),
        ]))
        rows = [["Task Group", "Desired", "Placed", "Healthy", "Unhealthy", "Promoted"]]
        for tg, s in sorted((d.get("TaskGroups") or {}).items()):
            rows.append([
                tg, s.get("DesiredTotal", 0), s.get("PlacedAllocs", 0),
                s.get("HealthyAllocs", 0), s.get("UnhealthyAllocs", 0),
                s.get("Promoted", False),
            ])
        ctx.out("\nDeployed")
        ctx.out(columns(rows))
        return 0

    def dpromote(ctx, a):
        _, rest = _split_flags(a)
        if not rest:
            raise CLIError("usage: nomad deployment promote <id>")
        out, _ = ctx.client.deployments.promote(_resolve(ctx, rest[0]))
        return monitor_eval(ctx.client, out.get("EvalID", ""), ctx.out) if out.get("EvalID") else 0

    def dfail(ctx, a):
        _, rest = _split_flags(a)
        if not rest:
            raise CLIError("usage: nomad deployment fail <id>")
        ctx.client.deployments.fail(_resolve(ctx, rest[0]))
        ctx.out("Deployment marked as failed")
        return 0

    def _dpause(ctx, a, pause: bool):
        _, rest = _split_flags(a)
        if not rest:
            verb = "pause" if pause else "resume"
            raise CLIError(f"usage: nomad deployment {verb} <id>")
        ctx.client.deployments.pause(_resolve(ctx, rest[0]), pause)
        ctx.out("Deployment paused" if pause else "Deployment resumed")
        return 0

    return _dispatch(ctx, args, {
        "list": dlist, "status": dstatus, "promote": dpromote, "fail": dfail,
        # reference command/deployment_pause.go / deployment_resume.go
        "pause": lambda c, a: _dpause(c, a, True),
        "resume": lambda c, a: _dpause(c, a, False),
    }, "deployment")


# ---------------------------------------------------------------------------
# acl family
# ---------------------------------------------------------------------------


def cmd_acl(ctx: Ctx, args: List[str]) -> int:
    c = ctx.client

    def bootstrap(ctx, a):
        tok, _ = c.acl_tokens.bootstrap()
        ctx.out(kv([
            ("Accessor ID", tok["AccessorID"]),
            ("Secret ID", tok["SecretID"]),
            ("Name", tok["Name"]),
            ("Type", tok["Type"]),
            ("Global", tok.get("Global", False)),
            ("Policies", "n/a"),
        ]))
        return 0

    def policy(ctx, a):
        if not a:
            raise CLIError("usage: nomad acl policy <apply|list|info|delete>")
        sub, rest_args = a[0], a[1:]
        if sub == "apply":
            flags, rest = _split_flags(rest_args)
            if len(rest) < 2:
                raise CLIError("usage: nomad acl policy apply <name> <rules-file>")
            with open(rest[1], "r", encoding="utf-8") as f:
                rules = f.read()
            c.acl_policies.upsert({
                "Name": rest[0],
                "Description": flags.get("description", ""),
                "Rules": rules,
            })
            ctx.out(f"Successfully wrote {rest[0]!r} ACL policy!")
            return 0
        if sub == "list":
            pols, _ = c.acl_policies.list()
            rows = [["Name", "Description"]]
            for p in pols or []:
                rows.append([p["Name"], p.get("Description", "")])
            ctx.out(columns(rows))
            return 0
        if sub == "info":
            if not rest_args:
                raise CLIError("usage: nomad acl policy info <name>")
            p, _ = c.acl_policies.info(rest_args[0])
            ctx.out(kv([("Name", p["Name"]), ("Description", p.get("Description", ""))]))
            ctx.out("Rules\n" + p.get("Rules", ""))
            return 0
        if sub == "delete":
            if not rest_args:
                raise CLIError("usage: nomad acl policy delete <name>")
            c.acl_policies.delete(rest_args[0])
            ctx.out(f"Successfully deleted {rest_args[0]!r} ACL policy!")
            return 0
        raise CLIError(f"unknown acl policy subcommand {sub!r}")

    def token(ctx, a):
        if not a:
            raise CLIError("usage: nomad acl token <create|list|info|self|delete>")
        sub, rest_args = a[0], a[1:]
        if sub == "create":
            flags, _ = _split_flags(rest_args)
            policies = [p for p in flags.get("policy", "").split(",") if p]
            tok, _ = c.acl_tokens.create({
                "Name": flags.get("name", ""),
                "Type": flags.get("type", "client"),
                "Policies": policies,
                "Global": _truthy(flags, "global"),
            })
            ctx.out(kv([
                ("Accessor ID", tok["AccessorID"]),
                ("Secret ID", tok["SecretID"]),
                ("Name", tok.get("Name", "")),
                ("Type", tok["Type"]),
                ("Policies", ",".join(tok.get("Policies") or [])),
            ]))
            return 0
        if sub == "list":
            toks, _ = c.acl_tokens.list()
            rows = [["Name", "Type", "Global", "Accessor ID"]]
            for t in toks or []:
                rows.append([t.get("Name", ""), t["Type"], t.get("Global", False), t["AccessorID"]])
            ctx.out(columns(rows))
            return 0
        if sub == "self":
            tok, _ = c.acl_tokens.self()
            ctx.out(kv([("Accessor ID", tok["AccessorID"]), ("Name", tok.get("Name", "")), ("Type", tok["Type"])]))
            return 0
        if sub == "info":
            if not rest_args:
                raise CLIError("usage: nomad acl token info <accessor>")
            tok, _ = c.acl_tokens.info(rest_args[0])
            ctx.out(kv([("Accessor ID", tok["AccessorID"]), ("Name", tok.get("Name", "")), ("Type", tok["Type"])]))
            return 0
        if sub == "delete":
            if not rest_args:
                raise CLIError("usage: nomad acl token delete <accessor>")
            c.acl_tokens.delete(rest_args[0])
            ctx.out("Token deleted successfully")
            return 0
        raise CLIError(f"unknown acl token subcommand {sub!r}")

    return _dispatch(ctx, args, {"bootstrap": bootstrap, "policy": policy, "token": token}, "acl")


# ---------------------------------------------------------------------------
# operator / system / server / misc
# ---------------------------------------------------------------------------


def cmd_operator(ctx: Ctx, args: List[str]) -> int:
    def sched(ctx, a):
        flags, rest = _split_flags(a)
        if rest and rest[0] == "set-config":
            body = {}
            if "scheduler-algorithm" in flags:
                body["SchedulerAlgorithm"] = flags["scheduler-algorithm"]
            if "preemption-system" in flags:
                body["PreemptionConfig"] = {"SystemSchedulerEnabled": _truthy(flags, "preemption-system")}
            ctx.client.operator.scheduler_set_configuration(body)
            ctx.out("Scheduler configuration updated!")
            return 0
        cfg, _ = ctx.client.operator.scheduler_get_configuration()
        ctx.out(json.dumps(cfg, indent=2, sort_keys=True))
        return 0

    def raft(ctx, a):
        flags, rest = _split_flags(a)
        if rest and rest[0] == "remove-peer":
            # reference command/operator_raft_remove.go
            peer = flags.get("peer-id", "") or (rest[1] if len(rest) > 1 else "")
            if not peer:
                raise CLIError(
                    "usage: nomad operator raft remove-peer -peer-id=<id>"
                )
            ctx.client.operator.raft_remove_peer(peer)
            ctx.out(f"Removed peer {peer}")
            return 0
        if rest and rest[0] not in ("list-peers",):
            raise CLIError(
                "usage: nomad operator raft [list-peers | remove-peer -peer-id=<id>]"
            )
        raftcfg, _ = ctx.client.operator.raft_get_configuration()
        rows = [["Node", "ID", "Address", "State", "Voter"]]
        for s in raftcfg.get("Servers") or []:
            rows.append([
                s.get("Node", ""), s.get("ID", ""), s.get("Address", ""),
                "leader" if s.get("Leader") else "follower", s.get("Voter", True),
            ])
        ctx.out(columns(rows))
        return 0

    def autopilot(ctx, a):
        # reference command/operator_autopilot_get.go / _set.go
        flags, rest = _split_flags(a)
        if rest and rest[0] == "set-config":
            # read-modify-write like operator_autopilot_set.go: flags not
            # passed must keep their current values, not reset to zeros
            body, _ = ctx.client.operator.autopilot_get_configuration()
            body = dict(body or {})
            if "cleanup-dead-servers" in flags:
                body["CleanupDeadServers"] = _truthy(flags, "cleanup-dead-servers")
            if "min-quorum" in flags:
                body["MinQuorum"] = int(flags["min-quorum"])
            ctx.client.operator.autopilot_set_configuration(body)
            ctx.out("Configuration updated!")
            return 0
        cfg, _ = ctx.client.operator.autopilot_get_configuration()
        ctx.out(json.dumps(cfg, indent=2, sort_keys=True))
        return 0

    def keygen(ctx, a):
        # reference command/operator_keygen.go: 32 bytes of entropy, b64
        import os as _os

        ctx.out(base64.b64encode(_os.urandom(32)).decode())
        return 0

    def keyring(ctx, a):
        # reference command/operator_keyring.go: -list/-install/-use/-remove
        flags, _ = _split_flags(a)
        try:
            if "install" in flags:
                ctx.client.agent.keyring_op("install", flags["install"])
                ctx.out("Successfully installed key!")
            elif "use" in flags:
                ctx.client.agent.keyring_op("use", flags["use"])
                ctx.out("Successfully changed primary key!")
            elif "remove" in flags:
                ctx.client.agent.keyring_op("remove", flags["remove"])
                ctx.out("Successfully removed key!")
            else:
                out = ctx.client.agent.keyring_list()
                rows = [["Key", "Primary"]]
                primaries = out.get("PrimaryKeys") or {}
                for k in out.get("Keys") or {}:
                    rows.append([k, "yes" if k in primaries else ""])
                ctx.out(columns(rows))
        except APIError as e:
            ctx.out(f"error: {e}")
            return 1
        return 0

    return _dispatch(ctx, args, {
        "scheduler": sched,
        "scheduler-config": sched,
        "raft": raft,
        "autopilot": autopilot,
        "keygen": keygen,
        "keyring": keyring,
    }, "operator")


def cmd_system(ctx: Ctx, args: List[str]) -> int:
    def gc(ctx, a):
        ctx.client.system.garbage_collect()
        ctx.out("System GC triggered")
        return 0

    def reconcile(ctx, a):
        ctx.client.system.reconcile_summaries()
        ctx.out("Summaries reconciled")
        return 0

    return _dispatch(ctx, args, {"gc": gc, "reconcile": reconcile}, "system")


def cmd_server(ctx: Ctx, args: List[str]) -> int:
    def members(ctx, a):
        flags, _rest = _split_flags(a)
        out = ctx.client.agent.members()
        if _formatted(ctx, flags, out.get("Members") or []):
            return 0
        rows = [["Name", "Address", "Port", "Status", "Leader", "Region"]]
        for m in out.get("Members") or []:
            rows.append([
                m.get("Name", ""), m.get("Addr", ""), m.get("Port", 0),
                m.get("Status", ""), m.get("Leader", False), m.get("Region", "global"),
            ])
        ctx.out(columns(rows))
        return 0

    def join(ctx, a):
        # reference command/server_join.go
        _, rest = _split_flags(a)
        if not rest:
            raise CLIError("usage: nomad server join <addr:port> [...]")
        out = ctx.client.agent.join(rest)
        n = out.get("num_joined", 0)
        ctx.out(f"Joined {n} servers successfully")
        return 0 if n else 1

    def force_leave(ctx, a):
        # reference command/server_force_leave.go
        _, rest = _split_flags(a)
        if not rest:
            raise CLIError("usage: nomad server force-leave <node>")
        ctx.client.agent.force_leave(rest[0])
        ctx.out(f"Force-leave issued for {rest[0]}")
        return 0

    return _dispatch(ctx, args, {
        "members": members, "join": join, "force-leave": force_leave,
    }, "server")


def cmd_ui(ctx: Ctx, args: List[str]) -> int:
    ctx.out(ctx.address + "/ui/")
    return 0


def cmd_version(ctx: Ctx, args: List[str]) -> int:
    from .. import __version__

    ctx.out(f"Nomad-TPU v{__version__}")
    return 0


# ---------------------------------------------------------------------------
# registry + entry point
# ---------------------------------------------------------------------------


def _usage(ctx: Ctx, text: str) -> int:
    ctx.out(f"usage: nomad {text}")
    return 1


def _dispatch(ctx: Ctx, args: List[str], subs: Dict[str, Callable], family: str) -> int:
    if not args or args[0] not in subs:
        ctx.out(f"usage: nomad {family} <{('|'.join(subs))}>")
        return 1
    return subs[args[0]](ctx, args[1:])


COMMANDS: Dict[str, Callable[[Ctx, List[str]], int]] = {
    "agent": cmd_agent,
    "agent-info": cmd_agent_info,
    "monitor": cmd_monitor,
    "job": cmd_job,
    "node": cmd_node,
    "alloc": lambda c, a: _dispatch(
        c, a,
        {"status": cmd_alloc_status, "logs": cmd_alloc_logs, "fs": cmd_alloc_fs,
         "restart": cmd_alloc_restart, "signal": cmd_alloc_signal,
         "exec": cmd_alloc_exec, "stop": cmd_alloc_stop},
        "alloc",
    ),
    "eval": lambda c, a: _dispatch(c, a, {"status": cmd_eval_status}, "eval"),
    "deployment": cmd_deployment,
    "acl": cmd_acl,
    "operator": cmd_operator,
    "system": cmd_system,
    "server": cmd_server,
    "ui": cmd_ui,
    "version": cmd_version,
    # top-level aliases (reference keeps `nomad run` etc. working)
    "run": cmd_job_run,
    "plan": cmd_job_plan,
    "status": cmd_job_status,
    "stop": cmd_job_stop,
    "validate": cmd_job_validate,
    "inspect": cmd_job_inspect,
    "init": cmd_job_init,
    "logs": cmd_alloc_logs,
    "fs": cmd_alloc_fs,
    "exec": cmd_alloc_exec,
}


def main(argv: Optional[List[str]] = None, out: Callable[[str], None] = print) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ctx = Ctx()
    ctx.out = out
    # a "--" terminator protects pass-through arguments (alloc exec
    # commands) from global-flag peeling: nothing after it is ours
    if "--" in argv:
        cut = argv.index("--")
        passthrough = argv[cut + 1:]
        argv = argv[:cut]
    else:
        passthrough = []
    # peel global flags wherever they appear (before any --)
    flags, rest = _split_flags(argv)
    _apply_global_flags(ctx, flags)
    # put non-global flags back for the subcommand (they were consumed;
    # simplest correct approach: re-split per command from the raw argv
    # minus global flag tokens)
    cleaned: List[str] = []
    skip = False
    for i, a in enumerate(argv):
        if skip:
            skip = False
            continue
        name = a.lstrip("-").partition("=")[0]
        if a.startswith("-") and name in ("address", "region", "namespace", "token"):
            if "=" not in a and i + 1 < len(argv) and not argv[i + 1].startswith("-"):
                skip = True
            continue
        cleaned.append(a)
    cleaned.extend(passthrough)
    if not cleaned:
        out("usage: nomad <command> [args]")
        out("Commands: " + ", ".join(sorted(COMMANDS)))
        return 1
    cmd, args = cleaned[0], cleaned[1:]
    fn = COMMANDS.get(cmd)
    if fn is None:
        out(f"unknown command {cmd!r}")
        out("Commands: " + ", ".join(sorted(COMMANDS)))
        return 1
    try:
        return fn(ctx, args)
    except CLIError as e:
        out(f"Error: {e}")
        return 1
    except APIError as e:
        out(f"Error querying server: {e}")
        return 1
    except FileNotFoundError as e:
        out(f"Error: {e}")
        return 1


if __name__ == "__main__":
    sys.exit(main())
