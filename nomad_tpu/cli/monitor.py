"""Evaluation monitor: follow an eval to completion, printing placements and
failures (reference command/monitor.go)."""

from __future__ import annotations

import time
from typing import Callable

from ..api import APIError, Client
from .fmt import short_id


def monitor_eval(client: Client, eval_id: str, out: Callable[[str], None],
                 timeout: float = 60.0, verbose: bool = False) -> int:
    """Poll until the eval reaches a terminal state. Returns exit code."""
    ident = eval_id if verbose else short_id(eval_id)
    out(f"==> Monitoring evaluation \"{ident}\"")
    seen_allocs = set()
    deadline = time.time() + timeout
    last_status = ""
    while time.time() < deadline:
        try:
            ev, _ = client.evaluations.info(eval_id)
        except APIError as e:
            out(f"==> Error reading evaluation: {e}")
            return 1
        status = ev.get("Status", "")
        if status != last_status:
            out(f"    Evaluation triggered by job \"{ev.get('JobID', '')}\"")
            last_status = status
        try:
            allocs, _ = client.evaluations.allocations(eval_id)
        except APIError:
            allocs = []
        for alloc in allocs or []:
            if alloc["ID"] in seen_allocs:
                continue
            seen_allocs.add(alloc["ID"])
            out(
                f"    Allocation \"{short_id(alloc['ID'])}\" created: "
                f"node \"{short_id(alloc.get('NodeID', ''))}\", "
                f"group \"{alloc.get('TaskGroup', '')}\""
            )
        if status in ("complete", "failed", "canceled"):
            out(f"==> Evaluation \"{ident}\" finished with status \"{status}\"")
            failures = ev.get("FailedTGAllocs") or {}
            if failures:
                out("==> Failed placements:")
                for tg, metric in failures.items():
                    out(f"    Task Group \"{tg}\" (failed to place)")
                    for klass, why in (metric.get("ClassFiltered") or {}).items():
                        out(f"      * Class {klass} filtered: {why}")
                    for dim, n in (metric.get("DimensionExhausted") or {}).items():
                        out(f"      * Dimension {dim!r} exhausted on {n} nodes")
                if ev.get("BlockedEval"):
                    out(
                        f"    Evaluation \"{short_id(ev['BlockedEval'])}\" "
                        "waiting for additional capacity to place remainder"
                    )
            return 0 if status == "complete" else 2
        time.sleep(0.2)
    out(f"==> Timed out monitoring evaluation \"{ident}\"")
    return 1
