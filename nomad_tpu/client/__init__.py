"""Client agent (reference ``client/``): node runtime executing allocations."""
from .allocdir import AllocDir, TaskDir
from .allocrunner import AllocRunner
from .client import Client, ClientConfig, ServerProxy
from .fingerprint import fingerprint_node
from .taskenv import TaskEnvBuilder
from .taskrunner import TaskRunner

# importing registers the built-in drivers
from .drivers import base as _base  # noqa: F401
from .drivers import exec_driver as _exec  # noqa: F401
from .drivers import mock_driver as _mock  # noqa: F401
from .drivers import raw_exec as _raw_exec  # noqa: F401

__all__ = [
    "AllocDir",
    "AllocRunner",
    "Client",
    "ClientConfig",
    "ServerProxy",
    "TaskDir",
    "TaskEnvBuilder",
    "TaskRunner",
    "fingerprint_node",
]
