"""Allocation directory tree.

Fills the role of reference ``client/allocdir/`` (alloc_dir.go, task_dir.go):
every allocation gets ``<state_dir>/<alloc_id>/`` containing a shared
``alloc/`` dir (``data/ logs/ tmp/``) and one dir per task with
``local/ secrets/ tmp/``. The chroot-embedding half of the reference
(fs_linux.go) belongs to the isolating executor, not here.
"""
from __future__ import annotations

import os
import shutil
from dataclasses import dataclass
from typing import Dict, List

SHARED_ALLOC_DIR = "alloc"
SHARED_SUBDIRS = ("data", "logs", "tmp")
TASK_LOCAL = "local"
TASK_SECRETS = "secrets"
TASK_TMP = "tmp"


@dataclass
class TaskDir:
    """Plain path bundle so it serializes across the driver-plugin
    boundary (the reference's driver.proto carries dir paths as strings)."""

    dir: str = ""
    shared_alloc_dir: str = ""
    local_dir: str = ""
    secrets_dir: str = ""
    tmp_dir: str = ""
    log_dir: str = ""

    @classmethod
    def create(cls, alloc_dir: str, task_name: str) -> "TaskDir":
        d = os.path.join(alloc_dir, task_name)
        shared = os.path.join(alloc_dir, SHARED_ALLOC_DIR)
        return cls(
            dir=d,
            shared_alloc_dir=shared,
            local_dir=os.path.join(d, TASK_LOCAL),
            secrets_dir=os.path.join(d, TASK_SECRETS),
            tmp_dir=os.path.join(d, TASK_TMP),
            log_dir=os.path.join(shared, "logs"),
        )

    def build(self) -> None:
        for d in (self.dir, self.local_dir, self.tmp_dir):
            os.makedirs(d, exist_ok=True)
        # secrets: mode 0700, wiped on destroy
        os.makedirs(self.secrets_dir, exist_ok=True)
        os.chmod(self.secrets_dir, 0o700)


class AllocDir:
    """Directory layout for one allocation (alloc_dir.go:AllocDir)."""

    def __init__(self, base_dir: str, alloc_id: str) -> None:
        self.alloc_id = alloc_id
        self.alloc_dir = os.path.join(base_dir, alloc_id)
        self.shared_dir = os.path.join(self.alloc_dir, SHARED_ALLOC_DIR)
        self.task_dirs: Dict[str, TaskDir] = {}

    def new_task_dir(self, task_name: str) -> TaskDir:
        td = TaskDir.create(self.alloc_dir, task_name)
        self.task_dirs[task_name] = td
        return td

    def build(self) -> None:
        os.makedirs(self.alloc_dir, exist_ok=True)
        for sub in SHARED_SUBDIRS:
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)

    def destroy(self) -> None:
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    def list_files(self, rel: str = "") -> List[str]:
        root = os.path.join(self.alloc_dir, rel)
        out = []
        for dirpath, _, files in os.walk(root):
            for f in files:
                out.append(os.path.relpath(os.path.join(dirpath, f), self.alloc_dir))
        return sorted(out)
