"""Allocation runner: supervises one allocation's task runners.

Fills the role of reference ``client/allocrunner/`` — alloc_runner.go:237
Run, the prerun/postrun hook chain (alloc_runner_hooks.go:123: allocDir,
await-previous-alloc, health watcher), and ``client/allochealth/`` (the
deployment health tracker: all tasks running for ``min_healthy_time`` ⇒
healthy; any task failing ⇒ unhealthy). Consul/CSI-backed hooks have no
backend here and are omitted.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from ..structs.structs import (
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    ALLOC_DESIRED_RUN,
    Allocation,
    AllocDeploymentStatus,
    TaskState,
)
from .allocdir import AllocDir
from .taskrunner import STATE_DEAD, STATE_RUNNING, TaskRunner


class AllocRunner:
    def __init__(
        self,
        alloc: Allocation,
        base_dir: str,
        node=None,
        on_update: Optional[Callable[["AllocRunner"], None]] = None,
        prev_alloc_watcher: Optional[Callable[[], None]] = None,
        device_manager=None,
        driver_factory=None,
        consul=None,
        vault_fn=None,
        vault_addr: str = "",
    ) -> None:
        self.alloc = alloc
        self.node = node
        self.on_update = on_update
        self.prev_alloc_watcher = prev_alloc_watcher
        self.device_manager = device_manager
        self.driver_factory = driver_factory
        self.consul = consul
        self.vault_fn = vault_fn
        self.vault_addr = vault_addr
        self.logger = logging.getLogger(f"nomad_tpu.allocrunner.{alloc.id[:8]}")

        self.alloc_dir = AllocDir(base_dir, alloc.id)
        self.task_runners: Dict[str, TaskRunner] = {}
        self.deployment_status: Optional[AllocDeploymentStatus] = None
        self._destroyed = threading.Event()
        self._aborted = False  # stopped/GC'd before tasks ever started
        self._lock = threading.Lock()
        self._waiters = 0

        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        self.task_group = tg
        self._health_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def run(self, recover_handles: Optional[Dict] = None) -> None:
        # prerun hooks: await previous alloc (upstream allocs hook), allocDir
        if self.prev_alloc_watcher is not None:
            self.prev_alloc_watcher()
            # the wait can outlive the alloc: a GC/stop that landed while
            # blocked must win, or we'd start tasks nothing tracks anymore.
            # Mark the abort so client_status() reports terminal — a
            # forever-"pending" stopped alloc would block ITS replacement's
            # watcher for the full timeout.
            if self._destroyed.is_set() or self.alloc.terminal_status() or (
                self.alloc.desired_status != ALLOC_DESIRED_RUN
            ):
                self._aborted = True
                self._notify()
                return
        self.alloc_dir.build()
        if self.task_group is None:
            self.logger.error("alloc %s has no task group in job", self.alloc.id)
            return
        for task in self.task_group.tasks:
            td = self.alloc_dir.new_task_dir(task.name)
            tr = TaskRunner(
                self.alloc, task, td, node=self.node, on_state_change=self._notify,
                device_manager=self.device_manager,
                driver_factory=self.driver_factory,
                consul=self.consul,
                vault_fn=self.vault_fn,
                vault_addr=self.vault_addr,
            )
            self.task_runners[task.name] = tr  # race-ok: populated before the health-watch thread starts; Thread.start publishes
            handle = (recover_handles or {}).get(task.name)
            if handle is not None and not tr.recover(handle):
                self.logger.info("task %s not recoverable; starting fresh", task.name)
        for tr in self.task_runners.values():
            tr.run()
        # GROUP services (incl. Connect sidecar proxy services) register
        # once per alloc (reference allocrunner groupServiceHook)
        self._group_consul_ids = []
        if self.consul is not None and getattr(self.task_group, "services", None):
            address = (
                self.node.attributes.get("unique.network.ip-address", "")
                if self.node is not None else ""
            )
            try:
                self._group_consul_ids = self.consul.register_group_services(
                    self.alloc, self.task_group, address=address
                )
            except Exception as e:  # noqa: BLE001 — consul outage isn't fatal
                self.logger.warning("group consul registration failed: %s", e)
        if self.alloc.deployment_id:
            self._health_thread = threading.Thread(
                target=self._watch_health, daemon=True,
                name=f"allochealth-{self.alloc.id[:8]}",
            )
            self._health_thread.start()

    def _notify(self) -> None:
        # group Consul services deregister as soon as EVERY task is done —
        # a batch alloc that finishes on its own must not leave its group
        # service/sidecar-proxy routing to a dead endpoint until GC
        if (
            getattr(self, "_group_consul_ids", None)
            and self.task_runners
            and all(tr.done.is_set() for tr in self.task_runners.values())
        ):
            ids, self._group_consul_ids = self._group_consul_ids, []
            try:
                self.consul.deregister_ids(ids)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("group consul deregistration failed: %s", e)
        if self.on_update is not None:
            self.on_update(self)

    # -- status roll-up (alloc_runner.go clientAlloc) --------------------

    def task_states(self) -> Dict[str, TaskState]:
        return {name: tr.state for name, tr in self.task_runners.items()}

    def client_status(self) -> str:
        states = list(self.task_states().values())
        if not states:
            return ALLOC_CLIENT_COMPLETE if self._aborted else ALLOC_CLIENT_PENDING
        if any(s.state == STATE_DEAD and s.failed for s in states):
            return ALLOC_CLIENT_FAILED
        if all(s.state == STATE_DEAD for s in states):
            return ALLOC_CLIENT_COMPLETE
        if any(s.state == STATE_RUNNING for s in states):
            return ALLOC_CLIENT_RUNNING
        return ALLOC_CLIENT_PENDING

    def client_alloc(self) -> Allocation:
        """The status-sync payload (client.go allocSync entries)."""
        a = Allocation(
            id=self.alloc.id,
            namespace=self.alloc.namespace,
            job_id=self.alloc.job_id,
            task_group=self.alloc.task_group,
            node_id=self.alloc.node_id,
            deployment_id=self.alloc.deployment_id,
        )
        a.client_status = self.client_status()
        a.task_states = {k: v for k, v in self.task_states().items()}
        a.deployment_status = self.deployment_status
        a.modify_time_ns = time.time_ns()
        return a

    # -- deployment health (client/allochealth/tracker.go) ---------------

    def _watch_health(self) -> None:
        tg = self.task_group
        update = tg.update if tg is not None else None
        min_healthy_ns = update.min_healthy_time_ns if update is not None else 10 * 10**9
        deadline_ns = update.healthy_deadline_ns if update is not None else 5 * 60 * 10**9
        start = time.time_ns()
        healthy_since: Optional[int] = None
        while not self._destroyed.is_set():
            status = self.client_status()
            if status == ALLOC_CLIENT_FAILED or any(
                s.failed for s in self.task_states().values()
            ):
                self._set_health(False)
                return
            if status == ALLOC_CLIENT_RUNNING:
                now = time.time_ns()
                healthy_since = healthy_since or now
                if now - healthy_since >= min_healthy_ns:
                    self._set_health(True)
                    return
            else:
                healthy_since = None
            if time.time_ns() - start > deadline_ns:
                self._set_health(False)
                return
            time.sleep(0.05)

    def _set_health(self, healthy: bool) -> None:
        self.deployment_status = AllocDeploymentStatus(
            healthy=healthy, timestamp_ns=time.time_ns()
        )
        self._notify()

    # -- teardown --------------------------------------------------------

    def update(self, alloc: Allocation) -> None:
        """Server pushed a new version of this alloc (alloc_runner.go Update)."""
        self.alloc.desired_status = alloc.desired_status
        self.alloc.desired_transition = alloc.desired_transition
        self.alloc.modify_index = alloc.modify_index
        if alloc.desired_status != ALLOC_DESIRED_RUN:
            self.stop()

    def restart_task(self, task_name: str = "") -> None:
        """Alloc.Restart: restart one task, or all (alloc_endpoint.go)."""
        targets = (
            [self.task_runners[task_name]] if task_name
            else list(self.task_runners.values())
        )
        for tr in targets:
            tr.restart()

    def signal_task(self, task_name: str, sig: str) -> None:
        """Alloc.Signal (alloc_endpoint.go Signal)."""
        targets = (
            [self.task_runners[task_name]] if task_name
            else list(self.task_runners.values())
        )
        for tr in targets:
            tr.driver.signal_task(tr.task_id, sig)

    def exec_task(self, task_name: str, cmd, timeout_s: float = 30.0):
        """One-shot exec in a task's context."""
        tr = self.task_runners[task_name]
        return tr.driver.exec_task(tr.task_id, list(cmd), timeout_s)

    def exec_task_streaming(self, task_name: str, cmd):
        """Interactive exec session (the reference's websocket-backed
        `nomad alloc exec`, alloc_endpoint.go execStream)."""
        tr = self.task_runners[task_name]
        return tr.driver.exec_task_streaming(tr.task_id, list(cmd))

    def stop(self) -> None:
        if self.consul is not None and getattr(self, "_group_consul_ids", None):
            try:
                self.consul.deregister_ids(self._group_consul_ids)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("group consul deregistration failed: %s", e)
            self._group_consul_ids = []
        for tr in self.task_runners.values():
            tr.kill_requested.set()
        for tr in self.task_runners.values():
            tr.done.wait(timeout=15.0)
        self._notify()

    def destroy(self) -> None:
        self._destroyed.set()
        self.stop()
        self.alloc_dir.destroy()

    def wait(self, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        for tr in self.task_runners.values():
            if not tr.done.wait(timeout=max(0.0, deadline - time.monotonic())):
                return False
        return True
