"""Previous-allocation watcher + ephemeral disk migrator.

Fills the role of reference ``client/allocwatcher`` (prevAllocWatcher,
prevAllocMigrator, remotePrevAlloc): before a replacement allocation
starts, block until its ``previous_allocation`` reaches a terminal client
state, then — when the task group's ephemeral disk asks for it — carry the
old alloc's shared ``alloc/data`` over:

- previous alloc on THIS node → move (sticky) or copy the directory tree
  locally (allocwatcher localPrevAlloc);
- previous alloc on ANOTHER node → fetch the tree through the remote
  node's alloc FS API (the reference streams a tar over the FS RPC;
  this walks ls/cat over the same endpoints).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import time
import urllib.parse
import urllib.request
from typing import Callable, Optional

logger = logging.getLogger("nomad_tpu.allocwatcher")

TERMINAL_CLIENT_STATUSES = ("complete", "failed", "lost")


class PrevAllocWatcher:
    """One watcher per replacement alloc (config.go NewAllocWatcher)."""

    def __init__(
        self,
        alloc,
        prev_alloc_id: str,
        local_runner_lookup: Callable[[str], Optional[object]],
        alloc_dir_base: str,
        remote_alloc_info: Optional[Callable[[str], Optional[dict]]] = None,
        poll_interval: float = 0.2,
        timeout: float = 300.0,
        auth_token: str = "",
        tls=None,  # rpc.transport.TLSConfig for https node addresses
    ) -> None:
        self.alloc = alloc
        self.prev_alloc_id = prev_alloc_id
        self.local_runner_lookup = local_runner_lookup
        self.alloc_dir_base = alloc_dir_base
        self.remote_alloc_info = remote_alloc_info
        self.poll_interval = poll_interval
        self.timeout = timeout
        self.auth_token = auth_token
        self.tls = tls

    # -- the prerun hook --------------------------------------------------

    def wait_and_migrate(self) -> None:
        tg = (
            self.alloc.job.lookup_task_group(self.alloc.task_group)
            if self.alloc.job
            else None
        )
        disk = tg.ephemeral_disk if tg is not None else None
        terminal = self._wait_terminal()
        if not terminal:
            # the previous alloc may still be writing; moving its data out
            # from under it would corrupt both sides — skip migration
            logger.warning(
                "previous alloc %s never went terminal; skipping disk migration",
                self.prev_alloc_id,
            )
            return
        if disk is not None and (disk.migrate or disk.sticky):
            try:
                self._migrate(move=disk.sticky and not disk.migrate)
            except Exception as e:  # noqa: BLE001 — data move is best-effort
                logger.warning(
                    "ephemeral disk migration from %s failed: %s",
                    self.prev_alloc_id, e,
                )

    # -- waiting ----------------------------------------------------------

    def _wait_terminal(self) -> bool:
        """True once the previous alloc is safely terminal (or gone)."""
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            runner = self.local_runner_lookup(self.prev_alloc_id)
            if runner is not None:
                status = runner.client_status()
                if status in TERMINAL_CLIENT_STATUSES:
                    return True
            else:
                info = (
                    self.remote_alloc_info(self.prev_alloc_id)
                    if self.remote_alloc_info is not None
                    else None
                )
                if info is None:
                    return True  # previous alloc GC'd / unknown: don't block
                if info.get("client_status") in TERMINAL_CLIENT_STATUSES:
                    return True
            time.sleep(self.poll_interval)
        logger.warning(
            "gave up waiting on previous alloc %s after %.0fs",
            self.prev_alloc_id, self.timeout,
        )
        return False

    # -- migration --------------------------------------------------------

    def _migrate(self, move: bool) -> None:
        dest = os.path.join(self.alloc_dir_base, self.alloc.id, "alloc", "data")
        prev_local = os.path.join(
            self.alloc_dir_base, self.prev_alloc_id, "alloc", "data"
        )
        if os.path.isdir(prev_local):
            self._migrate_local(prev_local, dest, move)
            return
        info = (
            self.remote_alloc_info(self.prev_alloc_id)
            if self.remote_alloc_info is not None
            else None
        )
        http_addr = (info or {}).get("node_http_addr")
        if http_addr:
            self._migrate_remote(http_addr, dest)

    @staticmethod
    def _migrate_local(src: str, dest: str, move: bool) -> None:
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        if os.path.isdir(dest):
            shutil.rmtree(dest)
        if move:
            shutil.move(src, dest)
            os.makedirs(src, exist_ok=True)  # old dir keeps a valid layout
        else:
            shutil.copytree(src, dest)

    def _migrate_remote(self, http_addr: str, dest: str) -> None:
        """Pull alloc/data through the remote node's FS API
        (remotePrevAlloc migrate; reference streams a snapshot tar)."""
        os.makedirs(dest, exist_ok=True)

        def fetch(rel: str, into: str) -> None:
            entries = self._remote_json(
                http_addr, f"/v1/client/fs/ls/{self.prev_alloc_id}",
                {"path": rel},
            )
            for e in entries or []:
                sub_rel = f"{rel.rstrip('/')}/{e['Name']}"
                target = os.path.join(into, e["Name"])
                if e.get("IsDir"):
                    os.makedirs(target, exist_ok=True)
                    fetch(sub_rel, target)
                else:
                    data = self._remote_raw(
                        http_addr, f"/v1/client/fs/cat/{self.prev_alloc_id}",
                        {"path": sub_rel},
                    )
                    with open(target, "wb") as f:
                        f.write(data)
                    mode = e.get("FileMode")
                    if mode:
                        try:
                            os.chmod(target, int(str(mode), 0) & 0o777)
                        except (ValueError, OSError):
                            pass

        fetch("/alloc/data", dest)

    def _remote_raw(self, http_addr: str, path: str, params: dict) -> bytes:
        base = http_addr if "://" in http_addr else f"http://{http_addr}"
        url = f"{base}{path}?{urllib.parse.urlencode(params)}"
        req = urllib.request.Request(url)
        if self.auth_token:
            req.add_header("X-Nomad-Token", self.auth_token)
        ctx = None
        if url.startswith("https://") and self.tls is not None:
            ctx = self.tls.http_client_context()
        with urllib.request.urlopen(req, timeout=30, context=ctx) as resp:
            return resp.read()

    def _remote_json(self, http_addr: str, path: str, params: dict):
        return json.loads(self._remote_raw(http_addr, path, params) or b"null")
