"""Artifact fetching — the go-getter core the reference's artifact hook
uses (client/allocrunner/taskrunner/artifact_hook.go:1-60; jobspec
``artifact`` stanza).

Supported sources: ``http://``, ``https://`` and ``file://``. Supported
options: ``checksum`` ("sha256:<hex>", "sha512:<hex>", "md5:<hex>" or a
bare hex digest, length-detected — go-getter's checksum query/option),
``archive`` ("false" disables auto-unpack). Archives (.zip, .tar,
.tar.gz/.tgz, .tar.bz2) unpack into the destination directory, matching
go-getter's decompressor behavior. Destinations resolve inside the task
directory and path escapes are rejected (the reference validates the
same way).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import urllib.request
import zipfile
from typing import Callable, Dict, Optional


class ArtifactError(Exception):
    """Fetch/verify failure — fails the task per its restart policy, like
    the reference's artifact hook error."""


_HASHES = {"md5": hashlib.md5, "sha1": hashlib.sha1,
           "sha256": hashlib.sha256, "sha512": hashlib.sha512}
_HEX_LEN_TO_ALGO = {32: "md5", 40: "sha1", 64: "sha256", 128: "sha512"}

_ARCHIVE_SUFFIXES = (".zip", ".tar", ".tar.gz", ".tgz", ".tar.bz2", ".tbz2")


def _checksum_spec(options: Dict[str, str]):
    spec = (options or {}).get("checksum", "")
    if not spec:
        return None
    if ":" in spec:
        algo, _, want = spec.partition(":")
        algo = algo.strip().lower()
    else:
        want = spec
        algo = _HEX_LEN_TO_ALGO.get(len(spec.strip()), "")
    want = want.strip().lower()
    if algo not in _HASHES:
        raise ArtifactError(f"unsupported checksum type in {spec!r}")
    return algo, want


def _verify(path: str, algo: str, want: str) -> None:
    h = _HASHES[algo]()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    got = h.hexdigest()
    if got != want:
        raise ArtifactError(
            f"checksum mismatch: got {algo}:{got}, want {algo}:{want}"
        )


def _is_archive(name: str) -> bool:
    low = name.lower()
    return any(low.endswith(s) for s in _ARCHIVE_SUFFIXES)


def _safe_join(root: str, *parts: str) -> str:
    dest = os.path.realpath(os.path.join(root, *parts))
    root_real = os.path.realpath(root)
    if dest != root_real and not dest.startswith(root_real + os.sep):
        raise ArtifactError(f"artifact destination escapes task dir: {parts}")
    return dest


def _unpack(archive: str, dest_dir: str) -> None:
    low = archive.lower()
    if low.endswith(".zip"):
        with zipfile.ZipFile(archive) as z:
            for member in z.namelist():
                _safe_join(dest_dir, member)  # zip-slip guard
            z.extractall(dest_dir)
        return
    mode = "r"
    if low.endswith((".tar.gz", ".tgz")):
        mode = "r:gz"
    elif low.endswith((".tar.bz2", ".tbz2")):
        mode = "r:bz2"
    with tarfile.open(archive, mode) as t:
        for member in t.getmembers():
            _safe_join(dest_dir, member.name)
        # filter="data" (3.12+) also blocks symlink-escape members the
        # name check can't see; no insecure fallback
        t.extractall(dest_dir, filter="data")


def fetch_artifact(art: Dict, task_root: str,
                   interp: Optional[Callable[[str], str]] = None,
                   timeout: float = 30.0) -> str:
    """Fetch one ``artifact`` stanza into the task directory; returns the
    destination path. ``interp`` applies env interpolation to the source
    and destination strings (taskenv, like the reference)."""
    interp = interp or (lambda s: s)
    source = interp(str(art.get("source", "")))
    if not source:
        raise ArtifactError("artifact has no source")
    options = {k: interp(str(v)) for k, v in (art.get("options") or {}).items()}
    dest_rel = interp(str(art.get("destination", "") or "local"))
    dest_dir = _safe_join(task_root, dest_rel)
    os.makedirs(dest_dir, exist_ok=True)

    checksum = _checksum_spec(options)

    if source.startswith("file://"):
        src_path = source[len("file://"):]
        if not os.path.exists(src_path):
            raise ArtifactError(f"artifact source not found: {src_path}")
        fname = os.path.basename(src_path)
        local_path = os.path.join(dest_dir, fname)
        shutil.copy(src_path, local_path)
    elif source.startswith(("http://", "https://")):
        fname = os.path.basename(source.split("?", 1)[0]) or "artifact"
        local_path = os.path.join(dest_dir, fname)
        try:
            req = urllib.request.Request(source, headers={"User-Agent": "nomad-tpu"})
            with urllib.request.urlopen(req, timeout=timeout) as resp, \
                    open(local_path, "wb") as out:
                shutil.copyfileobj(resp, out)
        except ArtifactError:
            raise
        except Exception as e:  # noqa: BLE001 — network errors fail the fetch
            raise ArtifactError(f"artifact download failed: {e}") from e
    else:
        raise ArtifactError(f"unsupported artifact source scheme: {source!r}")

    if checksum is not None:
        _verify(local_path, *checksum)

    if _is_archive(fname) and options.get("archive", "").lower() != "false":
        _unpack(local_path, dest_dir)
        os.unlink(local_path)

    return dest_dir
