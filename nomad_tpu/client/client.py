"""Client agent: node lifecycle + allocation execution.

Fills the role of reference ``client/client.go`` (2,900 LoC): register
(client.go:1670), heartbeat (:1433/:1700), watch allocations via blocking
query (:1873 watchAllocations), diff + spawn/update/remove alloc runners
(:2092 runAllocs), batched alloc status sync every 200ms (:1807 allocSync),
and state restore on boot (:991). The server is reached through a
``ServerProxy`` interface — in-process today, the RPC transport binds the
same surface at the process boundary.
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs.structs import (
    ALLOC_DESIRED_RUN,
    Allocation,
    Node,
)
from .allocrunner import AllocRunner
from .fingerprint import fingerprint_node
from .state import MemDB, SqliteDB, StateDB

ALLOC_SYNC_INTERVAL = 0.2  # client.go:90 allocSyncIntv


@dataclass
class ClientConfig:
    state_dir: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    persist_state: bool = False
    heartbeat_grace: float = 0.5
    token: str = ""  # ACL token for server + cross-node fs calls
    tls: Optional[object] = None  # TLSConfig for https node addresses
    # Consul agent address for task service registration (command/agent/
    # consul ServiceClient); empty = disabled
    consul: Optional[object] = None  # integrations.consul.ConsulConfig
    # Vault address for the template hook's {{ secret }} reads (the token
    # is the TASK's derived token, never the server's)
    vault_addr: str = ""
    # host_volume stanzas (reference client config): name -> host path,
    # advertised on the node for the HostVolumeChecker
    host_volumes: Dict[str, str] = field(default_factory=dict)
    # external plugins (reference client config plugin_dir + plugin stanzas):
    # plugin_dir is scanned for nomad-driver-*/nomad-device-* executables;
    # external_drivers forces built-in drivers out-of-process (the
    # reference's go-plugin default), name → plugin config stanza
    plugin_dir: str = ""
    external_drivers: Dict[str, dict] = field(default_factory=dict)
    # built-in device plugins to run, name → config stanza (e.g.
    # {"tpu": {}} or {"mock-device": {"count": 4}}); external device
    # plugins arrive via plugin_dir discovery
    device_plugins: Dict[str, dict] = field(default_factory=dict)
    # Terminal-alloc-dir GC (reference client/gc.go AllocGarbageCollector
    # + config.go GCInterval/GCDiskUsageThreshold/GCMaxAllocs): a
    # background sweep destroys the OLDEST terminal alloc runners (and
    # their dirs) when the alloc-dir filesystem passes the usage
    # threshold or the retained-alloc count passes gc_max_allocs.
    gc_interval: float = 60.0
    gc_disk_usage_threshold: float = 80.0  # percent of the alloc-dir fs
    gc_max_allocs: int = 50


class ServerProxy:
    """The client⇆server RPC surface (the endpoints client.go dials)."""

    def __init__(self, server) -> None:
        self.server = server

    def register_node(self, node: Node) -> float:
        return self.server.register_node(node)

    def heartbeat(self, node_id: str) -> float:
        return self.server.heartbeat(node_id)

    def pull_allocs(self, node_id: str, min_index: int, timeout: float):
        """Node.GetClientAllocs blocking query: (allocs, index)."""
        state = self.server.fsm.state

        def run(s):
            return [self._with_job(s, a) for a in s.allocs_by_node(node_id)]

        return state.blocking_query(run, min_index, timeout=timeout)

    @staticmethod
    def _with_job(state, alloc: Allocation) -> Allocation:
        if alloc.job is None:
            a = alloc.copy_skip_job()
            a.job = state.job_by_id(alloc.namespace, alloc.job_id)
            return a
        return alloc

    def update_allocs(self, allocs: List[Allocation]) -> None:
        self.server.update_allocs_from_client(allocs)

    def alloc_info(self, alloc_id: str) -> Optional[dict]:
        """Status + owning-node HTTP address of any alloc (the allocwatcher's
        view of Alloc.GetAlloc + Node.GetNode)."""
        state = self.server.fsm.state
        alloc = state.alloc_by_id(alloc_id)
        if alloc is None:
            return None
        node = state.node_by_id(alloc.node_id)
        return {
            "client_status": alloc.client_status,
            "node_http_addr": node.http_addr if node is not None else "",
        }

    def derive_vault_token(
        self, alloc_id: str, task_name: str, node_id: str = "", node_secret: str = ""
    ) -> str:
        """Node.DeriveVaultToken (node_endpoint.go)."""
        return self.server.derive_vault_token(
            alloc_id, [task_name], node_id, node_secret
        )[task_name]


class Client:
    def __init__(
        self,
        proxy: ServerProxy,
        config: Optional[ClientConfig] = None,
        node: Optional[Node] = None,
    ) -> None:
        self.config = config or ClientConfig()
        self.proxy = proxy
        if not self.config.state_dir:
            self.config.state_dir = tempfile.mkdtemp(prefix="nomad-client-")
        self.alloc_dir_base = os.path.join(self.config.state_dir, "allocs")

        # external plugins register into the driver registry BEFORE
        # fingerprinting so discovered drivers land in node attributes
        # (reference: plugin managers run before fingerprint merge)
        self.plugin_catalog = None
        if self.config.plugin_dir:
            from ..plugins.catalog import Catalog

            self.plugin_catalog = Catalog(self.config.plugin_dir).discover()
        # subprocess drivers this client owns (NOT process-global: two
        # clients in one process must not share or kill each other's
        # plugin subprocesses)
        self._external_driver_instances: Dict[str, object] = {}
        self._external_lock = threading.Lock()

        # device plugins: built-ins by name plus any discovered externally
        self.device_manager = None
        device_plugins = []
        for dev_name, dev_config in self.config.device_plugins.items():
            from .devicemanager import builtin_device_plugin

            device_plugins.append(builtin_device_plugin(dev_name, dev_config))
        if self.plugin_catalog is not None:
            device_plugins.extend(self.plugin_catalog.devices.values())
        if device_plugins:
            from .devicemanager import DeviceManager

            self.device_manager = DeviceManager(device_plugins)

        # Consul service client (command/agent/consul)
        self.consul = None
        if self.config.consul is not None and getattr(self.config.consul, "address", ""):
            from ..integrations.consul import ConsulClient

            self.consul = ConsulClient(self.config.consul)

        self.node = node or Node()
        self.node.datacenter = self.config.datacenter
        self.node.node_class = self.config.node_class
        self.node.meta.update(self.config.meta)
        if self.config.host_volumes:
            from ..structs.structs import HostVolume

            for vname, vpath in self.config.host_volumes.items():
                if not os.path.isdir(vpath):
                    # the reference client refuses to start on a missing
                    # host_volume path — fail loud, not at task runtime
                    raise ValueError(
                        f"host_volume {vname!r}: path {vpath!r} is not a "
                        "directory")
                self.node.host_volumes[vname] = HostVolume(
                    name=vname, path=vpath)
        fingerprint_node(self.node)
        if self.device_manager is not None:
            self.device_manager.fingerprint_node(self.node)
            self.node.compute_class()

        self.logger = logging.getLogger(f"nomad_tpu.client.{self.node.id[:8]}")
        self.state_db: StateDB = (
            SqliteDB(self.config.state_dir) if self.config.persist_state else MemDB()
        )
        self.allocrunners: Dict[str, AllocRunner] = {}
        self._dirty: Dict[str, Allocation] = {}  # pending status syncs
        # locally GC'd alloc id -> modify_index at collection: guards
        # _run_allocs against re-adding from a stale in-flight pull
        self._gced: Dict[str, int] = {}
        self._lock = threading.RLock()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self.heartbeat_ttl = 10.0

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._restore_state()
        if self.device_manager is not None:
            # periodic re-fingerprint; device changes re-register the node
            # so the scheduler sees fresh capacity
            def _devices_changed(devices):
                from .devicemanager import DeviceManager as _DM

                _DM.apply_to_node(self.node, devices)
                self.node.compute_class()
                self.proxy.register_node(self.node)

            self.device_manager.on_devices_changed = _devices_changed
            self.device_manager.start()
        try:
            self.heartbeat_ttl = self.proxy.register_node(self.node)
        except Exception as e:  # noqa: BLE001 — no leader yet at boot
            self.logger.warning(
                "node registration failed (retrying in background): %s", e
            )
            t = threading.Thread(
                target=self._register_retry_loop, name="client-register", daemon=True
            )
            t.start()
            self._threads.append(t)
        for target, name in (
            (self._heartbeat_loop, "heartbeat"),
            (self._watch_allocations, "watchallocs"),
            (self._alloc_sync_loop, "allocsync"),
            (self._gc_loop, "gc"),
        ):
            t = threading.Thread(target=target, name=f"client-{name}", daemon=True)
            t.start()
            self._threads.append(t)

    def _register_retry_loop(self) -> None:
        """Keep trying to register until a leader exists
        (client.go:1670 retryRegisterNode)."""
        while not self._shutdown.wait(2.0):
            try:
                self.heartbeat_ttl = self.proxy.register_node(self.node)
                self.logger.info("node registered")
                return
            except Exception as e:  # noqa: BLE001
                self.logger.debug("registration retry failed: %s", e)

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._lock:
            runners = list(self.allocrunners.values())
        for ar in runners:
            ar.stop()
        self.state_db.close()
        if self.device_manager is not None:
            self.device_manager.stop()
        if self.plugin_catalog is not None:
            self.plugin_catalog.close()
        close_proxy = getattr(self.proxy, "close", None)
        if close_proxy is not None:
            close_proxy()
        # stop the subprocess drivers this client owns
        with self._external_lock:
            instances = list(self._external_driver_instances.values())
            self._external_driver_instances.clear()
        for inst in instances:
            try:
                inst.close()
            except Exception:  # noqa: BLE001
                pass

    def resolve_driver(self, name: str):
        """Driver factory for this client's task runners: external_drivers
        names get a client-owned subprocess plugin instance (respawned if
        dead); everything else resolves through the shared registry."""
        if name not in self.config.external_drivers:
            from .drivers.base import new_driver

            return new_driver(name)
        from ..plugins.base import validate_config
        from ..plugins.catalog import launch_builtin_driver
        from .drivers.base import DriverError

        with self._external_lock:
            inst = self._external_driver_instances.get(name)
            if inst is not None and inst.client.alive():
                return inst
            inst = launch_builtin_driver(name)
            drv_config = self.config.external_drivers.get(name)
            if drv_config:
                schema = inst.config_schema()
                errors = validate_config(schema, drv_config) if schema else []
                if errors:
                    inst.close()
                    raise DriverError("; ".join(errors))
                inst.set_config(drv_config)
            self._external_driver_instances[name] = inst
            return inst

    # -- restore (client.go:991) -----------------------------------------

    def _restore_state(self) -> None:
        for alloc in self.state_db.get_all_allocations():
            if alloc.terminal_status():
                continue
            # a restart mid-wait must resume the await+migrate, not skip it
            watcher = self._make_prev_watcher(alloc)
            ar = AllocRunner(
                alloc, self.alloc_dir_base, node=self.node, on_update=self._on_ar_update,
                device_manager=self.device_manager, driver_factory=self.resolve_driver,
                consul=self.consul, vault_fn=self._vault_fn(),
                vault_addr=self.config.vault_addr,
                prev_alloc_watcher=watcher,
            )
            # re-attach live tasks BEFORE the runners start, so a recovered
            # task is waited on instead of started a second time
            handles = self.state_db.get_task_handles(alloc.id)
            self.allocrunners[alloc.id] = ar
            if watcher is not None:
                # never block startup on a prev-alloc wait: registration
                # and heartbeats must begin or the server marks us down
                threading.Thread(
                    target=ar.run, kwargs={"recover_handles": handles},
                    name=f"allocrestore-{alloc.id[:8]}", daemon=True,
                ).start()
            else:
                ar.run(recover_handles=handles)

    # -- heartbeats (client.go:1700) -------------------------------------

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            interval = max(self.heartbeat_ttl / 2.0, 0.05)
            if self._shutdown.wait(timeout=interval):
                return
            try:
                self.heartbeat_ttl = self.proxy.heartbeat(self.node.id)
            except Exception:  # noqa: BLE001
                self.logger.warning("heartbeat failed; retrying")

    # -- alloc watching (client.go:1873) ---------------------------------

    # rewound pulls must persist this long before the client adopts the
    # servers' older view as the new truth (DR restore / rebuilt
    # cluster). Transient replication lag after a failover clears in
    # seconds; adopting too eagerly could resurrect an alloc this
    # client GC'd (a lagging follower still lists it desired-run and
    # the _gced guard was pruned when a newer view omitted it).
    REWIND_ADOPT_AFTER_S = 30.0

    def _watch_allocations(self) -> None:
        import time as _time

        index = 0
        rewind_t0: Optional[float] = None
        while not self._shutdown.is_set():
            try:
                allocs, new_index = self.proxy.pull_allocs(
                    self.node.id, index, timeout=1.0
                )
            except Exception:  # noqa: BLE001
                if self._shutdown.wait(timeout=1.0):
                    return
                continue
            # Act only on strictly NEWER views. A blocking-query timeout
            # returns index == min_index (nothing changed), and after a
            # server failover a lagging follower can return an OLDER
            # view than we already processed — acting on a rewound view
            # could resurrect an alloc this client GC'd (its _gced guard
            # entry is pruned once a newer view omits the id).
            if new_index < index:
                # ...unless the rewind is PERMANENT (servers restored
                # from an older snapshot / rebuilt cluster): only after
                # rewound replies persist for REWIND_ADOPT_AFTER_S do we
                # adopt the servers' view instead of wedging alloc sync
                # forever. A follower merely catching up converges and
                # returns a newer index well before the deadline, which
                # resets the streak below.
                now = _time.monotonic()
                if rewind_t0 is None:
                    rewind_t0 = now
                if now - rewind_t0 < self.REWIND_ADOPT_AFTER_S:
                    continue
                self.logger.warning(
                    "server alloc index rewound %d -> %d for over %.0fs; "
                    "adopting server view", index, new_index,
                    self.REWIND_ADOPT_AFTER_S,
                )
            elif new_index == index:
                continue
            rewind_t0 = None
            index = new_index
            self._run_allocs(allocs)

    def _run_allocs(self, server_allocs: List[Allocation]) -> None:
        """Diff server view vs runners (client.go:2092 runAllocs)."""
        with self._lock:
            existing = dict(self.allocrunners)
        server_ids = {a.id for a in server_allocs}

        for alloc in server_allocs:
            ar = existing.get(alloc.id)
            if ar is None:
                if alloc.desired_status != ALLOC_DESIRED_RUN or alloc.terminal_status():
                    continue
                gc_index = self._gced.get(alloc.id)
                if gc_index is not None and alloc.modify_index <= gc_index:
                    continue  # stale pull of a locally GC'd alloc
                self._add_alloc(alloc)
            elif alloc.modify_index > ar.alloc.modify_index:
                ar.update(alloc)
                self.state_db.put_allocation(alloc)

        # server no longer knows these allocs (GC'd): destroy
        for alloc_id, ar in existing.items():
            if alloc_id not in server_ids:
                ar.destroy()
                self.state_db.delete_allocation(alloc_id)
                with self._lock:
                    self.allocrunners.pop(alloc_id, None)

        # Prune the GC guard once the server stops reporting an alloc:
        # pulls arrive from ONE sequential loop, so an id absent from
        # this (newest) pull can never resurface in a later one — the
        # guard entry is dead weight on a long-lived node otherwise.
        with self._lock:
            for aid in [a for a in self._gced if a not in server_ids]:
                del self._gced[aid]

    def _vault_fn(self):
        fn = getattr(self.proxy, "derive_vault_token", None)
        if fn is None:
            return None
        # bind this node's identity: the server verifies (node_id, secret)
        # against the registered node before minting tokens
        node = self.node

        def derive(alloc_id: str, task_name: str) -> str:
            return fn(alloc_id, task_name, node.id, node.secret_id)

        return derive

    def _make_prev_watcher(self, alloc: Allocation):
        """Upstream-alloc hook: replacements await their predecessor and
        migrate sticky ephemeral disk (client/allocwatcher)."""
        if not alloc.previous_allocation:
            return None
        from .allocwatcher import PrevAllocWatcher

        return PrevAllocWatcher(
            alloc,
            alloc.previous_allocation,
            local_runner_lookup=lambda aid: self.allocrunners.get(aid),
            alloc_dir_base=self.alloc_dir_base,
            remote_alloc_info=getattr(self.proxy, "alloc_info", None),
            auth_token=self.config.token,
            tls=self.config.tls,
        ).wait_and_migrate

    def _add_alloc(self, alloc: Allocation) -> None:
        watcher = self._make_prev_watcher(alloc)
        ar = AllocRunner(
            alloc, self.alloc_dir_base, node=self.node, on_update=self._on_ar_update,
            device_manager=self.device_manager, driver_factory=self.resolve_driver,
            consul=self.consul, vault_fn=self._vault_fn(),
            vault_addr=self.config.vault_addr,
            prev_alloc_watcher=watcher,
        )
        with self._lock:
            self.allocrunners[alloc.id] = ar
        self.state_db.put_allocation(alloc)

        def run_runner() -> None:
            ar.run()
            for name, tr in ar.task_runners.items():
                if tr.handle is not None:
                    self.state_db.put_task_handle(alloc.id, name, tr.handle)
            self._on_ar_update(ar)

        if watcher is not None:
            # the prev-alloc wait can block for minutes; it must not stall
            # the watchallocations loop (alloc_runner.go Run is a goroutine)
            t = threading.Thread(
                target=run_runner, name=f"allocrun-{alloc.id[:8]}", daemon=True
            )
            t.start()
        else:
            run_runner()

    # -- status sync (client.go:1807 allocSync) --------------------------

    def _on_ar_update(self, ar: AllocRunner) -> None:
        with self._lock:
            self._dirty[ar.alloc.id] = ar.client_alloc()
        for name, tr in ar.task_runners.items():
            if tr.handle is not None:
                self.state_db.put_task_handle(ar.alloc.id, name, tr.handle)

    def _alloc_sync_loop(self) -> None:
        while not self._shutdown.wait(timeout=ALLOC_SYNC_INTERVAL):
            with self._lock:
                if not self._dirty:
                    continue
                batch = list(self._dirty.values())
                self._dirty.clear()
            try:
                self.proxy.update_allocs(batch)
            except Exception:  # noqa: BLE001
                with self._lock:  # retry next tick
                    for a in batch:
                        self._dirty.setdefault(a.id, a)

    # -- terminal-alloc GC (reference client/gc.go) ----------------------

    def _gc_loop(self) -> None:
        """Periodic sweep (gc.go run): destroy the oldest terminal alloc
        runners when the alloc-dir filesystem passes the usage threshold
        or the retained count passes gc_max_allocs. A long-lived node
        must not keep dead alloc dirs forever."""
        while not self._shutdown.wait(timeout=self.config.gc_interval):
            try:
                self.garbage_collect(force=False)
            except Exception:  # noqa: BLE001 — GC must never kill the loop
                self.logger.exception("alloc GC sweep failed")

    def _disk_usage_pct(self) -> float:
        import shutil as _shutil

        try:
            du = _shutil.disk_usage(self.alloc_dir_base)
            return 100.0 * du.used / max(du.total, 1)
        except OSError:
            return 0.0

    def garbage_collect(self, force: bool = True) -> int:
        """GC terminal alloc runners; ``force`` (the /v1/client/gc shape,
        gc.go CollectAll) destroys every terminal runner, otherwise only
        down to the configured thresholds, oldest-completion first.
        Returns the number of allocs collected.

        The terminal client status is pushed to the server FIRST: a
        runner destroyed while its completion still sits in the dirty
        batch would be re-added by the next pull (the server still shows
        it running) and the task would execute twice. Sync failure skips
        collection this round."""
        with self._lock:
            terminal = [
                ar for ar in self.allocrunners.values()
                if ar.client_alloc().client_terminal_status()
            ]
        if not terminal:
            return 0
        try:
            self.proxy.update_allocs([ar.client_alloc() for ar in terminal])
            with self._lock:
                for ar in terminal:
                    self._dirty.pop(ar.alloc.id, None)
        except Exception:  # noqa: BLE001 — no server: do not destroy state
            self.logger.warning("alloc GC skipped: terminal status sync failed")
            return 0
        # oldest completion first (gc.go's indexed PQ ordering)
        terminal.sort(key=lambda ar: ar.alloc.modify_time_ns or ar.alloc.create_time_ns)
        collected = 0
        for ar in terminal:
            if not force:
                over_disk = (
                    self._disk_usage_pct() >= self.config.gc_disk_usage_threshold
                )
                over_count = self.num_allocs() > self.config.gc_max_allocs
                if not over_disk and not over_count:
                    break
            self.logger.info("garbage collecting alloc %s", ar.alloc.id[:8])
            try:
                ar.destroy()
            except Exception:  # noqa: BLE001
                self.logger.exception("alloc %s destroy failed", ar.alloc.id[:8])
            self.state_db.delete_allocation(ar.alloc.id)
            with self._lock:
                self.allocrunners.pop(ar.alloc.id, None)
                # an in-flight stale pull must not resurrect it
                self._gced[ar.alloc.id] = ar.alloc.modify_index
            collected += 1
        return collected

    # -- introspection ---------------------------------------------------

    def num_allocs(self) -> int:
        with self._lock:
            return len(self.allocrunners)
