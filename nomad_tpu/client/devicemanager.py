"""Client device manager.

Fills the role of reference ``client/devicemanager`` (989 LoC): owns the
node's device plugin instances (in-process built-ins and subprocess
plugins alike — both satisfy ``DevicePlugin``), merges their fingerprints
into ``Node.NodeResources.Devices`` for the scheduler's DeviceChecker /
deviceAllocator, and at task start turns the alloc's
``AllocatedDeviceResource`` assignments into env vars + mounts via the
owning plugin's ``Reserve`` (devicemanager manager.go → instance.go).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..plugins.device import ContainerReservation, DevicePlugin
from ..structs.structs import (
    AllocatedDeviceResource,
    Node,
    NodeDeviceInstance,
    NodeDeviceResource,
)

logger = logging.getLogger("nomad_tpu.devicemanager")

GroupId = Tuple[str, str, str]  # (vendor, type, name)


class DeviceManager:
    def __init__(self, plugins: Optional[List[DevicePlugin]] = None,
                 fingerprint_interval: float = 30.0) -> None:
        self.plugins: List[DevicePlugin] = list(plugins or [])
        self.fingerprint_interval = fingerprint_interval
        self._owners: Dict[GroupId, DevicePlugin] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set by the client: called with the fresh device list when a
        # periodic fingerprint changes it (triggers node re-registration)
        self.on_devices_changed = None
        self._last: List[NodeDeviceResource] = []

    # -- fingerprint -----------------------------------------------------

    def fingerprint(self) -> List[NodeDeviceResource]:
        """One fingerprint pass over every plugin; remembers which plugin
        owns each device group for later reservation."""
        out: List[NodeDeviceResource] = []
        owners: Dict[GroupId, DevicePlugin] = {}
        for plugin in self.plugins:
            try:
                groups = plugin.fingerprint()
            except Exception as e:  # noqa: BLE001 — a sick plugin mustn't kill the node
                logger.warning("device plugin %s fingerprint failed: %s",
                               getattr(plugin, "name", "?"), e)
                continue
            for g in groups:
                res = NodeDeviceResource(
                    vendor=g.vendor,
                    type=g.type,
                    name=g.name,
                    instances=[
                        NodeDeviceInstance(id=d.id, healthy=d.healthy)
                        for d in g.devices
                    ],
                    attributes=dict(g.attributes),
                )
                out.append(res)
                owners[(g.vendor, g.type, g.name)] = plugin
        with self._lock:
            self._owners = owners
            self._last = out
        return out

    def fingerprint_node(self, node: Node) -> None:
        """Merge device groups into the node (client.go:1324
        updateNodeFromFingerprint, batchFirstFingerprints)."""
        self.apply_to_node(node, self.fingerprint())

    @staticmethod
    def apply_to_node(node: Node, devices: List[NodeDeviceResource]) -> None:
        """Write devices into BOTH node_resources and the device.*
        attributes constraints match against — they must never diverge."""
        if node.node_resources is not None:
            node.node_resources.devices = devices
        stale = [k for k in node.attributes if k.startswith("device.")]
        for k in stale:
            del node.attributes[k]
        for res in devices:
            key = f"device.{res.vendor}.{res.type}.{res.name}"
            node.attributes[f"{key}.count"] = str(len(res.instances))
            for attr, val in res.attributes.items():
                node.attributes[f"{key}.{attr}"] = str(val)

    # -- reservation -----------------------------------------------------

    def reserve(self, assignments: List[AllocatedDeviceResource]) -> ContainerReservation:
        """Reserve every assigned device group; merged env/mounts/devices
        (taskrunner device_hook semantics)."""
        merged = ContainerReservation()
        for asg in assignments:
            with self._lock:
                plugin = self._owners.get((asg.vendor, asg.type, asg.name))
            if plugin is None:
                raise DeviceReservationError(
                    f"no device plugin owns {asg.vendor}/{asg.type}/{asg.name}"
                )
            res = plugin.reserve(list(asg.device_ids))
            merged.envs.update(res.envs)
            merged.mounts.extend(res.mounts)
            merged.devices.extend(res.devices)
        return merged

    # -- periodic refresh ------------------------------------------------

    def start(self) -> None:
        if self.fingerprint_interval <= 0 or not self.plugins:
            return
        self._thread = threading.Thread(
            target=self._loop, name="devicemanager", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.fingerprint_interval):
            before = self._snapshot_ids()
            self.fingerprint()
            if self._snapshot_ids() != before and self.on_devices_changed is not None:
                try:
                    self.on_devices_changed(list(self._last))
                except Exception:  # noqa: BLE001
                    logger.exception("devices-changed callback failed")

    def _snapshot_ids(self):
        with self._lock:
            return [
                (r.vendor, r.type, r.name,
                 tuple((i.id, i.healthy) for i in r.instances))
                for r in self._last
            ]

    def stop(self) -> None:
        self._stop.set()
        for plugin in self.plugins:
            close = getattr(plugin, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001
                    pass


class DeviceReservationError(Exception):
    pass


def builtin_device_plugin(name: str, config: Optional[dict] = None) -> DevicePlugin:
    """Instantiate a built-in device plugin by name (the device half of
    the plugin catalog's built-in registry)."""
    if name in ("mock", "mock-device"):
        from ..plugins.mock_device import MockDevicePlugin

        plugin = MockDevicePlugin()
    elif name == "tpu":
        from ..plugins.tpu_device import TPUDevicePlugin

        plugin = TPUDevicePlugin()
    else:
        raise ValueError(f"unknown built-in device plugin {name!r}")
    if config:
        plugin.set_config(config)
    return plugin
