"""Task drivers (reference ``drivers/``): mock, raw_exec/exec."""
from . import base, exec_driver, mock_driver, raw_exec  # noqa: F401  (registration side effects)
from .base import Driver, DriverError, TaskConfig, TaskHandle, available_drivers, new_driver

__all__ = [
    "Driver",
    "DriverError",
    "TaskConfig",
    "TaskHandle",
    "available_drivers",
    "new_driver",
]
