"""Task drivers (reference ``drivers/``): mock, raw_exec/exec, docker,
java, qemu."""
from . import (  # noqa: F401  (registration side effects)
    base,
    docker,
    exec_driver,
    java_driver,
    mock_driver,
    qemu,
    raw_exec,
)
from .base import Driver, DriverError, TaskConfig, TaskHandle, available_drivers, new_driver

__all__ = [
    "Driver",
    "DriverError",
    "TaskConfig",
    "TaskHandle",
    "available_drivers",
    "new_driver",
]
