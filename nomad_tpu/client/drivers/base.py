"""Task driver interface.

Fills the role of reference ``plugins/drivers/driver.go:40 DriverPlugin``:
TaskConfigSchema / Capabilities / Fingerprint / StartTask / WaitTask /
StopTask / DestroyTask / RecoverTask / InspectTask / TaskStats / SignalTask /
ExecTask. The reference runs drivers as go-plugin gRPC subprocesses; here
drivers are in-process classes behind the same interface, so an
out-of-process transport can wrap them without changing callers (the same
boundary discipline as the scheduler's State/Planner interfaces).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

# driver health (driver.go HealthState*)
HEALTH_UNDETECTED = "undetected"
HEALTH_UNHEALTHY = "unhealthy"
HEALTH_HEALTHY = "healthy"


@dataclass
class Fingerprint:
    health: str = HEALTH_HEALTHY
    health_description: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)


@dataclass
class TaskConfig:
    """What a driver needs to start a task (driver.go TaskConfig)."""

    id: str = ""  # <alloc_id>/<task_name>
    name: str = ""
    alloc_id: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    config: Dict[str, Any] = field(default_factory=dict)  # driver-specific
    task_dir: Optional[object] = None  # allocdir.TaskDir
    stdout_path: str = ""
    stderr_path: str = ""
    cpu_limit: int = 0
    memory_limit_mb: int = 0
    user: str = ""
    # device reservations (plugins/device ContainerReservation): isolating
    # drivers (docker/exec) honor these; unisolated drivers see the env only
    mounts: List[Any] = field(default_factory=list)
    devices: List[Any] = field(default_factory=list)


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    oom_killed: bool = False
    err: str = ""

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


@dataclass
class TaskStatus:
    id: str = ""
    name: str = ""
    state: str = "unknown"  # running | exited | unknown
    started_at_ns: int = 0
    completed_at_ns: int = 0
    exit_result: Optional[ExitResult] = None


@dataclass
class TaskStats:
    cpu_percent: float = 0.0
    memory_rss_bytes: int = 0
    timestamp_ns: int = 0


@dataclass
class Capabilities:
    """driver.go Capabilities."""

    send_signals: bool = False
    exec: bool = False
    fs_isolation: str = "none"  # none | chroot | image


@dataclass
class TaskHandle:
    """Serializable handle for recovery after a client restart
    (driver.go TaskHandle)."""

    driver: str = ""
    config: Optional[TaskConfig] = None
    state: str = "running"
    driver_state: Dict[str, Any] = field(default_factory=dict)  # e.g. pid


class DriverError(Exception):
    pass


def open_task_output(path: str, timeout: float = 30.0):
    """Open a task output path for append. Logmon paths are FIFOs: wait
    for the reader with a deadline instead of blocking forever (a dead
    logmon must fail the start, not hang the task runner), then clear
    O_NONBLOCK so the task's own writes block normally."""
    import errno
    import fcntl
    import os
    import stat as stat_mod

    try:
        is_fifo = stat_mod.S_ISFIFO(os.stat(path).st_mode)
    except OSError:
        is_fifo = False
    if not is_fifo:
        return open(path, "ab")
    deadline = time.monotonic() + timeout
    while True:
        try:
            fd = os.open(path, os.O_WRONLY | os.O_NONBLOCK)
            break
        except OSError as e:
            if e.errno != errno.ENXIO:
                raise DriverError(f"cannot open task output {path}: {e}") from e
            if time.monotonic() > deadline:
                raise DriverError(
                    f"no log collector reading {path} after {timeout}s"
                ) from e
            time.sleep(0.02)
    flags = fcntl.fcntl(fd, fcntl.F_GETFL)
    fcntl.fcntl(fd, fcntl.F_SETFL, flags & ~os.O_NONBLOCK)
    return os.fdopen(fd, "ab")


class Driver:
    """Base driver (DriverPlugin). Subclasses register via ``register``."""

    name = "base"
    capabilities = Capabilities()
    # drivers that redirect task stdout/stderr into the provided paths get
    # logmon FIFOs + rotation; purely synthetic drivers (mock) skip it
    produces_logs = False

    def fingerprint(self) -> Fingerprint:
        """One-shot detection (the reference streams; the client polls)."""
        return Fingerprint(
            health=HEALTH_HEALTHY,
            attributes={f"driver.{self.name}": "1"},
        )

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        raise NotImplementedError

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        """Block until the task exits; None on timeout."""
        raise NotImplementedError

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        raise NotImplementedError

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        raise NotImplementedError

    def inspect_task(self, task_id: str) -> TaskStatus:
        raise NotImplementedError

    def task_stats(self, task_id: str) -> TaskStats:
        return TaskStats(timestamp_ns=time.time_ns())

    def recover_task(self, handle: TaskHandle) -> None:
        raise DriverError(f"driver {self.name} cannot recover tasks")

    def signal_task(self, task_id: str, signal: str) -> None:
        raise DriverError(f"driver {self.name} does not support signals")

    def exec_task(self, task_id: str, cmd: List[str], timeout_s: float) -> Tuple[bytes, int]:
        raise DriverError(f"driver {self.name} does not support exec")

    def exec_task_streaming(self, task_id: str, cmd: List[str]) -> "ExecSession":
        """Interactive exec in the task's context (the reference's
        websocket-backed `nomad alloc exec`, driver ExecTaskStreaming)."""
        raise DriverError(f"driver {self.name} does not support streaming exec")


class ExecSession:
    """A live interactive command: stdin sink + stdout source + exit code.
    The transport layer (websocket bridge) pumps both directions."""

    def stdin_write(self, data: bytes) -> None:
        raise NotImplementedError

    def stdin_close(self) -> None:
        raise NotImplementedError

    def read_output(self, timeout: float = 0.25) -> Optional[bytes]:
        """Next output chunk; b"" when none ready yet; None at EOF."""
        raise NotImplementedError

    def exit_code(self) -> Optional[int]:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError


class SubprocessExecSession(ExecSession):
    """ExecSession over a local subprocess (raw_exec / exec drivers)."""

    def __init__(self, cmd: List[str], env=None, cwd=None) -> None:
        import queue as queue_mod
        import subprocess
        import threading

        self.proc = subprocess.Popen(
            cmd, env=env, cwd=cwd,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, bufsize=0,
        )
        self._q: "queue_mod.Queue[Optional[bytes]]" = queue_mod.Queue()

        def pump() -> None:
            try:
                while True:
                    # bufsize=0 gives a raw FileIO: read() returns as soon
                    # as ANY bytes are available (one syscall)
                    chunk = self.proc.stdout.read(65536)
                    if not chunk:
                        break
                    self._q.put(chunk)
            finally:
                self._q.put(None)

        self._pump = threading.Thread(target=pump, daemon=True)
        self._pump.start()
        self._eof = False

    def stdin_write(self, data: bytes) -> None:
        try:
            self.proc.stdin.write(data)
            self.proc.stdin.flush()
        except (BrokenPipeError, ValueError, OSError):
            pass

    def stdin_close(self) -> None:
        try:
            self.proc.stdin.close()
        except OSError:
            pass

    def read_output(self, timeout: float = 0.25) -> Optional[bytes]:
        import queue as queue_mod

        if self._eof:
            return None
        try:
            chunk = self._q.get(timeout=timeout)
        except queue_mod.Empty:
            return b""
        if chunk is None:
            self._eof = True
            try:
                # stdout EOF usually means exit, but a task that closed
                # its stdout while still running must not raise out of
                # the websocket pump
                self.proc.wait(timeout=5)
            except Exception:  # noqa: BLE001 — TimeoutExpired
                pass
            return None
        return chunk

    def exit_code(self) -> Optional[int]:
        return self.proc.poll()

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass


_REGISTRY: Dict[str, Callable[[], Driver]] = {}


def register(name: str, factory: Callable[[], Driver]) -> Optional[Callable[[], Driver]]:
    """Register a driver factory; returns the factory it replaced (if any)
    so plugin catalogs can restore it on shutdown."""
    prior = _REGISTRY.get(name)
    _REGISTRY[name] = factory
    return prior


def restore(name: str, factory: Optional[Callable[[], Driver]]) -> None:
    """Undo a register(): reinstate the prior factory or drop the name."""
    if factory is None:
        _REGISTRY.pop(name, None)
    else:
        _REGISTRY[name] = factory


def new_driver(name: str) -> Driver:
    factory = _REGISTRY.get(name)
    if factory is None:
        raise DriverError(f"unknown driver {name!r} (have: {sorted(_REGISTRY)})")
    return factory()


def available_drivers() -> List[str]:
    return sorted(_REGISTRY)
