"""Docker driver: containers over the Docker Engine HTTP API.

Fills the role of reference ``drivers/docker/`` (5,414 LoC): container
lifecycle against the daemon's unix socket (the go-dockerclient slot —
no SDK, plain REST), image pulls with a refcounting coordinator
(docker/coordinator.go) so concurrent tasks share pulls and images are
deleted when the last user stops, a log pump demuxing the container's
multiplexed log stream into the task's stdout/stderr files (the docklog
subprocess slot), and a reconciler that removes dangling nomad-labelled
containers (docker/reconciler.go). Fingerprint degrades to undetected
when no daemon socket answers (fingerprint.go).
"""
from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import struct
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional, Tuple

from .base import (
    Capabilities,
    Driver,
    DriverError,
    ExitResult,
    Fingerprint,
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    TaskConfig,
    TaskHandle,
    TaskStats,
    TaskStatus,
    register,
)

logger = logging.getLogger("nomad_tpu.docker")

DEFAULT_SOCKET = "/var/run/docker.sock"
NOMAD_LABEL = "com.hashicorp.nomad.alloc_id"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: Optional[float] = None) -> None:
        super().__init__("localhost", timeout=timeout)
        self.socket_path = socket_path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if self.timeout is not None:
            s.settimeout(self.timeout)
        s.connect(self.socket_path)
        self.sock = s


class DockerAPI:
    """Minimal Docker Engine REST client (go-dockerclient's role)."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET) -> None:
        self.socket_path = socket_path

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        params: Optional[dict] = None,
        timeout: Optional[float] = 60.0,
        raw: bool = False,
    ):
        if params:
            path += "?" + urllib.parse.urlencode(params)
        conn = _UnixHTTPConnection(self.socket_path, timeout=timeout)
        try:
            payload = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if payload else {}
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                msg = data.decode(errors="replace")
                try:
                    msg = json.loads(msg).get("message", msg)
                except (ValueError, AttributeError):
                    pass
                raise DriverError(f"docker {method} {path}: {resp.status} {msg}")
            if raw:
                return data
            return json.loads(data) if data else None
        except (OSError, http.client.HTTPException) as e:
            raise DriverError(f"docker daemon unreachable: {e}") from e
        finally:
            conn.close()

    # -- api surface -----------------------------------------------------

    def ping(self) -> bool:
        try:
            self._request("GET", "/_ping", raw=True, timeout=3.0)
            return True
        except DriverError:
            return False

    def version(self) -> dict:
        return self._request("GET", "/version", timeout=5.0) or {}

    @staticmethod
    def parse_image(image: str) -> Tuple[str, str]:
        """Split repo and tag like docker does: the tag is after the LAST
        ':' and only when that ':' follows the last '/', so registry ports
        ('localhost:5000/app') and digests ('app@sha256:...') stay intact."""
        if "@" in image:
            return image, ""  # digest reference: no tag parameter
        idx = image.rfind(":")
        if idx > image.rfind("/"):
            return image[:idx], image[idx + 1:]
        return image, "latest"

    def pull(self, image: str) -> None:
        """POST /images/create streams progress; drain until EOF."""
        name, tag = self.parse_image(image)
        params = {"fromImage": name}
        if tag:
            params["tag"] = tag
        self._request("POST", "/images/create", params=params,
                      raw=True, timeout=600.0)

    def image_exists(self, image: str) -> bool:
        try:
            self._request("GET", f"/images/{urllib.parse.quote(image, safe='')}/json",
                          timeout=10.0)
            return True
        except DriverError:
            return False

    def remove_image(self, image: str) -> None:
        self._request("DELETE", f"/images/{urllib.parse.quote(image, safe='')}",
                      timeout=60.0)

    def create_container(self, name: str, config: dict) -> str:
        out = self._request("POST", "/containers/create",
                            body=config, params={"name": name})
        return out["Id"]

    def start_container(self, cid: str) -> None:
        self._request("POST", f"/containers/{cid}/start")

    def stop_container(self, cid: str, timeout_s: int) -> None:
        self._request("POST", f"/containers/{cid}/stop",
                      params={"t": timeout_s}, timeout=timeout_s + 30.0)

    def kill_container(self, cid: str, signal: str = "SIGKILL") -> None:
        self._request("POST", f"/containers/{cid}/kill", params={"signal": signal})

    def remove_container(self, cid: str, force: bool = True) -> None:
        self._request("DELETE", f"/containers/{cid}",
                      params={"force": "true" if force else "false"})

    def wait_container(self, cid: str, timeout: Optional[float] = None) -> int:
        out = self._request("POST", f"/containers/{cid}/wait", timeout=timeout)
        return int(out.get("StatusCode", -1))

    def inspect_container(self, cid: str) -> dict:
        return self._request("GET", f"/containers/{cid}/json") or {}

    def list_containers(self, all_: bool = True,
                        label: Optional[str] = None) -> List[dict]:
        params: Dict[str, Any] = {"all": "true" if all_ else "false"}
        if label:
            params["filters"] = json.dumps({"label": [label]})
        return self._request("GET", "/containers/json", params=params) or []

    def container_stats(self, cid: str) -> dict:
        return self._request(
            "GET", f"/containers/{cid}/stats", params={"stream": "false"}
        ) or {}

    def container_logs_stream(self, cid: str):
        """Raw follow-mode log socket; caller demuxes and closes."""
        conn = _UnixHTTPConnection(self.socket_path, timeout=None)
        conn.request(
            "GET",
            f"/containers/{cid}/logs?follow=true&stdout=true&stderr=true",
        )
        return conn, conn.getresponse()

    def exec_in_container(self, cid: str, cmd: List[str],
                          timeout_s: float) -> Tuple[bytes, int]:
        """Attached exec: the start response carries the multiplexed
        output stream; exit code comes from exec inspect (what the
        reference uses for script checks and alloc exec)."""
        out = self._request("POST", f"/containers/{cid}/exec", body={
            "Cmd": cmd, "AttachStdout": True, "AttachStderr": True,
        })
        exec_id = out["Id"]
        conn = _UnixHTTPConnection(self.socket_path, timeout=timeout_s)
        try:
            conn.request(
                "POST", f"/exec/{exec_id}/start",
                body=json.dumps({"Detach": False, "Tty": False}).encode(),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise DriverError(f"exec stream failed: {e}") from e
        finally:
            conn.close()
        output = _demux_docker_stream(raw)
        info = self._request("GET", f"/exec/{exec_id}/json") or {}
        return output, int(info.get("ExitCode") or 0)


def _demux_docker_stream(raw: bytes) -> bytes:
    """Strip the 8-byte frame headers from docker's multiplexed stream;
    pass non-framed (tty) payloads through untouched."""
    out = bytearray()
    pos = 0
    while pos + 8 <= len(raw):
        stream = raw[pos]
        if stream not in (0, 1, 2) or raw[pos + 1:pos + 4] != b"\x00\x00\x00":
            return raw  # not framed (tty mode)
        size = struct.unpack(">I", raw[pos + 4:pos + 8])[0]
        out.extend(raw[pos + 8:pos + 8 + size])
        pos += 8 + size
    if pos != len(raw) and not out:
        return raw
    return bytes(out)


class ImageCoordinator:
    """Refcounted image pulls (reference docker/coordinator.go): many
    tasks share one pull; the image is removed when the last task using
    it stops (when image_gc is on)."""

    class _Pull:
        def __init__(self) -> None:
            self.done = threading.Event()
            self.error: Optional[Exception] = None

    def __init__(self, api: DockerAPI, image_gc: bool = True) -> None:
        self.api = api
        self.image_gc = image_gc
        self._lock = threading.Lock()
        self._refs: Dict[str, int] = {}
        self._pulls: Dict[str, "ImageCoordinator._Pull"] = {}
        # images with an acquire in flight: release() must not gc these —
        # the acquirer may have already probed image_exists()=True
        self._acquiring: Dict[str, int] = {}
        # images being deleted right now: acquire waits these out instead
        # of trusting a stale image_exists probe
        self._removing: set = set()

    def acquire(self, image: str) -> None:
        with self._lock:
            self._acquiring[image] = self._acquiring.get(image, 0) + 1
        try:
            self._acquire_inner(image)
        finally:
            with self._lock:
                n = self._acquiring.get(image, 1) - 1
                if n:
                    self._acquiring[image] = n
                else:
                    self._acquiring.pop(image, None)

    def _acquire_inner(self, image: str) -> None:
        # wait out an in-flight removal of THIS image so the exists-probe
        # below can't observe a half-deleted state
        deadline = time.monotonic() + 120
        while True:
            with self._lock:
                removing = image in self._removing
            if not removing:
                break
            if time.monotonic() > deadline:
                raise DriverError(f"image {image} stuck in removal")
            time.sleep(0.05)
        # probe outside the lock: a slow daemon must not serialize every
        # unrelated acquire/release behind one HTTP round trip
        with self._lock:
            pull = self._pulls.get(image)
        if pull is None:
            exists = self.api.image_exists(image)
            with self._lock:
                pull = self._pulls.get(image)  # someone may have raced us
                if pull is None and not exists:
                    pull = self._pulls[image] = self._Pull()
                    do_pull = True
                else:
                    do_pull = False
        else:
            do_pull = False
        if do_pull:
            try:
                self.api.pull(image)
            except Exception as e:  # noqa: BLE001 — waiters need the error
                pull.error = e
                raise
            finally:
                pull.done.set()
                with self._lock:
                    self._pulls.pop(image, None)
        elif pull is not None:
            pull.done.wait(timeout=600)
            if pull.error is not None:
                raise DriverError(f"shared pull of {image} failed: {pull.error}")
        with self._lock:
            self._refs[image] = self._refs.get(image, 0) + 1

    def release(self, image: str) -> None:
        with self._lock:
            n = self._refs.get(image, 0) - 1
            if n > 0:
                self._refs[image] = n
                return
            self._refs.pop(image, None)
            if self._acquiring.get(image):
                return  # a racing acquire will re-reference it
            if not self.image_gc:
                return
            # mark-then-remove outside the lock: acquires of THIS image
            # wait out the marker; unrelated images stay unblocked
            self._removing.add(image)
        try:
            self.api.remove_image(image)
        except DriverError as e:
            logger.debug("image gc of %s skipped: %s", image, e)
        finally:
            with self._lock:
                self._removing.discard(image)


class _DockerTask:
    def __init__(self, driver: "DockerDriver", cfg: TaskConfig, cid: str) -> None:
        self.driver = driver
        self.cfg = cfg
        self.cid = cid
        self.image = str(cfg.config.get("image", ""))
        self.started_at = time.time_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self._log_conn = None
        threading.Thread(target=self._wait, daemon=True).start()
        if cfg.stdout_path:
            threading.Thread(target=self._pump_logs, daemon=True).start()

    def _wait(self) -> None:
        try:
            code = self.driver.api.wait_container(self.cid, timeout=None)
        except DriverError:
            code = -1
        self.exit_result = ExitResult(exit_code=max(code, 0),
                                      err="" if code >= 0 else "wait failed")
        self.completed_at = time.time_ns()
        self.done.set()
        if self._log_conn is not None:
            try:
                self._log_conn.close()
            except OSError:
                pass

    def _pump_logs(self) -> None:
        """Demux docker's multiplexed log stream into the task's
        stdout/stderr files (reference docklog subprocess)."""
        try:
            conn, resp = self.driver.api.container_logs_stream(self.cid)
        except DriverError:
            return
        self._log_conn = conn
        try:
            with open(self.cfg.stdout_path, "ab") as out, \
                    open(self.cfg.stderr_path or os.devnull, "ab") as err:
                while True:
                    header = resp.read(8)
                    if len(header) < 8:
                        return
                    stream, size = header[0], struct.unpack(">I", header[4:8])[0]
                    data = resp.read(size)
                    target = err if stream == 2 else out
                    target.write(data)
                    target.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class DockerDriver(Driver):
    name = "docker"
    capabilities = Capabilities(send_signals=True, exec=True, fs_isolation="image")
    # the driver pumps container logs into the task files itself
    produces_logs = False
    config_schema = {
        "endpoint": {"type": "string"},
        "image_gc": {"type": "bool"},
    }

    RECONCILE_INTERVAL = 300.0  # docker/reconciler.go default period

    def __init__(self, socket_path: str = DEFAULT_SOCKET) -> None:
        self.api = DockerAPI(socket_path)
        self.coordinator = ImageCoordinator(self.api)
        self.tasks: Dict[str, _DockerTask] = {}
        self._lock = threading.Lock()
        self._reconciler_started = False

    def set_config(self, config: dict) -> None:
        if config.get("endpoint"):
            self.api = DockerAPI(str(config["endpoint"]).replace("unix://", ""))  # race-ok: plugin config lands before any task runs; reference swap is atomic
            self.coordinator.api = self.api
        if "image_gc" in config:
            self.coordinator.image_gc = bool(config["image_gc"])

    # -- fingerprint -----------------------------------------------------

    def fingerprint(self) -> Fingerprint:
        if not self.api.ping():
            return Fingerprint(
                health=HEALTH_UNDETECTED,
                health_description="docker daemon not reachable",
            )
        version = self.api.version().get("Version", "unknown")
        return Fingerprint(health=HEALTH_HEALTHY, attributes={
            "driver.docker": "1",
            "driver.docker.version": version,
        })

    # -- lifecycle -------------------------------------------------------

    @staticmethod
    def container_name(cfg: TaskConfig) -> str:
        return f"{cfg.name}-{cfg.alloc_id}"

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        image = cfg.config.get("image")
        if not image:
            raise DriverError("docker requires config.image")
        with self._lock:
            if cfg.id in self.tasks:
                raise DriverError(f"task {cfg.id} already started")
        self.coordinator.acquire(image)
        binds = []
        if cfg.task_dir is not None:
            binds = [
                f"{cfg.task_dir.shared_alloc_dir}:/alloc",
                f"{cfg.task_dir.local_dir}:/local",
                f"{cfg.task_dir.secrets_dir}:/secrets",
            ]
        binds += [
            f"{m.host_path}:{m.task_path}" + (":ro" if m.read_only else "")
            for m in cfg.mounts
        ]
        container = {
            "Image": image,
            "Cmd": ([cfg.config["command"]] if cfg.config.get("command") else [])
            + [str(a) for a in cfg.config.get("args", [])],
            "Env": [f"{k}={v}" for k, v in cfg.env.items()],
            "WorkingDir": str(cfg.config.get("work_dir", "")) or None,
            "Labels": {NOMAD_LABEL: cfg.alloc_id},
            "HostConfig": {
                "Binds": binds,
                "Memory": cfg.memory_limit_mb << 20,
                "CPUShares": cfg.cpu_limit,
                "NetworkMode": str(cfg.config.get("network_mode", "")) or "default",
            },
        }
        try:
            cid = self.api.create_container(self.container_name(cfg), container)
            self.api.start_container(cid)
        except DriverError:
            self.coordinator.release(image)
            raise
        task = _DockerTask(self, cfg, cid)
        with self._lock:
            self.tasks[cfg.id] = task
        self._ensure_reconciler()
        return TaskHandle(
            driver=self.name, config=cfg, state="running",
            driver_state={"container_id": cid, "image": image},
        )

    def _ensure_reconciler(self) -> None:
        """Lazy periodic dangling-container sweep: starts with the first
        task so idle drivers (and fingerprint-only instances) spawn no
        threads."""
        with self._lock:
            if self._reconciler_started:
                return
            self._reconciler_started = True

        def loop() -> None:
            while True:
                time.sleep(self.RECONCILE_INTERVAL)
                removed = self.reconcile_dangling()
                if removed:
                    logger.info("reconciler removed %d dangling containers",
                                len(removed))

        threading.Thread(target=loop, name="docker-reconciler", daemon=True).start()

    def _get(self, task_id: str) -> _DockerTask:
        t = self.tasks.get(task_id)
        if t is None:
            raise DriverError(f"unknown task {task_id}")
        return t

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        if not t.done.wait(timeout=timeout):
            return None
        return t.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        t = self._get(task_id)
        try:
            if signal != "SIGTERM":
                self.api.kill_container(t.cid, signal)
                if t.done.wait(timeout=max(timeout_s, 0.001)):
                    return
            self.api.stop_container(t.cid, int(max(timeout_s, 1)))
        except DriverError as e:
            logger.warning("stopping container %s: %s", t.cid[:12], e)
        t.done.wait(timeout=timeout_s + 10)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        with self._lock:
            t = self.tasks.get(task_id)
            if t is None:
                return
            if not t.done.is_set() and not force:
                raise DriverError(f"task {task_id} still running")
            # claim it under the lock so a concurrent destroy is a no-op
            del self.tasks[task_id]
        try:
            self.api.remove_container(t.cid, force=True)
        except DriverError as e:
            logger.warning("removing container %s: %s", t.cid[:12], e)
        self.coordinator.release(t.image)

    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=t.cfg.name,
            state="exited" if t.done.is_set() else "running",
            started_at_ns=t.started_at,
            completed_at_ns=t.completed_at,
            exit_result=t.exit_result,
        )

    def task_stats(self, task_id: str) -> TaskStats:
        t = self._get(task_id)
        try:
            raw = self.api.container_stats(t.cid)
        except DriverError:
            return TaskStats(timestamp_ns=time.time_ns())
        mem = (raw.get("memory_stats") or {}).get("usage", 0)
        cpu = raw.get("cpu_stats") or {}
        pre = raw.get("precpu_stats") or {}
        delta = (cpu.get("cpu_usage", {}).get("total_usage", 0)
                 - pre.get("cpu_usage", {}).get("total_usage", 0))
        sys_delta = cpu.get("system_cpu_usage", 0) - pre.get("system_cpu_usage", 0)
        pct = (delta / sys_delta * 100.0) if sys_delta > 0 else 0.0
        return TaskStats(cpu_percent=pct, memory_rss_bytes=mem,
                         timestamp_ns=time.time_ns())

    def signal_task(self, task_id: str, signal: str) -> None:
        t = self._get(task_id)
        self.api.kill_container(t.cid, signal)

    def exec_task(self, task_id: str, cmd: List[str], timeout_s: float) -> Tuple[bytes, int]:
        t = self._get(task_id)
        return self.api.exec_in_container(t.cid, cmd, timeout_s)

    def recover_task(self, handle: TaskHandle) -> None:
        """Re-attach to a live container after a client restart
        (driver.go RecoverTask)."""
        cid = handle.driver_state.get("container_id")
        if not cid or handle.config is None:
            raise DriverError("docker handle missing container id")
        info = self.api.inspect_container(cid)
        if not (info.get("State") or {}).get("Running", False):
            raise DriverError(f"container {cid[:12]} not running")
        self.coordinator.acquire(handle.driver_state.get("image", ""))
        task = _DockerTask(self, handle.config, cid)
        with self._lock:
            self.tasks[handle.config.id] = task

    # -- reconciler (docker/reconciler.go) -------------------------------

    def reconcile_dangling(self) -> List[str]:
        """Remove nomad-labelled containers no task tracks (leaked by a
        crash between create and handle persistence)."""
        with self._lock:
            known = {t.cid for t in self.tasks.values()}
        removed = []
        try:
            for c in self.api.list_containers(all_=True, label=NOMAD_LABEL):
                cid = c.get("Id", "")
                if cid and cid not in known:
                    try:
                        self.api.remove_container(cid, force=True)
                        removed.append(cid)
                    except DriverError:
                        pass
        except DriverError:
            pass
        return removed


# One driver instance per process: the image coordinator's refcounts and
# the reconciler's known-container set must span every task on the node
# (the reference's drivermanager holds a single plugin instance).
_shared_driver: Optional[DockerDriver] = None
_shared_lock = threading.Lock()


def shared_docker_driver() -> DockerDriver:
    global _shared_driver
    with _shared_lock:
        if _shared_driver is None:
            _shared_driver = DockerDriver()
        return _shared_driver


register("docker", shared_docker_driver)
