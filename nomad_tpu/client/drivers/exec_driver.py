"""exec driver: tasks supervised by the native out-of-process executor.

Fills the role of reference ``drivers/exec/driver.go`` + the executor
subprocess boundary (``drivers/shared/executor/``): the driver fork-execs
``nomad-executor`` (C++, native/executor/), which setsids, applies rlimits,
redirects stdio, runs the task, and records "<exit_code> <signal>" in a
status file. Because supervision lives outside the client process, tasks
survive a client restart and recovery re-attaches by executor pid — the
reference's reattach config (plugins/drivers/driver.go:47 RecoverTask).
"""
from __future__ import annotations

import os
import signal as _signal
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...native import ensure_built
from .base import (
    Capabilities,
    Driver,
    DriverError,
    ExitResult,
    TaskConfig,
    TaskHandle,
    TaskStats,
    TaskStatus,
    register,
)


def _proc_start_ticks(pid: int) -> Optional[int]:
    """Kernel start time of a pid (field 22 of /proc/<pid>/stat) — the
    identity that distinguishes a live executor from a recycled pid."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        # comm may contain spaces/parens; field 22 counts from after ')'
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return None


class _ExecutorTask:
    def __init__(self, cfg: TaskConfig, executor_bin: str) -> None:
        command = cfg.config.get("command")
        if not command:
            raise DriverError("exec requires config.command")
        args = [str(a) for a in cfg.config.get("args", [])]
        workdir = cfg.task_dir.dir if cfg.task_dir is not None else "/tmp"
        self.status_file = os.path.join(workdir, f".{cfg.name}.status")
        self.pid_file = os.path.join(workdir, f".{cfg.name}.pid")
        for stale in (self.status_file, self.pid_file):
            try:
                os.remove(stale)
            except OSError:
                pass
        argv = [
            executor_bin,
            "--status-file", self.status_file,
            "--pid-file", self.pid_file,
        ]
        if cfg.stdout_path:
            argv += ["--stdout", cfg.stdout_path]
        if cfg.stderr_path:
            argv += ["--stderr", cfg.stderr_path]
        argv += ["--cwd", workdir]
        kill_timeout = float(cfg.config.get("kill_timeout", 5.0))
        argv += ["--kill-timeout", str(kill_timeout)]
        for limit_flag in ("rlimit_cpu", "rlimit_as", "rlimit_nofile"):
            if cfg.config.get(limit_flag):
                argv += [f"--{limit_flag.replace('_', '-')}", str(cfg.config[limit_flag])]
        for k, v in cfg.env.items():
            argv += ["--env", f"{k}={v}"]
        argv += ["--", command] + args
        try:
            self.proc: Optional[subprocess.Popen] = subprocess.Popen(
                argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
            )
        except OSError as e:
            raise DriverError(f"failed to launch executor: {e}") from e
        self.pid = self.proc.pid
        self.cfg = cfg
        self.started_at = time.time_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        threading.Thread(target=self._reap, daemon=True).start()

    def task_pgid(self) -> Optional[int]:
        """The task's process-group id (== the executor's child pid)."""
        try:
            with open(self.pid_file) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _read_status(self) -> Optional[ExitResult]:
        try:
            with open(self.status_file) as f:
                parts = f.read().split()
            return ExitResult(exit_code=int(parts[0]), signal=int(parts[1]))
        except (OSError, IndexError, ValueError):
            return None

    def _executor_alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        try:
            os.kill(self.pid, 0)
            return True
        except (ProcessLookupError, PermissionError):
            return False

    def _reap(self) -> None:
        while True:
            if self.proc is not None:
                self.proc.wait()
            else:
                while self._executor_alive():
                    time.sleep(0.1)
            result = self._read_status()
            if result is None:
                result = ExitResult(exit_code=127, err="executor died without status")
            self.exit_result = result
            self.completed_at = time.time_ns()
            self.done.set()
            return


class ExecDriver(Driver):
    name = "exec"
    capabilities = Capabilities(send_signals=True, exec=False, fs_isolation="chroot")
    produces_logs = True

    def __init__(self) -> None:
        self.tasks: Dict[str, _ExecutorTask] = {}
        self._executor_bin: Optional[str] = None

    def _bin(self) -> str:
        if self._executor_bin is None:
            self._executor_bin = ensure_built("nomad-executor")
        return self._executor_bin

    def fingerprint(self):
        from .base import HEALTH_HEALTHY, HEALTH_UNDETECTED, Fingerprint

        try:
            self._bin()
        except Exception as e:  # noqa: BLE001
            return Fingerprint(health=HEALTH_UNDETECTED, health_description=str(e))
        return Fingerprint(
            health=HEALTH_HEALTHY, attributes={f"driver.{self.name}": "1"}
        )

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        if cfg.id in self.tasks:
            raise DriverError(f"task {cfg.id} already started")
        t = _ExecutorTask(cfg, self._bin())
        self.tasks[cfg.id] = t
        return TaskHandle(
            driver=self.name, config=cfg, state="running",
            driver_state={
                "pid": t.pid,
                "pid_start_ticks": _proc_start_ticks(t.pid),
                "status_file": t.status_file,
                "pid_file": t.pid_file,
            },
        )

    def _get(self, task_id: str) -> _ExecutorTask:
        t = self.tasks.get(task_id)
        if t is None:
            raise DriverError(f"unknown task {task_id}")
        return t

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        if not t.done.wait(timeout=timeout):
            return None
        return t.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        t = self._get(task_id)
        sig = getattr(_signal, signal, _signal.SIGTERM)
        pgid = t.task_pgid()
        if sig not in (_signal.SIGTERM, _signal.SIGINT) and pgid is not None:
            try:
                os.killpg(pgid, sig)
            except ProcessLookupError:
                pass
        # always poke the executor: it forwards SIGTERM to the task group
        # and escalates itself, and it covers the window before the pid
        # file lands on disk
        try:
            os.kill(t.pid, _signal.SIGTERM)
        except ProcessLookupError:
            pass
        kill_timeout = float(t.cfg.config.get("kill_timeout", 5.0))
        if not t.done.wait(timeout=max(timeout_s, kill_timeout) + 1.5):
            # last resort: SIGKILL the TASK GROUP (not just the executor —
            # the task runs setsid'd and would otherwise be orphaned alive).
            # Re-read the pid file: it may have landed since the first look.
            pgid = t.task_pgid()
            if pgid is not None:
                try:
                    os.killpg(pgid, _signal.SIGKILL)
                except ProcessLookupError:
                    pass
            try:
                os.kill(t.pid, _signal.SIGKILL)
            except ProcessLookupError:
                pass
            t.done.wait(timeout=5.0)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        t = self.tasks.get(task_id)
        if t is None:
            return
        if not t.done.is_set():
            if not force:
                raise DriverError(f"task {task_id} still running")
            self.stop_task(task_id, 0.0, "SIGKILL")
        del self.tasks[task_id]

    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=t.cfg.name,
            state="exited" if t.done.is_set() else "running",
            started_at_ns=t.started_at,
            completed_at_ns=t.completed_at,
            exit_result=t.exit_result,
        )

    def task_stats(self, task_id: str) -> TaskStats:
        t = self._get(task_id)
        rss = 0
        try:
            with open(f"/proc/{t.pid}/statm") as f:
                rss = int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            pass
        return TaskStats(memory_rss_bytes=rss, timestamp_ns=time.time_ns())

    def signal_task(self, task_id: str, signal: str) -> None:
        sig = getattr(_signal, signal, None)
        if sig is None:
            raise DriverError(f"unknown signal {signal}")
        t = self._get(task_id)
        pgid = t.task_pgid()
        try:
            if pgid is not None:
                os.killpg(pgid, sig)  # deliver to the task, not the supervisor
            else:
                os.kill(t.pid, sig)
        except ProcessLookupError:
            pass

    def recover_task(self, handle: TaskHandle) -> None:
        """Re-attach to a live executor by pid (RecoverTask)."""
        pid = (handle.driver_state or {}).get("pid")
        cfg = handle.config
        if pid is None or cfg is None:
            raise DriverError("handle missing pid")
        expected_ticks = handle.driver_state.get("pid_start_ticks")
        actual_ticks = _proc_start_ticks(pid)
        if (
            actual_ticks is not None
            and expected_ticks is not None
            and actual_ticks != expected_ticks
        ):
            raise DriverError(f"pid {pid} was recycled (start time mismatch)")
        t = _ExecutorTask.__new__(_ExecutorTask)
        t.cfg = cfg
        t.pid = pid
        t.proc = None  # not our child anymore
        t.status_file = handle.driver_state.get("status_file", "")
        t.pid_file = handle.driver_state.get("pid_file", "")
        t.started_at = time.time_ns()
        t.completed_at = 0
        t.exit_result = None
        t.done = threading.Event()
        # the executor may have finished while we were down
        if not t._executor_alive() and t._read_status() is None:
            raise DriverError(f"executor pid {pid} gone without status")
        threading.Thread(target=t._reap, daemon=True).start()
        self.tasks[cfg.id] = t


register("exec", ExecDriver)
