"""Java driver (reference ``drivers/java``, 800 LoC): runs a jar or a
class through the host JVM. Command construction mirrors driver.go
javaCmdArgs (jvm_options → -jar jar_path | -cp class_path class → args);
process supervision reuses the raw-exec machinery. Fingerprint degrades
to undetected without a ``java`` binary (driver.go Fingerprint exec of
``java -version``)."""
from __future__ import annotations

import shutil
import subprocess

from .base import (
    Capabilities,
    DriverError,
    Fingerprint,
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    TaskConfig,
    TaskHandle,
    register,
)
from .raw_exec import RawExecDriver


def java_cmd_args(config: dict) -> list:
    """driver.go javaCmdArgs."""
    args = [str(a) for a in config.get("jvm_options", [])]
    if config.get("jar_path"):
        args += ["-jar", str(config["jar_path"])]
    elif config.get("class"):
        if config.get("class_path"):
            args += ["-cp", str(config["class_path"])]
        args.append(str(config["class"]))
    else:
        raise DriverError("java requires config.jar_path or config.class")
    args += [str(a) for a in config.get("args", [])]
    return args


class JavaDriver(RawExecDriver):
    name = "java"
    capabilities = Capabilities(send_signals=True, exec=False, fs_isolation="none")
    produces_logs = True

    def fingerprint(self) -> Fingerprint:
        java = shutil.which("java")
        if java is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description="java binary not found")
        try:
            out = subprocess.run(
                [java, "-version"], capture_output=True, text=True, timeout=10
            )
            version_line = (out.stderr or out.stdout).splitlines()[0]
        except (OSError, subprocess.TimeoutExpired, IndexError):
            version_line = "unknown"
        return Fingerprint(health=HEALTH_HEALTHY, attributes={
            "driver.java": "1",
            "driver.java.version": version_line,
        })

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        rewritten = TaskConfig(**{**cfg.__dict__})
        rewritten.config = {
            "command": shutil.which("java") or "java",
            "args": java_cmd_args(cfg.config),
        }
        handle = super().start_task(rewritten)
        handle.driver = self.name
        return handle


register("java", JavaDriver)
