"""Scriptable mock driver for tests.

Fills the role of reference ``drivers/mock/driver.go`` (928 LoC): a task
"runs" for ``run_for`` seconds, exits with ``exit_code``, optionally errors
on start (``start_error``), blocks for ``start_block_for``, and ignores the
stop signal for ``kill_after`` (exercising kill-timeout escalation).
Config keys mirror the reference's mock config stanza.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .base import (
    Capabilities,
    Driver,
    DriverError,
    ExitResult,
    TaskConfig,
    TaskHandle,
    TaskStatus,
    register,
)


def _seconds(v) -> float:
    """Accept a number of seconds (possibly as a bare string) or a Go-style
    duration ("5s"). Malformed values surface as DriverError so the task
    runner records a driver failure instead of losing its run thread."""
    try:
        return float(v)
    except (TypeError, ValueError):
        pass
    from ...jobspec.parse import HCLError, parse_duration_ns

    try:
        return parse_duration_ns(v) / 1e9
    except HCLError as e:
        raise DriverError(f"bad duration {v!r}: {e}") from e


class _MockTask:
    def __init__(self, cfg: TaskConfig) -> None:
        self.cfg = cfg
        c = cfg.config
        self.run_for = _seconds(c.get("run_for", 0.0))
        self.exit_code = int(c.get("exit_code", 0))
        self.exit_signal = int(c.get("exit_signal", 0))
        self.kill_after = _seconds(c.get("kill_after", 0.0))
        self.started_at = time.time_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        self.kill_requested = threading.Event()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        deadline = time.monotonic() + self.run_for
        while time.monotonic() < deadline:
            if self.kill_requested.wait(timeout=0.01):
                # honor the kill only after kill_after
                time.sleep(self.kill_after)
                self._finish(ExitResult(exit_code=0, signal=15))
                return
        self._finish(ExitResult(exit_code=self.exit_code, signal=self.exit_signal))

    def _finish(self, result: ExitResult) -> None:
        # the force-kill path may have finished the task first; the first
        # result wins and must not be overwritten
        if self.done.is_set():
            return
        self.exit_result = result
        self.completed_at = time.time_ns()
        self.done.set()


class MockDriver(Driver):
    name = "mock"
    capabilities = Capabilities(send_signals=True, exec=False, fs_isolation="none")

    def __init__(self) -> None:
        self.tasks: Dict[str, _MockTask] = {}

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        if cfg.config.get("start_error"):
            raise DriverError(str(cfg.config["start_error"]))
        block = _seconds(cfg.config.get("start_block_for", 0.0))
        if block:
            time.sleep(block)
        if cfg.id in self.tasks:
            raise DriverError(f"task {cfg.id} already started")
        self.tasks[cfg.id] = _MockTask(cfg)
        return TaskHandle(driver=self.name, config=cfg, state="running")

    def _get(self, task_id: str) -> _MockTask:
        t = self.tasks.get(task_id)
        if t is None:
            raise DriverError(f"unknown task {task_id}")
        return t

    def exec_task_streaming(self, task_id: str, cmd):
        """Echo session: every stdin write comes back as output; EOF
        exits 0 (interactive-exec plumbing tests without real processes)."""
        import queue as queue_mod

        self._get(task_id)

        class _EchoSession:
            def __init__(self) -> None:
                self._q: "queue_mod.Queue" = queue_mod.Queue()
                self._code = None
                self._eof = False

            def stdin_write(self, data: bytes) -> None:
                self._q.put(data)

            def stdin_close(self) -> None:
                self._code = 0
                self._q.put(None)

            def read_output(self, timeout: float = 0.25):
                if self._eof:
                    return None
                try:
                    chunk = self._q.get(timeout=timeout)
                except queue_mod.Empty:
                    return b""
                if chunk is None:
                    self._eof = True
                    return None
                return chunk

            def exit_code(self):
                return self._code

            def kill(self) -> None:
                self._code = 137
                self._q.put(None)

        return _EchoSession()

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        if not t.done.wait(timeout=timeout):
            return None
        return t.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        t = self._get(task_id)
        t.kill_requested.set()
        if not t.done.wait(timeout=timeout_s):
            t._finish(ExitResult(exit_code=0, signal=9))  # force kill

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        t = self.tasks.get(task_id)
        if t is None:
            return
        if not t.done.is_set():
            if not force:
                raise DriverError(f"task {task_id} still running")
            self.stop_task(task_id, 0.0)
        del self.tasks[task_id]

    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=t.cfg.name,
            state="exited" if t.done.is_set() else "running",
            started_at_ns=t.started_at,
            completed_at_ns=t.completed_at,
            exit_result=t.exit_result,
        )

    def signal_task(self, task_id: str, signal: str) -> None:
        self._get(task_id)  # accept silently, like the reference mock

    def recover_task(self, handle: TaskHandle) -> None:
        # mock tasks die with the process; a recovered task is re-started
        if handle.config is not None and handle.config.id not in self.tasks:
            self.tasks[handle.config.id] = _MockTask(handle.config)


register("mock", MockDriver)
