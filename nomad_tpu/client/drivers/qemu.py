"""QEMU driver (reference ``drivers/qemu``, 816 LoC): boots a VM image
under qemu-system-x86_64. Argument construction mirrors driver.go
StartTask (accelerator, memory from the task's resources, image drive,
port forwards via user-mode netdev, -nographic); supervision reuses the
raw-exec machinery. Fingerprint degrades to undetected without the
binary."""
from __future__ import annotations

import shutil
import subprocess

from .base import (
    Capabilities,
    DriverError,
    Fingerprint,
    HEALTH_HEALTHY,
    HEALTH_UNDETECTED,
    TaskConfig,
    TaskHandle,
    register,
)
from .raw_exec import RawExecDriver

QEMU_BIN = "qemu-system-x86_64"


def qemu_args(cfg: TaskConfig) -> list:
    config = cfg.config
    image = config.get("image_path")
    if not image:
        raise DriverError("qemu requires config.image_path")
    mem = cfg.memory_limit_mb or 512
    args = [
        "-machine", f"type=pc,accel={config.get('accelerator', 'tcg')}",
        "-name", cfg.name,
        "-m", f"{mem}M",
        "-drive", f"file={image}",
        "-nographic",
    ]
    port_map = config.get("port_map") or {}
    if port_map:
        fwds = ",".join(
            f"hostfwd=tcp::{host}-:{guest}" for guest, host in port_map.items()
        )
        args += ["-netdev", f"user,id=user.0,{fwds}",
                 "-device", "virtio-net,netdev=user.0"]
    args += [str(a) for a in config.get("args", [])]
    return args


class QemuDriver(RawExecDriver):
    name = "qemu"
    capabilities = Capabilities(send_signals=True, exec=False, fs_isolation="image")
    produces_logs = True

    def fingerprint(self) -> Fingerprint:
        binary = shutil.which(QEMU_BIN)
        if binary is None:
            return Fingerprint(health=HEALTH_UNDETECTED,
                               health_description=f"{QEMU_BIN} not found")
        try:
            out = subprocess.run([binary, "--version"], capture_output=True,
                                 text=True, timeout=10)
            version = out.stdout.splitlines()[0] if out.stdout else "unknown"
        except (OSError, subprocess.TimeoutExpired):
            version = "unknown"
        return Fingerprint(health=HEALTH_HEALTHY, attributes={
            "driver.qemu": "1",
            "driver.qemu.version": version,
        })

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        rewritten = TaskConfig(**{**cfg.__dict__})
        rewritten.config = {
            "command": shutil.which(QEMU_BIN) or QEMU_BIN,
            "args": qemu_args(cfg),
        }
        handle = super().start_task(rewritten)
        handle.driver = self.name
        return handle


register("qemu", QemuDriver)
