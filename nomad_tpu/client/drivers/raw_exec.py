"""raw_exec driver: unisolated fork/exec.

Fills the role of reference ``drivers/rawexec/driver.go`` (712 LoC): runs
``command`` + ``args`` as a child process with the task env, stdout/stderr
captured to the task log dir, no resource isolation. Process-group kill
(setsid) mirrors the reference's executor shutdown. Recovery re-attaches by
pid (reference RecoverTask via the executor reattach config).
"""
from __future__ import annotations

import os
import signal as _signal
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from .base import (
    Capabilities,
    Driver,
    DriverError,
    ExitResult,
    TaskConfig,
    TaskHandle,
    TaskStats,
    TaskStatus,
    open_task_output,
    register,
)

_SIGNALS = {s.name: s.value for s in _signal.Signals}


class _ExecTask:
    def __init__(self, cfg: TaskConfig) -> None:
        command = cfg.config.get("command")
        if not command:
            raise DriverError("raw_exec requires config.command")
        args = [str(a) for a in cfg.config.get("args", [])]
        cwd = cfg.task_dir.dir if cfg.task_dir is not None else None
        self.stdout = open_task_output(cfg.stdout_path) if cfg.stdout_path else subprocess.DEVNULL
        self.stderr = open_task_output(cfg.stderr_path) if cfg.stderr_path else subprocess.DEVNULL
        env = dict(os.environ)
        env.update(cfg.env)
        try:
            self.proc = subprocess.Popen(
                [command] + args,
                env=env,
                cwd=cwd,
                stdout=self.stdout,
                stderr=self.stderr,
                start_new_session=True,  # own process group for group-kill
            )
        except OSError as e:
            for f in (self.stdout, self.stderr):
                if hasattr(f, "close"):
                    f.close()
            raise DriverError(f"failed to start {command}: {e}") from e
        self.cfg = cfg
        self.started_at = time.time_ns()
        self.completed_at = 0
        self.exit_result: Optional[ExitResult] = None
        self.done = threading.Event()
        threading.Thread(target=self._reap, daemon=True).start()

    def _reap(self) -> None:
        code = self.proc.wait()
        if code < 0:
            self.exit_result = ExitResult(exit_code=0, signal=-code)
        else:
            self.exit_result = ExitResult(exit_code=code)
        self.completed_at = time.time_ns()
        for f in (self.stdout, self.stderr):
            if hasattr(f, "close"):
                f.close()
        self.done.set()

    def signal_group(self, sig: int) -> None:
        try:
            os.killpg(self.proc.pid, sig)
        except ProcessLookupError:
            pass


class RawExecDriver(Driver):
    name = "raw_exec"
    capabilities = Capabilities(send_signals=True, exec=True, fs_isolation="none")
    produces_logs = True

    def __init__(self) -> None:
        self.tasks: Dict[str, _ExecTask] = {}

    def start_task(self, cfg: TaskConfig) -> TaskHandle:
        if cfg.id in self.tasks:
            raise DriverError(f"task {cfg.id} already started")
        t = _ExecTask(cfg)
        self.tasks[cfg.id] = t
        return TaskHandle(
            driver=self.name, config=cfg, state="running",
            driver_state={"pid": t.proc.pid},
        )

    def _get(self, task_id: str) -> _ExecTask:
        t = self.tasks.get(task_id)
        if t is None:
            raise DriverError(f"unknown task {task_id}")
        return t

    def wait_task(self, task_id: str, timeout: Optional[float] = None) -> Optional[ExitResult]:
        t = self._get(task_id)
        if not t.done.wait(timeout=timeout):
            return None
        return t.exit_result

    def stop_task(self, task_id: str, timeout_s: float, signal: str = "SIGTERM") -> None:
        t = self._get(task_id)
        t.signal_group(_SIGNALS.get(signal, _signal.SIGTERM))
        if not t.done.wait(timeout=max(timeout_s, 0.001)):
            t.signal_group(_signal.SIGKILL)
            t.done.wait(timeout=5.0)

    def destroy_task(self, task_id: str, force: bool = False) -> None:
        t = self.tasks.get(task_id)
        if t is None:
            return
        if not t.done.is_set():
            if not force:
                raise DriverError(f"task {task_id} still running")
            self.stop_task(task_id, 0.0, "SIGKILL")
        del self.tasks[task_id]

    def inspect_task(self, task_id: str) -> TaskStatus:
        t = self._get(task_id)
        return TaskStatus(
            id=task_id,
            name=t.cfg.name,
            state="exited" if t.done.is_set() else "running",
            started_at_ns=t.started_at,
            completed_at_ns=t.completed_at,
            exit_result=t.exit_result,
        )

    def task_stats(self, task_id: str) -> TaskStats:
        t = self._get(task_id)
        rss = 0
        try:
            with open(f"/proc/{t.proc.pid}/statm") as f:
                rss = int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            pass
        return TaskStats(memory_rss_bytes=rss, timestamp_ns=time.time_ns())

    def signal_task(self, task_id: str, signal: str) -> None:
        sig = _SIGNALS.get(signal)
        if sig is None:
            raise DriverError(f"unknown signal {signal}")
        self._get(task_id).signal_group(sig)

    def exec_task(self, task_id: str, cmd: List[str], timeout_s: float) -> Tuple[bytes, int]:
        t = self._get(task_id)
        try:
            out = subprocess.run(
                cmd, env=t.cfg.env, capture_output=True, timeout=timeout_s
            )
        except subprocess.TimeoutExpired as e:
            return (e.stdout or b""), 124
        return out.stdout + out.stderr, out.returncode

    def exec_task_streaming(self, task_id: str, cmd: List[str]):
        from .base import SubprocessExecSession

        t = self._get(task_id)
        cwd = None
        td = t.cfg.task_dir
        if td is not None:
            cwd = getattr(td, "local_dir", None) or getattr(td, "dir", None)
        return SubprocessExecSession(cmd, env=t.cfg.env, cwd=cwd)

    def recover_task(self, handle: TaskHandle) -> None:
        """Re-attach to a live pid after client restart (RecoverTask)."""
        pid = handle.driver_state.get("pid")
        cfg = handle.config
        if pid is None or cfg is None:
            raise DriverError("handle missing pid")
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError) as e:
            raise DriverError(f"pid {pid} gone: {e}") from e
        t = _ExecTask.__new__(_ExecTask)
        t.cfg = cfg
        t.stdout = t.stderr = subprocess.DEVNULL
        t.started_at = time.time_ns()
        t.completed_at = 0
        t.exit_result = None
        t.done = threading.Event()

        class _Reattached:
            def __init__(self, pid: int) -> None:
                self.pid = pid

            def wait(self) -> int:
                # not our child: poll liveness (legacy-reattach semantics)
                while True:
                    try:
                        os.kill(self.pid, 0)
                    except ProcessLookupError:
                        return 0
                    time.sleep(0.1)

        t.proc = _Reattached(pid)
        threading.Thread(target=t._reap, daemon=True).start()
        self.tasks[cfg.id] = t


register("raw_exec", RawExecDriver)
