"""Node fingerprinting.

Fills the role of reference ``client/fingerprint/`` + fingerprint_manager.go:
detectors populate ``Node.attributes`` and ``Node.node_resources``. The
registry mirrors fingerprint.go (arch, cpu, memory, storage, host, nomad,
signal); cloud-env detectors (env_aws/env_gce) and consul/vault are absent
with their backends. Driver fingerprints ride the same mechanism
(drivermanager in the reference).
"""
from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
from typing import Callable, Dict, List

from ..structs.structs import Node, NodeResources

from .drivers.base import HEALTH_HEALTHY, available_drivers, new_driver


def _arch(node: Node) -> None:
    node.attributes["cpu.arch"] = platform.machine()


def _cpu(node: Node) -> None:
    cores = multiprocessing.cpu_count()
    node.attributes["cpu.numcores"] = str(cores)
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    node.attributes["cpu.frequency"] = str(int(mhz))
    total = int(cores * mhz)
    node.attributes["cpu.totalcompute"] = str(total)
    if node.node_resources.cpu_shares == 0:
        node.node_resources.cpu_shares = total


def _memory(node: Node) -> None:
    mb = 1024
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal"):
                    mb = int(line.split()[1]) // 1024
                    break
    except (OSError, ValueError, IndexError):
        pass
    node.attributes["memory.totalbytes"] = str(mb * 1024 * 1024)
    if node.node_resources.memory_mb == 0:
        node.node_resources.memory_mb = mb


def _storage(node: Node) -> None:
    usage = shutil.disk_usage("/")
    node.attributes["unique.storage.bytestotal"] = str(usage.total)
    node.attributes["unique.storage.bytesfree"] = str(usage.free)
    if node.node_resources.disk_mb == 0:
        node.node_resources.disk_mb = usage.free // (1024 * 1024)


def _host(node: Node) -> None:
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()
    node.attributes["os.version"] = platform.version()
    node.attributes["unique.hostname"] = socket.gethostname()
    if not node.name:
        node.name = socket.gethostname()


def _network(node: Node) -> None:
    """Interface + speed detection (reference client/fingerprint/network.go);
    mirrors mock.node()'s shape so scheduling fit math sees a real offer."""
    from ..structs.structs import NetworkResource

    ip = "127.0.0.1"
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
    except OSError:
        pass
    node.attributes["unique.network.ip-address"] = ip
    if not node.node_resources.networks:
        node.node_resources.networks = [
            NetworkResource(device="eth0", cidr=f"{ip}/32", ip=ip, mbits=1000)
        ]


def _nomad(node: Node) -> None:
    from .. import __version__

    node.attributes["nomad.version"] = __version__
    node.attributes["nomad.revision"] = "tpu"


def _signal(node: Node) -> None:
    import signal as _s

    node.attributes["os.signals"] = ",".join(sorted(s.name for s in _s.Signals))


def _drivers(node: Node) -> None:
    """Driver detection (the reference's drivermanager fingerprint loop)."""
    from ..structs.structs import DriverInfo

    for name in available_drivers():
        fp = new_driver(name).fingerprint()
        healthy = fp.health == HEALTH_HEALTHY
        node.attributes[f"driver.{name}"] = "1" if healthy else "0"
        node.attributes.update(fp.attributes)
        node.drivers[name] = DriverInfo(
            name=name, detected=True, healthy=healthy,
            health_description=fp.health_description,
        )


FINGERPRINTERS: List[Callable[[Node], None]] = [
    _arch, _cpu, _memory, _storage, _host, _network, _nomad, _signal, _drivers,
]


def fingerprint_node(node: Node) -> Node:
    """Run every detector (fingerprint_manager.go:32 batch first run)."""
    if node.node_resources is None:
        node.node_resources = NodeResources()
    for fp in FINGERPRINTERS:
        fp(node)
    node.compute_class()
    return node
