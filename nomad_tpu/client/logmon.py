"""Logmon: out-of-process task log capture with rotation.

Fills the role of reference ``client/logmon`` (logmon.go + the go-plugin
subprocess launched per task via the main.go:16 init hack): the task's
stdout/stderr are FIFOs; a detached logmon process drains them into
size-rotated files ``<task>.stdout.0``, ``.1``, … in the alloc's shared
log dir, so log capture survives a client-agent restart exactly like the
task itself does (both are re-attached on recovery, not restarted).

Rotation matches the reference's logging/rotator: a file rolls when it
reaches ``max_file_size_mb``; the newest file has the highest index and
at most ``max_files`` are kept (structs.go LogConfig defaults 10 × 10MB).
"""
from __future__ import annotations

import os
import re
import subprocess
import sys
import threading
from typing import List, Optional, Tuple


class RotatingWriter:
    """Append-only writer over ``<dir>/<base>.<index>`` with size caps."""

    def __init__(self, directory: str, base: str, max_files: int = 10,
                 max_bytes: int = 10 << 20) -> None:
        self.directory = directory
        self.base = base
        self.max_files = max(1, max_files)
        self.max_bytes = max(1, max_bytes)
        os.makedirs(directory, exist_ok=True)
        self.index = self._newest_index()
        self._fh = open(self._path(self.index), "ab")

    def _path(self, index: int) -> str:
        return os.path.join(self.directory, f"{self.base}.{index}")

    def _indexes(self) -> List[int]:
        pat = re.compile(re.escape(self.base) + r"\.(\d+)$")
        out = []
        try:
            for name in os.listdir(self.directory):
                m = pat.match(name)
                if m:
                    out.append(int(m.group(1)))
        except OSError:
            pass
        return sorted(out)

    def _newest_index(self) -> int:
        idxs = self._indexes()
        return idxs[-1] if idxs else 0

    def write(self, data: bytes) -> None:
        while data:
            room = self.max_bytes - self._fh.tell()
            if room <= 0:
                self._rotate()
                continue
            chunk, data = data[:room], data[room:]
            self._fh.write(chunk)
        self._fh.flush()

    def _rotate(self) -> None:
        self._fh.close()
        self.index += 1
        self._fh = open(self._path(self.index), "ab")
        for old in self._indexes():
            if old <= self.index - self.max_files:
                try:
                    os.unlink(self._path(old))
                except OSError:
                    pass

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass


def _drain(fifo_path: str, writer: RotatingWriter) -> None:
    """Block until the task opens the FIFO, then copy until EOF."""
    try:
        # unbuffered: BufferedReader.read(n) would block until n bytes or
        # EOF, sitting on partial lines forever; raw reads return whatever
        # the pipe has
        with open(fifo_path, "rb", buffering=0) as f:
            while True:
                data = f.read(65536)
                if not data:
                    return
                writer.write(data)
    except OSError:
        pass
    finally:
        writer.close()


def run_logmon(log_dir: str, task_name: str, stdout_fifo: str, stderr_fifo: str,
               max_files: int, max_bytes: int) -> None:
    """Logmon process body: one drain thread per stream; exits when both
    streams hit EOF (task exited and closed its ends)."""
    threads = []
    for fifo, kind in ((stdout_fifo, "stdout"), (stderr_fifo, "stderr")):
        w = RotatingWriter(log_dir, f"{task_name}.{kind}", max_files, max_bytes)
        t = threading.Thread(target=_drain, args=(fifo, w), daemon=False)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    for fifo in (stdout_fifo, stderr_fifo):
        try:
            os.unlink(fifo)
        except OSError:
            pass


def spawn_logmon(
    log_dir: str,
    task_name: str,
    max_files: int = 10,
    max_bytes: int = 10 << 20,
) -> Tuple[str, str, subprocess.Popen]:
    """Create the task's stdout/stderr FIFOs and launch a detached logmon
    process draining them (go-plugin logmon launch, logmon_hook.go).
    Returns (stdout_fifo, stderr_fifo, process)."""
    os.makedirs(log_dir, exist_ok=True)
    # unique per-attempt FIFO names: an exiting logmon unlinks its own
    # FIFOs, which must never collide with a restart's fresh ones
    attempt = os.urandom(4).hex()
    stdout_fifo = os.path.join(log_dir, f".{task_name}.stdout.{attempt}.fifo")
    stderr_fifo = os.path.join(log_dir, f".{task_name}.stderr.{attempt}.fifo")
    for fifo in (stdout_fifo, stderr_fifo):
        os.mkfifo(fifo)
    # run THIS FILE as a bare script under -S -E: the module body is
    # stdlib-only, and skipping site processing + the package import
    # cuts interpreter startup from ~2s to ~30ms on a loaded box — a
    # burst of task starts must not exhaust the FIFO-attach deadline
    # queueing on interpreter startups (the reference's logmon is a
    # compiled go-plugin binary with no such cost)
    proc = subprocess.Popen(
        [
            sys.executable, "-S", "-E", os.path.abspath(__file__),
            log_dir, task_name, stdout_fifo, stderr_fifo,
            str(max_files), str(max_bytes),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        stdin=subprocess.DEVNULL,
        start_new_session=True,  # survive client restarts, like the task
    )
    return stdout_fifo, stderr_fifo, proc


def find_log_files(log_dir: str, task_name: str, kind: str) -> List[str]:
    """Sorted rotated files for one stream, oldest first."""
    pat = re.compile(re.escape(task_name) + r"\." + kind + r"\.(\d+)$")
    out = []
    try:
        for name in os.listdir(log_dir):
            m = pat.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(log_dir, name)))
    except OSError:
        return []
    return [p for _, p in sorted(out)]


def read_logs(log_dir: str, task_name: str, kind: str,
              offset: int = 0, limit: int = 1 << 20,
              origin: str = "start") -> Tuple[bytes, int]:
    """Read across the rotated file sequence as one logical stream
    (fs_endpoint.go logs semantics, simplified to non-follow).
    Returns (data, next_offset). ``origin="end"`` counts offset back from
    the stream end."""
    files = find_log_files(log_dir, task_name, kind)
    sizes = []
    total = 0
    for path in files:
        try:
            n = os.path.getsize(path)
        except OSError:
            n = 0
        sizes.append(n)
        total += n
    if origin == "end":
        offset = max(0, total - offset)
    offset = min(offset, total)
    out = bytearray()
    pos = 0
    for path, n in zip(files, sizes):
        if len(out) >= limit:
            break
        file_start = pos
        pos += n
        if pos <= offset:
            continue
        skip = max(0, offset - file_start)
        try:
            with open(path, "rb") as f:
                f.seek(skip)
                out.extend(f.read(min(limit - len(out), n - skip)))
        except OSError:
            continue
    return bytes(out), offset + len(out)


if __name__ == "__main__":
    a = sys.argv[1:]
    run_logmon(a[0], a[1], a[2], a[3], int(a[4]), int(a[5]))
