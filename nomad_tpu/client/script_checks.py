"""Script checks — commands run INSIDE the task via the driver exec API,
heartbeating a Consul TTL check (reference command/agent/consul/
script.go:1-40: Nomad registers script checks as TTL checks and updates
them itself after each run).
"""
from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional

logger = logging.getLogger("nomad_tpu.client.script_checks")


def parse_duration_s(v, default: float) -> float:
    """"10s"/"1m"/"500ms" (or a bare number of seconds) → seconds."""
    if v is None or v == "":
        return default
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("h"):
            return float(s[:-1]) * 3600.0
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        if s.endswith("s"):
            return float(s[:-1])
        return float(s)
    except ValueError:
        return default


class ScriptCheckRunner:
    """One script check: exec the command every ``interval`` with
    ``timeout``, report passing (exit 0) / critical to the TTL check."""

    def __init__(self, consul, check_id: str, command: str, args: List[str],
                 interval_s: float, timeout_s: float,
                 exec_fn: Callable[[List[str], float], tuple],
                 stop_event: Optional[threading.Event] = None) -> None:
        self.consul = consul
        self.check_id = check_id
        self.cmd = [command] + list(args or [])
        self.interval_s = max(interval_s, 0.1)
        self.timeout_s = timeout_s
        self.exec_fn = exec_fn
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"script-check-{self.check_id[-12:]}",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                out, code = self.exec_fn(self.cmd, self.timeout_s)
                # Consul's script convention (script.go): 0 passing,
                # 1 warning (degraded but discoverable), else critical
                status = {0: "passing", 1: "warning"}.get(code, "critical")
                output = out.decode(errors="replace") if isinstance(out, bytes) else str(out)
            except Exception as e:  # noqa: BLE001 — exec failure = critical
                status, output = "critical", str(e)
            # a stop that landed mid-exec means the check may already be
            # deregistered — don't heartbeat into the void
            if self._stop.is_set():
                return
            try:
                self.consul.update_ttl(self.check_id, status, output[-500:])
            except Exception as e:  # noqa: BLE001 — consul blip, retry next tick
                logger.warning("ttl update for %s failed: %s", self.check_id, e)
            if self._stop.wait(self.interval_s):
                return
