"""Per-call server failover for client agents.

Fills the role of reference ``client/servers/manager.go``: the client
keeps the full candidate server list, every RPC goes to the current best
server, and a failed call rotates the list and retries the remaining
servers before surfacing the error — so a dead server costs one timeout,
not the client.
"""
from __future__ import annotations

import random
import threading
from typing import List, Optional, Tuple

from ..rpc.endpoints import RemoteServerProxy
from ..rpc.transport import RPCError


class ServersManager:
    """Ordered candidate list with rotate-on-failure (manager.go
    NotifyFailedServer) and an initial shuffle so a fleet of clients
    doesn't pile onto the first configured server (rebalance)."""

    def __init__(self, addrs: List[Tuple[str, int]], shuffle: bool = True) -> None:
        if not addrs:
            raise ValueError("at least one server address required")
        self._lock = threading.Lock()
        self._addrs = list(addrs)
        if shuffle and len(self._addrs) > 1:
            random.shuffle(self._addrs)

    def current(self) -> Tuple[str, int]:
        with self._lock:
            return self._addrs[0]

    def all(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._addrs)

    def notify_failed(self, addr: Tuple[str, int]) -> None:
        """Cycle the failed server to the back (manager.go:303)."""
        with self._lock:
            if self._addrs and self._addrs[0] == addr:
                self._addrs.append(self._addrs.pop(0))

    def set_servers(self, addrs: List[Tuple[str, int]]) -> None:
        with self._lock:
            self._addrs = list(addrs) or self._addrs


class FailoverServerProxy:
    """RemoteServerProxy facade that routes every call through the
    ServersManager: use the current server, and on connection failure
    rotate and retry each remaining candidate once."""

    def __init__(self, manager: ServersManager, tls=None) -> None:
        self.manager = manager
        self.tls = tls
        self._lock = threading.Lock()
        # one proxy per server address, kept for the agent's lifetime
        # (bounded by the configured server count). Never closed during
        # failover: closing a proxy whose blocking RPC another thread is
        # inside would serialize every caller behind that 90s timeout.
        self._proxies: dict = {}

    def _proxy_for(self, addr: Tuple[str, int]) -> RemoteServerProxy:
        with self._lock:
            proxy = self._proxies.get(addr)
            if proxy is None:
                proxy = self._proxies[addr] = RemoteServerProxy(*addr, tls=self.tls)
            return proxy

    def _call(self, name: str, *args):
        attempts = max(1, len(self.manager.all()))
        last_err: Optional[BaseException] = None
        for _ in range(attempts):
            addr = self.manager.current()
            proxy = self._proxy_for(addr)
            try:
                return getattr(proxy, name)(*args)
            except (ConnectionError, OSError, TimeoutError) as e:
                last_err = e
                self.manager.notify_failed(addr)
            except RPCError as e:
                # leadership errors rotate like the reference's
                # canRetry (client/rpc.go IsErrNoLeader); other
                # application errors surface to the caller
                msg = str(e)
                if "NotLeaderError" in msg or "not the leader" in msg \
                        or "no known leader" in msg:
                    last_err = e
                    self.manager.notify_failed(addr)
                    continue
                raise e
        raise last_err  # type: ignore[misc]

    # -- ServerProxy surface --------------------------------------------

    def register_node(self, node):
        return self._call("register_node", node)

    def heartbeat(self, node_id: str):
        return self._call("heartbeat", node_id)

    def pull_allocs(self, node_id: str, min_index: int, timeout: float):
        return self._call("pull_allocs", node_id, min_index, timeout)

    def update_allocs(self, allocs):
        return self._call("update_allocs", allocs)

    def derive_vault_token(self, alloc_id, task_name, node_id="", node_secret=""):
        return self._call(
            "derive_vault_token", alloc_id, task_name, node_id, node_secret
        )

    def alloc_info(self, alloc_id: str):
        return self._call("alloc_info", alloc_id)

    def close(self) -> None:
        with self._lock:
            proxies = list(self._proxies.values())
            self._proxies.clear()
        for proxy in proxies:
            proxy.close()
