"""Client-local persistent state for restart recovery.

Fills the role of reference ``client/state/`` (state_database.go over
BoltDB via helper/boltdd): alloc specs and task driver handles survive a
client restart so runners re-attach instead of re-running. SQLite stands in
for BoltDB (both are single-file embedded stores; sqlite3 ships with the
interpreter). The in-memory variant mirrors client/state/memdb.go for tests.
"""
from __future__ import annotations

import json
import os
import pickle
import sqlite3
import threading
from typing import Dict, List, Optional, Tuple

from ..structs.structs import Allocation
from .drivers.base import TaskHandle


class StateDB:
    """Interface (client/state/interface.go)."""

    def put_allocation(self, alloc: Allocation) -> None:
        raise NotImplementedError

    def get_all_allocations(self) -> List[Allocation]:
        raise NotImplementedError

    def delete_allocation(self, alloc_id: str) -> None:
        raise NotImplementedError

    def put_task_handle(self, alloc_id: str, task_name: str, handle: TaskHandle) -> None:
        raise NotImplementedError

    def get_task_handles(self, alloc_id: str) -> Dict[str, TaskHandle]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemDB(StateDB):
    """client/state/memdb.go equivalent."""

    def __init__(self) -> None:
        self.allocs: Dict[str, Allocation] = {}
        self.handles: Dict[Tuple[str, str], TaskHandle] = {}

    def put_allocation(self, alloc: Allocation) -> None:
        self.allocs[alloc.id] = alloc

    def get_all_allocations(self) -> List[Allocation]:
        return list(self.allocs.values())

    def delete_allocation(self, alloc_id: str) -> None:
        self.allocs.pop(alloc_id, None)
        for key in [k for k in self.handles if k[0] == alloc_id]:
            del self.handles[key]

    def put_task_handle(self, alloc_id: str, task_name: str, handle: TaskHandle) -> None:
        self.handles[(alloc_id, task_name)] = handle

    def get_task_handles(self, alloc_id: str) -> Dict[str, TaskHandle]:
        return {t: h for (a, t), h in self.handles.items() if a == alloc_id}


class SqliteDB(StateDB):
    """client/state/state_database.go equivalent."""

    def __init__(self, state_dir: str) -> None:
        os.makedirs(state_dir, exist_ok=True)
        self.path = os.path.join(state_dir, "client_state.db")
        self._lock = threading.Lock()
        self._closed = False
        self.db = sqlite3.connect(self.path, check_same_thread=False)
        with self._lock:
            self.db.execute(
                "CREATE TABLE IF NOT EXISTS allocations (id TEXT PRIMARY KEY, data BLOB)"
            )
            self.db.execute(
                "CREATE TABLE IF NOT EXISTS task_handles ("
                "alloc_id TEXT, task_name TEXT, data BLOB,"
                "PRIMARY KEY (alloc_id, task_name))"
            )
            self.db.commit()

    def put_allocation(self, alloc: Allocation) -> None:
        blob = pickle.dumps(alloc)
        with self._lock:
            if self._closed:
                return
            self.db.execute(
                "INSERT OR REPLACE INTO allocations VALUES (?, ?)", (alloc.id, blob)
            )
            self.db.commit()

    def get_all_allocations(self) -> List[Allocation]:
        with self._lock:
            if self._closed:
                return []
            rows = self.db.execute("SELECT data FROM allocations").fetchall()
        return [pickle.loads(r[0]) for r in rows]

    def delete_allocation(self, alloc_id: str) -> None:
        with self._lock:
            if self._closed:
                return
            self.db.execute("DELETE FROM allocations WHERE id = ?", (alloc_id,))
            self.db.execute("DELETE FROM task_handles WHERE alloc_id = ?", (alloc_id,))
            self.db.commit()

    def put_task_handle(self, alloc_id: str, task_name: str, handle: TaskHandle) -> None:
        blob = pickle.dumps(handle)
        with self._lock:
            if self._closed:
                return
            self.db.execute(
                "INSERT OR REPLACE INTO task_handles VALUES (?, ?, ?)",
                (alloc_id, task_name, blob),
            )
            self.db.commit()

    def get_task_handles(self, alloc_id: str) -> Dict[str, TaskHandle]:
        with self._lock:
            if self._closed:
                return {}
            rows = self.db.execute(
                "SELECT task_name, data FROM task_handles WHERE alloc_id = ?",
                (alloc_id,),
            ).fetchall()
        return {name: pickle.loads(blob) for name, blob in rows}

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                self._closed = True
                self.db.close()
