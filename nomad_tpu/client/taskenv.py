"""Task environment builder.

Fills the role of reference ``client/taskenv/env.go``: assembles the
``NOMAD_*`` environment for a task plus attribute/meta interpolation of
``${...}`` references in task config values (taskenv is also what the
scheduler-side constraint resolver mirrors, feasible.go:497 resolveTarget).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from ..structs.structs import Allocation, Node, Task

_INTERP = re.compile(r"\$\{([^}]+)\}")


class TaskEnvBuilder:
    """Builds env maps (env.go:Builder)."""

    def __init__(
        self,
        node: Optional[Node],
        alloc: Optional[Allocation],
        task: Optional[Task],
        region: str = "global",
    ) -> None:
        self.node = node
        self.alloc = alloc
        self.task = task
        self.region = region
        self.task_dir: str = ""
        self.local_dir: str = ""
        self.secrets_dir: str = ""
        self.alloc_dir: str = ""

    def set_task_dirs(self, task_dir) -> "TaskEnvBuilder":
        self.task_dir = task_dir.dir
        self.local_dir = task_dir.local_dir
        self.secrets_dir = task_dir.secrets_dir
        self.alloc_dir = task_dir.shared_alloc_dir
        return self

    def _base_env(self) -> Dict[str, str]:
        """The NOMAD_* map only — computed without touching user env, so
        ``${env.*}`` resolution can't recurse into interpolation."""
        env: Dict[str, str] = {}
        if self.alloc_dir:
            env["NOMAD_ALLOC_DIR"] = self.alloc_dir
            env["NOMAD_TASK_DIR"] = self.local_dir
            env["NOMAD_SECRETS_DIR"] = self.secrets_dir
        if self.alloc is not None:
            env["NOMAD_ALLOC_ID"] = self.alloc.id
            env["NOMAD_ALLOC_NAME"] = self.alloc.name
            env["NOMAD_ALLOC_INDEX"] = str(self.alloc.index())
            env["NOMAD_GROUP_NAME"] = self.alloc.task_group
            env["NOMAD_JOB_ID"] = self.alloc.job_id
            env["NOMAD_NAMESPACE"] = self.alloc.namespace
            if self.alloc.job is not None:
                env["NOMAD_JOB_NAME"] = self.alloc.job.name
                env["NOMAD_JOB_PARENT_ID"] = self.alloc.job.parent_id
        if self.task is not None:
            env["NOMAD_TASK_NAME"] = self.task.name
            if self.task.resources is not None:
                env["NOMAD_CPU_LIMIT"] = str(self.task.resources.cpu)
                env["NOMAD_MEMORY_LIMIT"] = str(self.task.resources.memory_mb)
        if self.node is not None:
            env["NOMAD_DC"] = self.node.datacenter
            env["NOMAD_REGION"] = self.region
        # job -> group -> task meta, exposed as NOMAD_META_<key>
        if self.alloc is not None and self.alloc.job is not None and self.task is not None:
            meta = self.alloc.job.combined_task_meta(self.alloc.task_group, self.task.name)
            for k, v in meta.items():
                env[f"NOMAD_META_{k}"] = v
                env[f"NOMAD_META_{k.upper()}"] = v
        return env

    def build(self) -> Dict[str, str]:
        env = self._base_env()
        # user-specified env wins, with interpolation against the base map
        if self.task is not None:
            for k, v in self.task.env.items():
                env[k] = self.interpolate(v)
        return env

    # -- ${...} interpolation (env.go ReplaceEnv / feasible.go semantics) --

    def _resolve(self, ref: str) -> Optional[str]:
        if self.node is not None:
            if ref == "node.unique.id":
                return self.node.id
            if ref == "node.unique.name":
                return self.node.name
            if ref == "node.datacenter":
                return self.node.datacenter
            if ref == "node.class":
                return self.node.node_class
            if ref == "node.region":
                return self.region
            if ref.startswith("attr."):
                return self.node.attributes.get(ref[len("attr."):])
            if ref.startswith("meta."):
                return self.node.meta.get(ref[len("meta."):])
        if ref.startswith("env."):
            return self._base_env().get(ref[len("env."):])
        return None

    def interpolate(self, value: str) -> str:
        def sub(m: re.Match) -> str:
            resolved = self._resolve(m.group(1).strip())
            return resolved if resolved is not None else m.group(0)

        return _INTERP.sub(sub, value)
