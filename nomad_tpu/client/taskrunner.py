"""Task runner: one task's lifecycle on a client.

Fills the role of reference ``client/allocrunner/taskrunner/`` —
``task_runner.go:243 TaskRunner``, the prestart/poststart/exited/stop hook
chain (task_runner_hooks.go:61), and the restart tracker
(restarts/restarts.go). The hook set here is the subset with in-scope
backends: validate, taskDir, env builder, dispatch payload, templates
(Consul KV/Vault rendering + change modes, client/template.py), artifacts
(http(s)/file + checksum + unpack, client/artifacts.py); logmon is folded into the
drivers (stdout/stderr straight to the task log dir, reference logmon.go).
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..structs.structs import RestartPolicy, Task, TaskState
from .allocdir import TaskDir
from .drivers.base import DriverError, ExitResult, TaskConfig, TaskHandle, new_driver
from .taskenv import TaskEnvBuilder

# task events (reference structs.go TaskEvent types)
EV_RECEIVED = "Received"
EV_TASK_SETUP = "Task Setup"
EV_STARTED = "Started"
EV_TERMINATED = "Terminated"
EV_RESTARTING = "Restarting"
EV_NOT_RESTARTING = "Not Restarting"
EV_KILLING = "Killing"
EV_KILLED = "Killed"
EV_DRIVER_FAILURE = "Driver Failure"

STATE_PENDING = "pending"
STATE_RUNNING = "running"
STATE_DEAD = "dead"


class TaskEvent:
    def __init__(self, type_: str, message: str = "") -> None:
        self.type = type_
        self.message = message
        self.time_ns = time.time_ns()

    def __repr__(self) -> str:  # pragma: no cover
        return f"TaskEvent({self.type!r}, {self.message!r})"


class RestartTracker:
    """Restart policy decisions (reference restarts/restarts.go): up to
    ``attempts`` restarts per ``interval``, then mode: "delay" waits out the
    interval remainder, "fail" kills the task."""

    def __init__(self, policy: RestartPolicy, batch: bool) -> None:
        self.policy = policy or RestartPolicy()
        self.batch = batch
        self.count = 0
        self.start_time_ns = 0

    def next(self, exit_result: Optional[ExitResult], failure: bool) -> tuple:
        """Returns (behavior, wait_s): behavior in restart|wait|kill."""
        now = time.time_ns()
        if self.start_time_ns == 0 or now - self.start_time_ns > self.policy.interval_ns:
            self.count = 0
            self.start_time_ns = now
        # successful batch tasks don't restart; successful service tasks do
        if exit_result is not None and exit_result.successful() and self.batch:
            return ("kill", 0.0)
        self.count += 1
        delay = self._jitter(self.policy.delay_ns / 1e9)
        if self.count <= self.policy.attempts:
            return ("restart", delay)
        if self.policy.mode == "fail":
            return ("kill", 0.0)
        # delay mode: wait out the rest of the interval, then a fresh window
        remaining = (self.start_time_ns + self.policy.interval_ns - now) / 1e9
        return ("wait", self._jitter(max(remaining, 0.0) + delay))

    @staticmethod
    def _jitter(base: float) -> float:
        return base * (1.0 + random.random() * 0.25)


class TaskRunner:
    def __init__(
        self,
        alloc,
        task: Task,
        task_dir: TaskDir,
        node=None,
        on_state_change: Optional[Callable[[], None]] = None,
        update_interval: float = 0.05,
        device_manager=None,
        driver_factory=None,
        consul=None,
        vault_fn=None,
        vault_addr: str = "",
    ) -> None:
        self.alloc = alloc
        self.task = task
        self.task_dir = task_dir
        self.node = node
        self.on_state_change = on_state_change
        self.device_manager = device_manager
        self.driver_factory = driver_factory or new_driver
        self.consul = consul
        self.vault_fn = vault_fn
        self.vault_addr = vault_addr
        self._vault_token = ""
        self._template_hook = None
        self._consul_ids = []
        self._script_checks = []
        self.update_interval = update_interval
        self.logger = logging.getLogger(f"nomad_tpu.taskrunner.{task.name}")

        self.driver = self.driver_factory(task.driver)
        self.task_id = f"{alloc.id}/{task.name}"
        self.handle: Optional[TaskHandle] = None
        self._recovered = False
        self.state = TaskState(state=STATE_PENDING)
        self.events: List[TaskEvent] = []
        self.kill_requested = threading.Event()
        self._user_restart = threading.Event()
        self.done = threading.Event()
        self._thread: Optional[threading.Thread] = None

        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        policy = task.restart_policy or (tg.restart_policy if tg else None)
        batch = bool(alloc.job and alloc.job.type == "batch")
        self.restart_tracker = RestartTracker(policy, batch)

    # -- lifecycle -------------------------------------------------------

    def run(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"taskrunner-{self.task.name}", daemon=True
        )
        self._thread.start()

    MAX_SYNCED_EVENTS = 10  # reference structs.go taskState event cap

    def _emit(self, event: TaskEvent) -> None:
        self.events.append(event)
        self.state.events.append({
            "Type": event.type,
            "Message": event.message,
            "DisplayMessage": event.message or event.type,
            "Time": event.time_ns,
        })
        if len(self.state.events) > self.MAX_SYNCED_EVENTS:
            self.state.events = self.state.events[-self.MAX_SYNCED_EVENTS:]
        self.state.restarts = max(0, self.restart_tracker.count - 1)
        if self.on_state_change is not None:
            self.on_state_change()

    def _set_state(self, state: str, failed: bool = False) -> None:
        self.state.state = state
        if failed:
            self.state.failed = True
        if state == STATE_RUNNING and self.state.started_at_ns == 0:
            self.state.started_at_ns = time.time_ns()
        if state == STATE_DEAD:
            self.state.finished_at_ns = time.time_ns()
        if self.on_state_change is not None:
            self.on_state_change()

    def _run(self) -> None:
        self._emit(TaskEvent(EV_RECEIVED))
        try:
            self._prestart()
        except Exception as e:  # noqa: BLE001
            self._emit(TaskEvent(EV_DRIVER_FAILURE, str(e)))
            self._set_state(STATE_DEAD, failed=True)
            self.done.set()
            return

        while not self.kill_requested.is_set():
            try:
                if self._recovered:
                    # a restart re-attached to the live task; skip the start
                    self._recovered = False
                else:
                    self._start_task()
            except DriverError as e:
                self._emit(TaskEvent(EV_DRIVER_FAILURE, str(e)))
                behavior, wait_s = self.restart_tracker.next(None, failure=True)
                if behavior == "kill" or not self._sleep(wait_s):
                    self._set_state(STATE_DEAD, failed=True)
                    break
                self._emit(TaskEvent(EV_RESTARTING, f"in {wait_s:.1f}s"))
                continue

            self._set_state(STATE_RUNNING)
            self._emit(TaskEvent(EV_STARTED))
            self._register_services()
            if self._template_hook is not None and self._template_hook._thread is None:
                self._template_hook.start_watcher()
            result = self._wait_exit()
            self._deregister_services()
            if result is None:  # killed
                self._set_state(STATE_DEAD)
                break
            self._emit(
                TaskEvent(
                    EV_TERMINATED,
                    f"exit_code={result.exit_code} signal={result.signal}",
                )
            )
            if self._user_restart.is_set():
                self._user_restart.clear()
                self._emit(TaskEvent(EV_RESTARTING, "user requested"))
                continue  # unconditional, no policy attempt consumed
            behavior, wait_s = self.restart_tracker.next(result, failure=False)
            if behavior == "kill":
                self._set_state(STATE_DEAD, failed=not result.successful())
                break
            self._emit(TaskEvent(EV_RESTARTING, f"{behavior} {wait_s:.1f}s"))
            if not self._sleep(wait_s):
                self._set_state(STATE_DEAD)
                break
        else:
            self._set_state(STATE_DEAD)
        if self._template_hook is not None:
            self._template_hook.stop()
        self.done.set()

    def _mount_volumes(self) -> None:
        """Host-volume mounts (volume_hook.go): volume_mount.volume names
        a group ``volume`` request whose source must exist in the node's
        host_volumes. Destination resolves inside the task dir (leading
        "/" mapped to the task root, like the container-absolute paths
        the reference mounts)."""
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        vol_requests = tg.volumes if tg is not None else {}
        host_vols = self.node.host_volumes if self.node is not None else {}
        root = os.path.realpath(self.task_dir.dir)
        for vm in self.task.volume_mounts:
            name = vm.volume
            req = vol_requests.get(name)
            if req is None:
                raise ValueError(
                    f"volume_mount references undeclared volume {name!r}")
            hv = host_vols.get(req.source)
            if hv is None:
                raise ValueError(
                    f"host volume {req.source!r} not present on this node")
            dest_rel = str(vm.destination or name).lstrip("/")
            dest = os.path.join(root, dest_rel)
            # escape check resolves the PARENT only: the final component
            # may legitimately be the (re-used, e.g. after a client
            # restart) symlink pointing at the host path
            parent = os.path.realpath(os.path.dirname(dest))
            norm = os.path.normpath(dest)
            if (parent != root and not parent.startswith(root + os.sep))                     or not norm.startswith(root):
                raise ValueError(
                    f"volume destination escapes task dir: {dest_rel}")
            os.makedirs(parent, exist_ok=True)
            if os.path.islink(dest):
                if os.readlink(dest) == hv.path:
                    continue  # already mounted (prestart re-run)
                os.unlink(dest)
            elif os.path.exists(dest):
                raise ValueError(
                    f"volume destination already exists: {dest_rel}")
            os.symlink(hv.path, dest)
            if vm.read_only or req.read_only:
                # symlink realization cannot enforce read-only without
                # bind mounts (the reference's raw_exec doesn't support
                # volume mounts at all); advisory here
                self.logger.warning(
                    "volume %s mounted read_only=true: advisory only "
                    "under the symlink realization", name,
                )

    def _write_envoy_bootstrap(self, service_name: str) -> None:
        """Generate the sidecar's Envoy bootstrap into
        secrets/envoy_bootstrap.json (the reference shells out to
        ``consul connect envoy -bootstrap``; this runtime generates the
        equivalent static bootstrap: admin listener, node identity for
        the proxy service, and Consul's agent as the config source)."""
        import json as _json

        proxy_id = f"_nomad-group-{self.alloc.id}-{service_name}-sidecar-proxy"
        # ADS rides Consul's agent gRPC xDS endpoint (port 8502), NOT the
        # HTTP API — derive the host from the configured HTTP address
        grpc_host = "127.0.0.1"
        if self.consul is not None:
            from urllib.parse import urlparse

            http_addr = getattr(self.consul.config, "address", "")
            if http_addr:
                grpc_host = urlparse(http_addr).hostname or "127.0.0.1"
        bootstrap = {
            "admin": {
                "access_log_path": "/dev/null",
                "address": {"socket_address": {
                    "address": "127.0.0.1", "port_value": 19001}},
            },
            "node": {
                "cluster": service_name,
                "id": proxy_id,
                "metadata": {
                    "namespace": self.alloc.namespace or "default",
                    "envoy_version": "1.11.2",
                },
            },
            "static_resources": {
                "clusters": [{
                    "name": "local_agent",
                    "connect_timeout": "1s",
                    "type": "STATIC",
                    "hosts": [{"url": f"tcp://{grpc_host}:8502"}],
                }],
            },
            "dynamic_resources": {
                "lds_config": {"ads": {}},
                "cds_config": {"ads": {}},
                "ads_config": {
                    "api_type": "GRPC",
                    "grpc_services": {"envoy_grpc": {
                        "cluster_name": "local_agent"}},
                },
            },
        }
        dest = os.path.join(self.task_dir.secrets_dir, "envoy_bootstrap.json")
        with open(dest, "w") as f:
            _json.dump(bootstrap, f, indent=2)
        os.chmod(dest, 0o600)

    def _signal_task(self, signal: str) -> None:
        """Template change_mode=signal application."""
        try:
            self.driver.signal_task(self.task_id, signal)
        except DriverError as e:
            self.logger.warning("template change signal failed: %s", e)

    def _template_restart(self) -> None:
        """Template change_mode=restart: restart only a RUNNING task. A
        task that's already dead or in restart backoff picks the
        re-rendered file up on its next start — latching the
        user-restart flag there would later override the restart policy
        (e.g. rerunning a completed batch task)."""
        if self.state.state == STATE_RUNNING and self.handle is not None:
            self.restart()

    def _sleep(self, seconds: float) -> bool:
        """False if the kill arrived during the sleep."""
        return not self.kill_requested.wait(timeout=seconds)

    # -- hooks (task_runner_hooks.go subset) -----------------------------

    def _prestart(self) -> None:
        self._emit(TaskEvent(EV_TASK_SETUP))
        # validate hook
        if not self.task.driver:
            raise ValueError("task has no driver")
        # taskDir hook
        self.task_dir.build()
        # dispatch payload hook (parameterized jobs)
        payload = self.alloc.job.payload if self.alloc.job else b""
        if payload and self.task.dispatch_payload_file:
            dest = os.path.join(self.task_dir.local_dir, self.task.dispatch_payload_file)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(payload)
        # volume hook (task_runner_hooks.go volumes hook): resolve the
        # task's volume_mount stanzas through the group's volume requests
        # to the node's host volumes; realized as symlinks inside the
        # task dir (this runtime's raw_exec-compatible bind)
        if getattr(self.task, "volume_mounts", None):
            self._mount_volumes()
        # artifacts hook (artifact_hook.go + go-getter core): http(s) and
        # file sources, checksum verification, archive unpacking
        if self.task.artifacts:
            from .artifacts import fetch_artifact

            builder = TaskEnvBuilder(self.node, self.alloc, self.task) \
                .set_task_dirs(self.task_dir)
            self._emit(TaskEvent(EV_TASK_SETUP, "downloading artifacts"))
            for art in self.task.artifacts:
                fetch_artifact(art, self.task_dir.dir, interp=builder.interpolate)
        # vault hook (task_runner_hooks.go vault hook): derive the task's
        # token and drop it in the secrets dir. Derivation goes over RPC,
        # so transient failures (leader election, blip) retry with backoff
        # (vault_hook.go deriveVaultToken retry loop) until the kill.
        if self.task.vault and self.vault_fn is not None:
            backoff = 0.5
            while True:
                try:
                    self._vault_token = self.vault_fn(self.alloc.id, self.task.name)
                    break
                except Exception as e:  # noqa: BLE001
                    if self.kill_requested.is_set() or backoff > 16:
                        raise
                    self.logger.warning(
                        "vault token derivation failed (retrying in %.1fs): %s",
                        backoff, e,
                    )
                    if self.kill_requested.wait(backoff):
                        raise
                    backoff *= 2
            token_path = os.path.join(self.task_dir.secrets_dir, "vault_token")
            with open(token_path, "w") as f:
                f.write(self._vault_token)
            os.chmod(token_path, 0o600)
        # envoy bootstrap hook (task_runner_hooks.go:112-116,
        # envoybootstrap_hook.go): a Connect sidecar task gets its Envoy
        # bootstrap config written into its secrets dir before start
        # (the stanza's default args point at it)
        kind = getattr(self.task, "kind", "") or ""
        if kind.startswith("connect-proxy:"):
            self._write_envoy_bootstrap(kind.split(":", 1)[1])
        # template hook (task_runner_hooks.go template hook /
        # consul-template): initial render blocks on missing dependencies;
        # the change watcher starts after the task is up
        if self.task.templates:
            from .template import TemplateHook

            builder = TaskEnvBuilder(self.node, self.alloc, self.task) \
                .set_task_dirs(self.task_dir)
            vault_read = None
            if self.vault_addr:
                from ..integrations.vault import VaultClient, VaultConfig

                vc = VaultClient(VaultConfig(
                    enabled=True, address=self.vault_addr,
                    token=self._vault_token,
                ))
                vault_read = vc.read_secret
            self._template_hook = TemplateHook(
                self.task.templates, self.task_dir.dir,
                consul=self.consul, vault_read=vault_read,
                env_fn=lambda: builder.build(),
                interp=builder.interpolate,
                restart_cb=self._template_restart,
                signal_cb=self._signal_task,
                # share the kill event: a task kill interrupts the
                # dependency wait instead of riding out block_timeout
                stop_event=self.kill_requested,
            )
            self._emit(TaskEvent(EV_TASK_SETUP, "rendering templates"))
            self._template_hook.prestart()

    def _register_services(self) -> None:
        """Consul services hook (task_runner_hooks.go services hook) +
        script checks (command/agent/consul/script.go: the command runs
        through the driver exec API and heartbeats a TTL check)."""
        if self.consul is None or not self.task.services:
            return
        try:
            address = self.node.attributes.get("unique.network.ip-address", "") \
                if self.node is not None else ""
            self._consul_ids = self.consul.register_task_services(
                self.alloc, self.task, address=address
            )
        except Exception as e:  # noqa: BLE001 — consul outage isn't fatal
            self.logger.warning("consul registration failed: %s", e)
            return
        from ..integrations.consul import task_service_id
        from .script_checks import ScriptCheckRunner, parse_duration_s

        for svc in self.task.services or []:
            sid = task_service_id(self.alloc.id, self.task.name, svc.name)
            for k, chk in enumerate(getattr(svc, "checks", []) or []):
                if not self.consul.is_script_check(chk):
                    continue
                interval = parse_duration_s(chk.get("interval"), 10.0)
                timeout = parse_duration_s(chk.get("timeout"), 5.0)
                check_id = f"{sid}-script-{k}"
                try:
                    # TTL = interval + timeout + slack: a heartbeat cycle
                    # is one (possibly timeout-long) run plus the sleep,
                    # so anything shorter flaps a slow-but-passing script
                    # (script.go registers interval+timeout the same way);
                    # a wedged script still turns critical on its own
                    self.consul.register_ttl_check(
                        check_id, chk.get("name", f"script check {k}"),
                        sid, f"{max(interval + timeout + 1.0, 2.0):.0f}s",
                    )
                except Exception as e:  # noqa: BLE001
                    self.logger.warning("script check register failed: %s", e)
                    continue
                runner = ScriptCheckRunner(
                    self.consul, check_id, chk.get("command", ""),
                    chk.get("args") or [], interval, timeout,
                    exec_fn=lambda cmd, t: self.driver.exec_task(self.task_id, cmd, t),
                )
                runner.start()
                self._script_checks.append(runner)

    def _deregister_services(self) -> None:
        for runner in self._script_checks:
            runner.stop()
            try:
                self.consul.deregister_check(runner.check_id)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("script check deregister failed: %s", e)
        self._script_checks = []
        if self.consul is None or not self._consul_ids:
            return
        try:
            self.consul.deregister_ids(self._consul_ids)
        except Exception as e:  # noqa: BLE001
            self.logger.warning("consul deregistration failed: %s", e)
        self._consul_ids = []

    def _device_reservation(self):
        """Device hook (task_runner_hooks.go device hook): reserve the
        alloc's assigned device instances, yielding env/mounts/devices.
        Failures surface as DriverError so the run loop's restart policy
        handles them like any other start failure."""
        if self.device_manager is None or self.alloc.allocated_resources is None:
            return None
        task_res = self.alloc.allocated_resources.tasks.get(self.task.name)
        if task_res is None or not task_res.devices:
            return None
        try:
            return self.device_manager.reserve(task_res.devices)
        except DriverError:
            raise
        except Exception as e:  # noqa: BLE001 — reservation errors are varied
            raise DriverError(f"device reservation failed: {e}") from e

    def _setup_logmon(self):
        """Logmon hook (task_runner_hooks.go logmon hook): rotated capture
        through FIFOs, detached so it survives client restarts. Returns
        (stdout_path, stderr_path)."""
        log_dir = self.task_dir.log_dir
        plain = (
            os.path.join(log_dir, f"{self.task.name}.stdout.0"),
            os.path.join(log_dir, f"{self.task.name}.stderr.0"),
        )
        if not getattr(self.driver, "produces_logs", False):
            return plain
        from .logmon import spawn_logmon

        lc = self.task.log_config
        try:
            stdout_fifo, stderr_fifo, self._logmon = spawn_logmon(
                log_dir, self.task.name,
                max_files=lc.max_files,
                max_bytes=lc.max_file_size_mb << 20,
            )
            return stdout_fifo, stderr_fifo
        except OSError as e:
            self.logger.warning("logmon unavailable, writing plain files: %s", e)
            return plain

    def _kill_logmon(self) -> None:
        lm = getattr(self, "_logmon", None)
        if lm is not None and lm.poll() is None:
            lm.terminate()
        self._logmon = None

    def _start_task(self) -> None:
        env = (
            TaskEnvBuilder(self.node, self.alloc, self.task)
            .set_task_dirs(self.task_dir)
            .build()
        )
        reservation = self._device_reservation()
        if reservation is not None:
            env.update(reservation.envs)
        if self._vault_token and (self.task.vault or {}).get("env", True):
            env["VAULT_TOKEN"] = self._vault_token
        os.makedirs(self.task_dir.log_dir, exist_ok=True)
        stdout_path, stderr_path = self._setup_logmon()
        cfg = TaskConfig(
            id=self.task_id,
            name=self.task.name,
            alloc_id=self.alloc.id,
            env=env,
            config=dict(self.task.config),
            task_dir=self.task_dir,
            stdout_path=stdout_path,
            stderr_path=stderr_path,
            cpu_limit=self.task.resources.cpu if self.task.resources else 0,
            memory_limit_mb=self.task.resources.memory_mb if self.task.resources else 0,
            mounts=list(reservation.mounts) if reservation else [],
            devices=list(reservation.devices) if reservation else [],
        )
        # interpolate driver config values
        builder = TaskEnvBuilder(self.node, self.alloc, self.task).set_task_dirs(self.task_dir)
        cfg.config = {
            k: builder.interpolate(v) if isinstance(v, str) else v
            for k, v in cfg.config.items()
        }
        try:
            self.handle = self.driver.start_task(cfg)
        except Exception:
            # a logmon blocked on its never-opened FIFOs must not outlive
            # the failed start
            self._kill_logmon()
            raise

    def _wait_exit(self) -> Optional[ExitResult]:
        while True:
            result = self.driver.wait_task(self.task_id, timeout=self.update_interval)
            if result is not None:
                try:
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                return result
            if self.kill_requested.is_set():
                self._emit(TaskEvent(EV_KILLING))
                kill_timeout = (self.task.kill_timeout_ns or 5 * 10**9) / 1e9
                try:
                    self.driver.stop_task(self.task_id, kill_timeout, self.task.kill_signal or "SIGTERM")
                    self.driver.destroy_task(self.task_id, force=True)
                except DriverError:
                    pass
                self._emit(TaskEvent(EV_KILLED))
                return None

    # -- external control ------------------------------------------------

    def recover(self, handle: TaskHandle) -> bool:
        """Re-attach to a live task before ``run()`` (RecoverTask,
        plugins/drivers/driver.go:47). Returns False when the task is gone
        — the run loop then starts it fresh."""
        try:
            self.driver.recover_task(handle)
        except DriverError:
            return False
        self.handle = handle
        self._recovered = True
        return True

    def kill(self, timeout: float = 10.0) -> None:
        self.kill_requested.set()
        self.done.wait(timeout=timeout)

    def restart(self) -> None:
        """User-requested in-place restart (alloc restart CLI). Bypasses
        the restart policy counter — the reference's Alloc.Restart is
        unconditional, not a policy event."""
        self._user_restart.set()
        if self.handle is not None:
            try:
                self.driver.stop_task(self.task_id, 5.0)
            except DriverError:
                pass
