"""Template rendering hook — the consul-template slot (reference
client/allocrunner/taskrunner/template/template.go:1-80, registered at
task_runner_hooks.go:80-90).

A task's ``template`` stanzas render Consul KV values and Vault secrets
into files under the task directory, re-render when the upstream values
change, and apply the stanza's ``change_mode``:

  noop     leave the running task alone
  restart  restart the task (the default)
  signal   send ``change_signal`` to the task

Template language: a documented subset of consul-template's function
set (full Go text/template is out of scope for this runtime):

  {{ key "path" }}             Consul KV value (blocks until present,
                               like consul-template's dependency wait)
  {{ secret "path" "field" }}  Vault secret field (KV-v1 GET /v1/<path>)
  {{ env "NAME" }}             task environment variable

plus ``${...}`` task-env interpolation applied to source/destination
paths. ``data`` provides inline template text; ``source`` names a file
(task-dir relative). ``destination`` is task-dir relative; ``perms`` is
an octal string (e.g. "600"); ``splay``/poll interval via the hook.
"""
from __future__ import annotations

import logging
import os
import re
import threading
from typing import Callable, Dict, List, Optional

logger = logging.getLogger("nomad_tpu.client.template")

_FUNC_RE = re.compile(
    r"\{\{\s*(key|secret|env)\s+\"([^\"]+)\"(?:\s+\"([^\"]+)\")?\s*\}\}"
)

DEFAULT_POLL_S = 0.5


class TemplateError(Exception):
    """Render failure — fails/blocks the task like consul-template."""


class TemplateHook:
    """Renders a task's template stanzas and watches for changes.

    ``restart_cb``/``signal_cb`` apply change modes; ``consul`` is a
    ConsulClient (or None), ``vault_read`` a callable(path) -> dict.
    """

    def __init__(self, templates: List[Dict], task_root: str,
                 consul=None, vault_read: Optional[Callable] = None,
                 env_fn: Optional[Callable[[], Dict[str, str]]] = None,
                 interp: Optional[Callable[[str], str]] = None,
                 restart_cb: Optional[Callable[[], None]] = None,
                 signal_cb: Optional[Callable[[str], None]] = None,
                 poll_interval: float = DEFAULT_POLL_S,
                 block_timeout: float = 30.0,
                 stop_event: Optional[threading.Event] = None) -> None:
        self.templates = templates or []
        self.task_root = task_root
        self.consul = consul
        self.vault_read = vault_read
        self.env_fn = env_fn or (lambda: {})
        self.interp = interp or (lambda s: s)
        self.restart_cb = restart_cb
        self.signal_cb = signal_cb
        self.poll_interval = poll_interval
        self.block_timeout = block_timeout
        self._rendered: Dict[int, str] = {}
        # the caller may supply its kill event so a task kill interrupts
        # the prestart dependency wait immediately
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- rendering -------------------------------------------------------

    def _template_text(self, tpl: Dict) -> str:
        if tpl.get("data"):
            return str(tpl["data"])
        source = self.interp(str(tpl.get("source", "")))
        if not source:
            raise TemplateError("template has neither data nor source")
        path = source if os.path.isabs(source) else os.path.join(self.task_root, source)
        with open(path) as f:
            return f.read()

    def _resolve(self, func: str, arg: str, field: Optional[str]):
        """One template function call; None = dependency missing (block)."""
        if func == "key":
            if self.consul is None:
                raise TemplateError("template uses {{ key }} but consul is not configured")
            return self.consul.kv_get(arg)
        if func == "secret":
            if self.vault_read is None:
                raise TemplateError("template uses {{ secret }} but vault is not configured")
            try:
                data = self.vault_read(arg)
            except Exception as e:  # noqa: BLE001
                # a MISSING secret blocks (dependency wait); auth/transport
                # errors are permanent — surface them instead of a
                # misleading dependency timeout
                if "404" in str(e):
                    return None
                raise TemplateError(f"vault read {arg!r} failed: {e}") from e
            if data is None:
                return None
            if field:
                return data.get(field)
            if len(data) == 1:
                return next(iter(data.values()))
            raise TemplateError(
                f"secret {arg!r} has multiple fields; name one: {sorted(data)}"
            )
        if func == "env":
            return self.env_fn().get(arg, "")
        raise TemplateError(f"unknown template function {func!r}")

    def render_once(self, tpl: Dict) -> Optional[str]:
        """Rendered content, or None when a dependency is missing."""
        text = self._template_text(tpl)
        missing: List[str] = []

        def sub(m: re.Match) -> str:
            val = self._resolve(m.group(1), m.group(2), m.group(3))
            if val is None:
                missing.append(m.group(2))
                return ""
            return str(val)

        out = _FUNC_RE.sub(sub, text)
        if missing:
            return None
        return out

    def _write(self, tpl: Dict, content: str) -> str:
        dest_rel = self.interp(str(tpl.get("destination", "")))
        if not dest_rel:
            raise TemplateError("template has no destination")
        dest = os.path.realpath(os.path.join(self.task_root, dest_rel))
        root = os.path.realpath(self.task_root)
        if dest != root and not dest.startswith(root + os.sep):
            raise TemplateError(f"template destination escapes task dir: {dest_rel}")
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "w") as f:
            f.write(content)
        perms = str(tpl.get("perms", "") or "")
        if perms:
            os.chmod(dest, int(perms, 8))
        return dest

    def prestart(self) -> None:
        """Initial render of every template; blocks (polling) until every
        dependency exists, up to ``block_timeout`` — consul-template's
        dependency wait."""
        deadline = None
        pending = list(enumerate(self.templates))
        while pending:
            still = []
            for i, tpl in pending:
                content = self.render_once(tpl)
                if content is None:
                    still.append((i, tpl))
                    continue
                self._write(tpl, content)
                self._rendered[i] = content
            if not still:
                return
            import time as _time

            if deadline is None:
                deadline = _time.monotonic() + self.block_timeout
            if _time.monotonic() >= deadline:
                raise TemplateError(
                    "timed out waiting for template dependencies: "
                    f"{[t.get('destination') for _, t in still]}"
                )
            if self._stop.wait(self.poll_interval):
                raise TemplateError("task stopping")
            pending = still

    # -- change watching -------------------------------------------------

    def start_watcher(self) -> None:
        if not self.templates:
            return
        self._thread = threading.Thread(
            target=self._watch_loop, name="template-watcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            restart = False
            signals: List[str] = []
            for i, tpl in enumerate(self.templates):
                try:
                    content = self.render_once(tpl)
                except Exception as e:  # noqa: BLE001 — watcher must survive
                    logger.warning("template re-render failed: %s", e)
                    continue
                if content is None or content == self._rendered.get(i):
                    continue
                try:
                    self._write(tpl, content)
                except Exception as e:  # noqa: BLE001
                    logger.warning("template write failed: %s", e)
                    continue
                self._rendered[i] = content
                mode = str(tpl.get("change_mode", "restart") or "restart")
                if mode == "restart":
                    restart = True
                elif mode == "signal":
                    signals.append(str(tpl.get("change_signal", "SIGHUP")))
                # noop: just the re-render
            # coalesce: one restart beats any number of signals
            # (template.go change-mode application)
            if restart and self.restart_cb is not None:
                self.restart_cb()
            elif signals and self.signal_cb is not None:
                for sig in signals:
                    self.signal_cb(sig)
