"""Gossip membership (the serf/memberlist slot).

Fills the role of the reference's vendored hashicorp/serf + memberlist
(nomad/serf.go, nomad/server.go:1250 setupSerf): SWIM-style failure
detection and metadata dissemination over UDP, feeding server peer
reconciliation and cross-region federation.
"""
from .memberlist import Member, Memberlist, MemberlistConfig

__all__ = ["Member", "Memberlist", "MemberlistConfig"]
