"""SWIM gossip membership over UDP.

Fills the role of the reference's vendored hashicorp/memberlist + serf
(nomad/server.go:1250 setupSerf; nomad/serf.go event loop): each member
runs a UDP listener, periodically probes a random peer (ping → ack, with
indirect ping-req relays on timeout), and disseminates membership
transitions (alive / suspect / dead / left) as piggybacked broadcasts on
every protocol message. Tags ride the alive message, so metadata updates
(e.g. a server gaining leadership) propagate the same way joins do, and a
member that hears rumors of its own death refutes them with a higher
incarnation number — the standard SWIM+inc protocol memberlist implements.

Intentional deltas from memberlist: push-pull state sync rides UDP (server
gossip pools are small — a handful of servers per region, never the
thousands of client nodes, which don't gossip in the reference either:
clients poll servers over RPC). Message encryption fills the serf keyring
slot: with ``MemberlistConfig.encrypt_key`` set, every datagram is
AES-GCM sealed and unauthenticated packets are dropped (single static
key; no key rotation protocol).
"""
from __future__ import annotations

import logging
import random
import socket
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

STATUS_ALIVE = "alive"
STATUS_SUSPECT = "suspect"
STATUS_DEAD = "dead"
STATUS_LEFT = "left"

MAX_DATAGRAM = 60000


def resolve_advertise_host(host: str) -> str:
    """An unroutable advertise address (0.0.0.0/::) would have every peer
    dialing itself; best-effort resolve the host's primary address."""
    if host in ("0.0.0.0", "::"):
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"
    return host


@dataclass
class Member:
    name: str
    host: str
    port: int
    tags: Dict[str, str] = field(default_factory=dict)
    incarnation: int = 0
    status: str = STATUS_ALIVE
    status_change: float = field(default_factory=time.monotonic)

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def to_wire(self) -> dict:
        return {
            "name": self.name,
            "host": self.host,
            "port": self.port,
            "tags": self.tags,
            "inc": self.incarnation,
            "status": self.status,
        }


@dataclass
class MemberlistConfig:
    name: str = "node"
    bind_host: str = "127.0.0.1"
    bind_port: int = 0  # 0 = ephemeral
    # address gossiped to peers; defaults to the bound address, which is
    # wrong when binding 0.0.0.0 — set it explicitly for multi-host
    advertise_host: str = ""
    probe_interval: float = 0.3
    probe_timeout: float = 0.15
    indirect_checks: int = 2
    suspicion_timeout: float = 1.2  # suspect → dead
    push_pull_interval: float = 2.0
    retransmit_mult: int = 3
    dead_reclaim_time: float = 30.0  # forget dead/left members after this
    # Serf keyring slot (reference agent `encrypt` option, memberlist
    # SecretKey): base64 or raw 16/24/32-byte key. When set, every
    # datagram is AES-GCM sealed; plaintext (or wrong-key) packets are
    # dropped. All members must share the key.
    encrypt_key: bytes = b""


def _normalize_gossip_key(key, logger) -> bytes:
    """16/24/32 raw bytes, or their base64 (serf keygen's textual form).
    Base64 takes PRECEDENCE: base64 of a 16-byte key is exactly 24 chars,
    so "len in (16,24,32) -> raw" would silently use the ASCII text as
    the key and split the cluster against nodes configured with the
    decoded bytes."""
    import base64 as b64_mod

    if isinstance(key, str):
        key = key.encode()
    decoded = None
    try:
        decoded = b64_mod.b64decode(key, validate=True)
    except Exception:  # noqa: BLE001 — not base64: try raw
        decoded = None
    if decoded is not None and len(decoded) in (16, 24, 32):
        if len(key) in (16, 24, 32):
            # ambiguous: a 32-char ASCII string is both a valid raw key
            # and valid base64 of 24 bytes — be loud about which reading
            # wins so mixed fleets can't silently partition
            logger.warning(
                "encrypt key is both raw-sized and base64-decodable; "
                "using the BASE64 interpretation (%d bytes)", len(decoded),
            )
        return bytes(decoded)
    if len(key) not in (16, 24, 32):
        raise ValueError(
            "encrypt key must be 16/24/32 bytes raw, or their base64"
        )
    return bytes(key)


class Memberlist:
    """One gossip participant. Thread-safe; all callbacks fire off the
    listener/probe threads — keep them fast and non-blocking."""

    def __init__(self, config: MemberlistConfig, tags: Optional[Dict[str, str]] = None):
        self.config = config
        self.logger = logging.getLogger(f"nomad_tpu.gossip.{config.name}")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((config.bind_host, config.bind_port))
        bound: Tuple[str, int] = self._sock.getsockname()
        advertise_host = resolve_advertise_host(config.advertise_host or bound[0])
        self.addr: Tuple[str, int] = (advertise_host, bound[1])

        # Keyring (serf keyring semantics): index 0 is the PRIMARY key
        # (seals outgoing datagrams); every installed key is tried for
        # unsealing, so a rolling `install -> use -> remove` rotation
        # never partitions the cluster. Empty = plaintext gossip.
        self._keys: List[bytes] = []
        self._aeads: List = []
        # broadcast op ids (dedupe): bounded FIFO — evicting oldest-first
        # keeps recently-seen rumors deduped, where a wholesale clear
        # would let a still-circulating old 'use' op re-apply and flip
        # the primary sealing key back after a rotation completed
        self._keyring_seen: "OrderedDict[str, None]" = OrderedDict()
        # lamport clock over keyring ops: ORDER-SENSITIVE rumors ('use',
        # 'remove') older than the newest applied op are dropped even
        # after their id ages out of the FIFO. 'install' is exempt from
        # the global clock (it is idempotent and commutative, and a
        # delayed install rumor must still apply after unrelated newer
        # ops — dropping it would silently partition the node once the
        # old key is removed); installs are guarded per KEY instead, so
        # an install can never resurrect a key a newer remove deleted.
        self._keyring_clock = 0
        self._key_clocks: Dict[bytes, int] = {}
        if config.encrypt_key:
            key = _normalize_gossip_key(config.encrypt_key, self.logger)
            self._install_key_locked(key)

        self._lock = threading.RLock()
        self.incarnation = 1
        self._local = Member(
            name=config.name,
            host=self.addr[0],
            port=self.addr[1],
            tags=dict(tags or {}),
            incarnation=self.incarnation,
        )
        self.members: Dict[str, Member] = {config.name: self._local}
        # broadcast queue: (remaining_transmits, wire_msg)
        self._broadcasts: List[List] = []
        self._seq = 0
        self._acks: Dict[int, threading.Event] = {}
        self._probe_ring: List[str] = []
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []

        # event hooks (serf EventMemberJoin/Leave/Failed/Update equivalents)
        self.on_join: Optional[Callable[[Member], None]] = None
        self.on_leave: Optional[Callable[[Member], None]] = None
        self.on_fail: Optional[Callable[[Member], None]] = None
        self.on_update: Optional[Callable[[Member], None]] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Memberlist":
        for target, name in (
            (self._listen_loop, "gossip-listen"),
            (self._probe_loop, "gossip-probe"),
            (self._push_pull_loop, "gossip-pushpull"),
        ):
            t = threading.Thread(target=target, name=f"{name}-{self.config.name}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass

    def leave(self) -> None:
        """Graceful exit: broadcast the left intent, then stop."""
        with self._lock:
            self.incarnation += 1
            self._local.incarnation = self.incarnation
            self._local.status = STATUS_LEFT
            msg = {"t": "leave", "name": self.config.name, "inc": self.incarnation}
            self._queue_broadcast(msg)
        # push the rumor out directly to a few peers; the queue alone may
        # never flush since we stop probing immediately after
        for m in self._gossip_targets(3):
            self._send(m.addr, self._with_gossip({"t": "compound"}))
        self.shutdown()

    # -- public API ------------------------------------------------------

    def join(self, seeds: List[Tuple[str, int]]) -> int:
        """Push-pull sync with each seed; returns how many responded."""
        ok = 0
        for addr in seeds:
            if tuple(addr) == self.addr:
                continue
            if self._push_pull(tuple(addr)):
                ok += 1
        return ok

    def force_leave(self, name: str) -> bool:
        """Operator eviction of a (typically failed) member: inject a
        leave rumor at its current incarnation and gossip it (serf
        RemoveFailedNode). A LIVE target will refute with a higher
        incarnation — exactly serf's semantics. Returns False for an
        unknown member."""
        with self._lock:
            cur = self.members.get(name)
            if cur is None or name == self.config.name:
                return False
            inc = cur.incarnation
        self._on_dead_msg(name, inc, STATUS_LEFT)
        return True

    def set_tags(self, tags: Dict[str, str]) -> None:
        """Re-tag and re-broadcast ourselves (serf SetTags)."""
        with self._lock:
            self.incarnation += 1
            self._local.incarnation = self.incarnation
            self._local.tags = dict(tags)
            self._queue_broadcast({"t": "alive", "member": self._local.to_wire()})

    def alive_members(self) -> List[Member]:
        with self._lock:
            return [m for m in self.members.values() if m.status == STATUS_ALIVE]

    def all_members(self) -> List[Member]:
        with self._lock:
            return list(self.members.values())

    def local_member(self) -> Member:
        with self._lock:
            return self._local

    def num_alive(self) -> int:
        return len(self.alive_members())

    # -- keyring (serf agent keyring: install / use / remove / list) -----

    def _install_key_locked(self, key: bytes) -> None:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        if key not in self._keys:
            self._keys.append(key)
            self._aeads.append(AESGCM(key))

    def _require_encryption(self) -> None:
        if not self._keys:
            raise ValueError("keyring operations require gossip encryption")

    def keyring_list(self) -> List[str]:
        import base64 as b64_mod

        with self._lock:
            return [b64_mod.b64encode(k).decode() for k in self._keys]

    def keyring_install(self, key: str) -> None:
        """Add a key to the ring (starts UNSEALING with it; the primary
        still seals)."""
        self._require_encryption()
        kb = _normalize_gossip_key(key, self.logger)
        with self._lock:
            self._install_key_locked(kb)

    def keyring_broadcast(self, op: str, key: str) -> None:
        """Apply a keyring op locally AND propagate it to the cluster
        (serf's keyring ops are cluster-wide queries): the op rides a
        sealed gossip message — only holders of a current ring key can
        rotate — and is also pushed directly to every alive member for
        promptness. Apply order matters for `use` (the sender must seal
        with the NEW key only after peers can unseal it), so operators
        still follow install-everywhere -> use -> remove-everywhere; this
        broadcast makes each step one call instead of N."""
        if op == "list":
            return
        import base64 as b64_mod
        import uuid as uuid_mod

        # seal the op with the CURRENT primary before applying `use`
        # locally, so peers that still hold only the old key can unseal
        mid = uuid_mod.uuid4().hex
        kb = _normalize_gossip_key(key, self.logger)
        with self._lock:
            self._keyring_clock += 1
            clock = self._keyring_clock
            self._key_clocks[kb] = max(self._key_clocks.get(kb, 0), clock)
            # our own rumor echoes back via peer rebroadcast: mark it
            # seen so it is not re-applied against ourselves
            self._keyring_seen[mid] = None
            while len(self._keyring_seen) > 256:
                self._keyring_seen.popitem(last=False)
        msg = {
            "t": "keyring", "op": op,
            "key": b64_mod.b64encode(kb).decode(),
            "id": mid,
            "c": clock,
        }
        targets = [m for m in self.alive_members() if m.name != self.config.name]
        for m in targets:
            self._send(m.addr, msg)
        self._queue_broadcast(msg)
        getattr(self, f"keyring_{op}")(key)

    def _on_keyring_msg(self, msg: dict) -> None:
        mid = msg.get("id", "")
        clock = msg.get("c")
        op = msg.get("op", "")
        if op not in ("install", "use", "remove"):
            return
        try:
            kb = _normalize_gossip_key(msg.get("key", ""), self.logger)
        except ValueError:
            return
        with self._lock:
            if mid in self._keyring_seen:
                return
            if clock is not None:
                # Lamport guards: a still-circulating rumor of an OLDER
                # ORDER-SENSITIVE op ('use'/'remove' — e.g. the previous
                # 'use' during a rotation) must never re-apply after
                # newer ops were seen; the bounded id-FIFO alone forgets
                # ids under rumor pressure. 'install' is order-free and
                # only guarded against resurrecting a key that a newer
                # remove deleted (per-key clock). Ties apply: concurrent
                # ops from distinct origins share a clock value and each
                # must land at least once.
                if op in ("use", "remove") and clock < self._keyring_clock:
                    return
                if op == "install" and clock < self._key_clocks.get(kb, 0):
                    return
        try:
            getattr(self, f"keyring_{op}")(msg.get("key", ""))
        except ValueError as e:
            # Apply failed (e.g. 'use' raced ahead of its 'install' in
            # rumor order): do NOT advance the clocks or mark the id
            # seen — the prerequisite rumor must still apply when it
            # arrives, and a retransmit of THIS rumor must retry.
            self.logger.warning("gossiped keyring %s failed: %s", op, e)
            return
        with self._lock:
            if clock is not None:
                self._keyring_clock = max(self._keyring_clock, clock)
                self._key_clocks[kb] = max(self._key_clocks.get(kb, 0), clock)
            self._keyring_seen[mid] = None
            while len(self._keyring_seen) > 256:
                self._keyring_seen.popitem(last=False)
        self._queue_broadcast(msg)  # keep the rumor moving

    def keyring_use(self, key: str) -> None:
        """Make an installed key the primary (sealing) key."""
        self._require_encryption()
        kb = _normalize_gossip_key(key, self.logger)
        with self._lock:
            if kb not in self._keys:
                raise ValueError("key is not installed in the keyring")
            i = self._keys.index(kb)
            self._keys.insert(0, self._keys.pop(i))
            self._aeads.insert(0, self._aeads.pop(i))

    def keyring_remove(self, key: str) -> None:
        self._require_encryption()
        kb = _normalize_gossip_key(key, self.logger)
        with self._lock:
            if kb not in self._keys:
                raise ValueError("key is not installed in the keyring")
            i = self._keys.index(kb)
            if i == 0:
                raise ValueError("cannot remove the primary key; use another first")
            self._keys.pop(i)
            self._aeads.pop(i)

    # -- wire helpers ----------------------------------------------------

    def _seal(self, data: bytes) -> bytes:
        """AES-GCM with a fresh 12-byte nonce per datagram (the serf
        encrypted-gossip wire: [version byte][nonce][ciphertext+tag]);
        the PRIMARY keyring key seals."""
        if not self._aeads:
            return data
        import os as os_mod

        nonce = os_mod.urandom(12)
        return b"\x01" + nonce + self._aeads[0].encrypt(nonce, data, b"")

    def _unseal(self, data: bytes) -> Optional[bytes]:
        if not self._aeads:
            return data
        if len(data) < 13 or data[0:1] != b"\x01":
            return None  # plaintext or foreign traffic: drop
        for aead in list(self._aeads):
            try:
                return aead.decrypt(data[1:13], data[13:], b"")
            except Exception:  # noqa: BLE001 — try the next ring key
                continue
        return None  # no ring key fits / tampered

    def _send(self, addr: Tuple[str, int], msg: dict) -> None:
        try:
            data = self._seal(msgpack.packb(msg, use_bin_type=True))
            if len(data) > MAX_DATAGRAM:
                self.logger.warning("dropping oversized gossip msg (%d bytes)", len(data))
                return
            self._sock.sendto(data, addr)
        except OSError:
            pass

    def _queue_broadcast(self, msg: dict) -> None:
        n = max(1, self.config.retransmit_mult * max(1, len(self.members)).bit_length())
        with self._lock:
            self._broadcasts.append([n, msg])

    def _with_gossip(self, msg: dict) -> dict:
        """Piggyback queued broadcasts onto an outgoing message."""
        with self._lock:
            gossip = []
            keep = []
            for entry in self._broadcasts:
                gossip.append(entry[1])
                entry[0] -= 1
                if entry[0] > 0:
                    keep.append(entry)
            self._broadcasts = keep
        if gossip:
            msg = dict(msg)
            msg["g"] = gossip
        return msg

    def _gossip_targets(self, k: int) -> List[Member]:
        with self._lock:
            others = [
                m for m in self.members.values()
                if m.name != self.config.name and m.status in (STATUS_ALIVE, STATUS_SUSPECT)
            ]
        random.shuffle(others)
        return others[:k]

    # -- listener --------------------------------------------------------

    def _listen_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                data, src = self._sock.recvfrom(65536)
            except OSError:
                return
            data = self._unseal(data)
            if data is None:
                self.logger.debug("dropping unauthenticated gossip from %s", src)
                continue
            try:
                msg = msgpack.unpackb(data, raw=False)
                self._handle(msg, src)
            except Exception:  # noqa: BLE001 — a bad datagram must not kill the loop
                self.logger.exception("bad gossip datagram from %s", src)

    def _handle(self, msg: dict, src: Tuple[str, int]) -> None:
        for rumor in msg.get("g", ()):
            self._handle(rumor, src)
        t = msg.get("t")
        if t == "ping":
            self._send(src, self._with_gossip({"t": "ack", "seq": msg["seq"]}))
        elif t == "ack":
            ev = self._acks.get(msg.get("seq"))
            if ev is not None:
                ev.set()
        elif t == "ping-req":
            # probe the target on behalf of the requester and relay the ack
            target = tuple(msg["target"])
            seq = msg["seq"]

            def relay():
                if self._ping(target):
                    self._send(src, {"t": "ack", "seq": seq})

            threading.Thread(target=relay, daemon=True).start()
        elif t == "alive":
            self._on_alive_msg(msg["member"])
        elif t == "suspect":
            self._on_suspect_msg(msg["name"], msg["inc"])
        elif t == "dead":
            self._on_dead_msg(msg["name"], msg["inc"], STATUS_DEAD)
        elif t == "leave":
            self._on_dead_msg(msg["name"], msg["inc"], STATUS_LEFT)
        elif t == "keyring":
            self._on_keyring_msg(msg)
        elif t == "push-pull":
            self._merge_remote_state(msg.get("members", []))
            self._merge_keyring_clock(msg.get("kc"))
            with self._lock:
                kc = self._keyring_clock
            self._send(src, {
                "t": "push-pull-ack",
                "seq": msg.get("seq"),
                "members": [m.to_wire() for m in self.all_members()],
                "kc": kc,
            })
        elif t == "push-pull-ack":
            self._merge_remote_state(msg.get("members", []))
            self._merge_keyring_clock(msg.get("kc"))
            ev = self._acks.get(msg.get("seq"))
            if ev is not None:
                ev.set()
        elif t == "compound":
            pass  # pure gossip carrier

    # -- state merging ---------------------------------------------------

    def _on_alive_msg(self, wire: dict) -> None:
        name = wire["name"]
        inc = wire["inc"]
        with self._lock:
            if name == self.config.name:
                # someone has stale info about us (wrong status, or a stale
                # address from before a restart); refute with higher inc
                if inc >= self.incarnation and (
                    wire.get("status") != STATUS_ALIVE
                    or (wire["host"], wire["port"]) != (self._local.host, self._local.port)
                ):
                    self._refute(inc)
                return
            cur = self.members.get(name)
            if cur is None:
                m = Member(
                    name=name, host=wire["host"], port=wire["port"],
                    tags=dict(wire.get("tags") or {}), incarnation=inc,
                )
                self.members[name] = m
                self._probe_ring.append(name)
                self._queue_broadcast({"t": "alive", "member": m.to_wire()})
                hook, arg = self.on_join, m
            elif inc > cur.incarnation or (
                inc == cur.incarnation and cur.status != STATUS_ALIVE
            ):
                was_dead = cur.status in (STATUS_DEAD, STATUS_LEFT, STATUS_SUSPECT)
                tags_changed = dict(wire.get("tags") or {}) != cur.tags
                if inc == cur.incarnation and cur.status == STATUS_DEAD:
                    # an equal-inc alive can't beat a dead rumor (SWIM rule);
                    # the member itself will refute with a higher inc
                    return
                cur.incarnation = inc
                cur.host, cur.port = wire["host"], wire["port"]
                cur.tags = dict(wire.get("tags") or {})
                cur.status = STATUS_ALIVE
                cur.status_change = time.monotonic()
                self._queue_broadcast({"t": "alive", "member": cur.to_wire()})
                hook = self.on_join if was_dead else (self.on_update if tags_changed else None)
                arg = cur
            else:
                return
        if hook is not None:
            try:
                hook(arg)
            except Exception:  # noqa: BLE001
                self.logger.exception("membership hook failed")

    def _on_suspect_msg(self, name: str, inc: int) -> None:
        with self._lock:
            if name == self.config.name:
                if inc >= self.incarnation:
                    self._refute(inc)
                return
            cur = self.members.get(name)
            if cur is None or inc < cur.incarnation or cur.status != STATUS_ALIVE:
                return
            cur.status = STATUS_SUSPECT
            cur.status_change = time.monotonic()
            self._queue_broadcast({"t": "suspect", "name": name, "inc": inc})

    def _on_dead_msg(self, name: str, inc: int, status: str) -> None:
        with self._lock:
            if name == self.config.name:
                # refute dead AND left rumors: a restarted instance must be
                # able to rejoin even after its predecessor left gracefully
                if inc >= self.incarnation:
                    self._refute(inc)
                return
            cur = self.members.get(name)
            if cur is None or inc < cur.incarnation:
                return
            if cur.status == STATUS_LEFT:
                return
            if cur.status == STATUS_DEAD and status != STATUS_LEFT:
                # dead -> LEFT is allowed: force-leave evicts failed
                # members (serf RemoveFailedNode); dead -> dead is noise
                return
            cur.status = status
            cur.incarnation = inc
            cur.status_change = time.monotonic()
            self._queue_broadcast(
                {"t": "dead" if status == STATUS_DEAD else "leave", "name": name, "inc": inc}
            )
            hook = self.on_leave if status == STATUS_LEFT else self.on_fail
        if hook is not None:
            try:
                hook(cur)
            except Exception:  # noqa: BLE001
                self.logger.exception("membership hook failed")

    def _refute(self, rumor_inc: int = 0) -> None:
        """Rumors of our demise: outbid the rumor's incarnation and
        re-broadcast alive. Caller holds the lock. Jumping past rumor_inc
        matters after a restart, when our own counter reset to 1 but the
        cluster remembers a higher one."""
        self.incarnation = max(self.incarnation, rumor_inc) + 1
        self._local.incarnation = self.incarnation
        self._local.status = STATUS_ALIVE
        self._queue_broadcast({"t": "alive", "member": self._local.to_wire()})

    def _merge_remote_state(self, wires: List[dict]) -> None:
        for wire in wires:
            status = wire.get("status", STATUS_ALIVE)
            if status == STATUS_ALIVE:
                self._on_alive_msg(wire)
            elif status == STATUS_SUSPECT:
                self._on_suspect_msg(wire["name"], wire["inc"])
            else:
                self._on_dead_msg(wire["name"], wire["inc"], status)

    # -- probing ---------------------------------------------------------

    def _next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def _ping(self, addr: Tuple[str, int], timeout: Optional[float] = None) -> bool:
        seq = self._next_seq()
        ev = threading.Event()
        self._acks[seq] = ev
        try:
            self._send(addr, self._with_gossip({"t": "ping", "seq": seq}))
            return ev.wait(timeout or self.config.probe_timeout)
        finally:
            self._acks.pop(seq, None)

    def _merge_keyring_clock(self, kc) -> None:
        """Adopt the larger keyring lamport clock from push-pull state:
        a restarted node (clock reset to 0) would otherwise broadcast
        keyring ops with a clock every converged peer silently drops."""
        if not isinstance(kc, int):
            return
        with self._lock:
            self._keyring_clock = max(self._keyring_clock, kc)

    def _push_pull(self, addr: Tuple[str, int]) -> bool:
        seq = self._next_seq()
        ev = threading.Event()
        self._acks[seq] = ev
        try:
            with self._lock:
                kc = self._keyring_clock
            self._send(addr, {
                "t": "push-pull",
                "seq": seq,
                "members": [m.to_wire() for m in self.all_members()],
                "kc": kc,
            })
            return ev.wait(self.config.probe_timeout * 4)
        finally:
            self._acks.pop(seq, None)

    def _probe_loop(self) -> None:
        while not self._shutdown.wait(self.config.probe_interval):
            target = self._next_probe_target()
            if target is not None:
                self._probe(target)
            self._expire_suspects()
            self._reap_dead()

    def _next_probe_target(self) -> Optional[Member]:
        with self._lock:
            if not self._probe_ring:
                self._probe_ring = [
                    n for n, m in self.members.items()
                    if n != self.config.name and m.status in (STATUS_ALIVE, STATUS_SUSPECT)
                ]
                random.shuffle(self._probe_ring)
            while self._probe_ring:
                name = self._probe_ring.pop()
                m = self.members.get(name)
                if m is not None and m.status in (STATUS_ALIVE, STATUS_SUSPECT):
                    return m
        return None

    def _probe(self, member: Member) -> None:
        if self._ping(member.addr):
            return
        # indirect probes through k other members (SWIM ping-req)
        seq = self._next_seq()
        ev = threading.Event()
        self._acks[seq] = ev
        try:
            relays = [m for m in self._gossip_targets(self.config.indirect_checks)
                      if m.name != member.name]
            for relay in relays:
                self._send(relay.addr, {
                    "t": "ping-req", "seq": seq, "target": list(member.addr),
                })
            if relays and ev.wait(self.config.probe_timeout * 3):
                return
        finally:
            self._acks.pop(seq, None)
        self._on_suspect_msg(member.name, member.incarnation)

    def _expire_suspects(self) -> None:
        now = time.monotonic()
        expired = []
        with self._lock:
            for m in self.members.values():
                if m.status == STATUS_SUSPECT and (
                    now - m.status_change > self.config.suspicion_timeout
                ):
                    expired.append((m.name, m.incarnation))
        for name, inc in expired:
            self._on_dead_msg(name, inc, STATUS_DEAD)

    def _reap_dead(self) -> None:
        now = time.monotonic()
        with self._lock:
            for name in list(self.members):
                m = self.members[name]
                if m.status in (STATUS_DEAD, STATUS_LEFT) and (
                    now - m.status_change > self.config.dead_reclaim_time
                ):
                    del self.members[name]

    def _push_pull_loop(self) -> None:
        while not self._shutdown.wait(self.config.push_pull_interval):
            targets = self._gossip_targets(1)
            if targets:
                self._push_pull(targets[0].addr)
