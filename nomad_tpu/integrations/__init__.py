"""External system integrations: Vault (secrets) and Consul (service
registry), talked to over their HTTP APIs with in-tree mock servers for
tests (reference nomad/vault.go, command/agent/consul/)."""
