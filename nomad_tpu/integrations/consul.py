"""Consul integration: task service/check registration + agent
self-registration.

Fills the role of reference ``command/agent/consul/`` (ServiceClient):
tasks' ``service`` stanzas register into Consul's agent API when the task
starts and deregister when it stops, with Nomad-style service IDs
(``_nomad-task-<alloc>-<task>-<service>``); server/client agents
self-register as the ``nomad``/``nomad-client`` services. Transport is
Consul's HTTP agent API; ``MockConsulServer`` stands in for tests.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Optional

logger = logging.getLogger("nomad_tpu.consul")


@dataclass
class ConsulConfig:
    address: str = ""  # e.g. http://127.0.0.1:8500
    token: str = ""
    auto_advertise: bool = True  # self-register the agent


class ConsulError(Exception):
    pass


def task_service_id(alloc_id: str, task: str, service: str) -> str:
    """command/agent/consul/client.go makeTaskServiceID shape."""
    return f"_nomad-task-{alloc_id}-{task}-{service}"


class ConsulClient:
    def __init__(self, config: ConsulConfig) -> None:
        self.config = config

    @property
    def enabled(self) -> bool:
        return bool(self.config.address)

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              raw_body: Optional[str] = None):
        data = None
        if raw_body is not None:
            data = raw_body.encode()
        elif body is not None:
            data = json.dumps(body).encode()
        req = urllib.request.Request(
            self.config.address + path,
            method=method,
            data=data,
            headers={"X-Consul-Token": self.config.token} if self.config.token else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else None
        except urllib.error.HTTPError as e:
            raise ConsulError(f"consul {path}: {e.code} {e.read().decode(errors='replace')}")
        except OSError as e:
            raise ConsulError(f"consul unreachable at {self.config.address}: {e}")

    # -- agent service API ----------------------------------------------

    def register_service(
        self,
        service_id: str,
        name: str,
        address: str = "",
        port: int = 0,
        tags: Optional[List[str]] = None,
        checks: Optional[List[dict]] = None,
        kind: str = "",
        proxy: Optional[dict] = None,
    ) -> None:
        body = {
            "ID": service_id,
            "Name": name,
            "Tags": list(tags or []),
            "Address": address,
            "Port": port,
        }
        if checks:
            body["Checks"] = checks
        if kind:
            body["Kind"] = kind  # "connect-proxy" for Connect sidecars
        if proxy:
            body["Proxy"] = proxy
        self._call("PUT", "/v1/agent/service/register", body)

    def deregister_service(self, service_id: str) -> None:
        self._call("PUT", f"/v1/agent/service/deregister/{service_id}")

    # -- KV store (the template hook's {{ key }} source) ----------------

    def kv_get(self, key: str) -> Optional[str]:
        """Value at ``key`` or None (Consul /v1/kv API, base64 values)."""
        import base64

        try:
            entries = self._call("GET", f"/v1/kv/{key.lstrip('/')}")
        except ConsulError:
            return None
        if not entries:
            return None
        raw = entries[0].get("Value") or ""
        return base64.b64decode(raw).decode() if raw else ""

    def kv_put(self, key: str, value: str) -> None:
        self._call("PUT", f"/v1/kv/{key.lstrip('/')}", raw_body=value)

    def services(self) -> Dict[str, dict]:
        return self._call("GET", "/v1/agent/services") or {}

    # -- TTL checks (the script-check slot, command/agent/consul/script.go:
    # Nomad registers script checks as TTL checks and heartbeats them
    # itself after running the command through the driver exec API) -----

    def register_ttl_check(self, check_id: str, name: str, service_id: str,
                           ttl: str) -> None:
        self._call("PUT", "/v1/agent/check/register", {
            "ID": check_id, "Name": name, "ServiceID": service_id, "TTL": ttl,
        })

    def update_ttl(self, check_id: str, status: str, output: str = "") -> None:
        self._call("PUT", f"/v1/agent/check/update/{check_id}", {
            "Status": status, "Output": output,
        })

    def deregister_check(self, check_id: str) -> None:
        self._call("PUT", f"/v1/agent/check/deregister/{check_id}")

    # -- task lifecycle hooks (consul/client.go RegisterWorkload) --------

    @staticmethod
    def is_script_check(c: dict) -> bool:
        return c.get("type") == "script" or bool(c.get("command"))

    @staticmethod
    def _check_body(svc_name: str, c: dict) -> Optional[dict]:
        """Consul rejects TTL+Interval together; shape per check kind.
        Script checks return None — they register separately as TTL
        checks the client heartbeats (script.go semantics)."""
        if ConsulClient.is_script_check(c):
            return None
        body = {"Name": c.get("name", f"service: {svc_name} check")}
        if c.get("ttl"):
            body["TTL"] = c["ttl"]
        elif c.get("http"):
            body["HTTP"] = c["http"]
            body["Interval"] = c.get("interval", "10s")
        elif c.get("tcp"):
            body["TCP"] = c["tcp"]
            body["Interval"] = c.get("interval", "10s")
        return body

    @staticmethod
    def _resolve_port(alloc, task, port_label: str) -> int:
        """Map a service's port label to the alloc's assigned port value
        (consul/client.go serviceRegs → GetTaskEnv port lookup)."""
        if not port_label:
            return 0
        res = alloc.allocated_resources
        task_res = res.tasks.get(task.name) if res is not None else None
        networks = list(task_res.networks) if task_res is not None else []
        for net in networks:
            for port in list(net.dynamic_ports) + list(net.reserved_ports):
                if port.label == port_label:
                    return port.value
        return 0

    def register_task_services(self, alloc, task, address: str = "") -> List[str]:
        """Register every service stanza on the task; returns the ids for
        deregistration at task stop."""
        ids = []
        for svc in task.services or []:
            sid = task_service_id(alloc.id, task.name, svc.name)
            checks = [
                b for b in (
                    self._check_body(svc.name, c)
                    for c in getattr(svc, "checks", []) or []
                ) if b is not None
            ]
            try:
                self.register_service(
                    sid, svc.name, address=address,
                    port=self._resolve_port(alloc, task, svc.port_label),
                    tags=svc.tags, checks=checks or None,
                )
                ids.append(sid)
            except ConsulError as e:
                logger.warning("registering %s failed: %s", sid, e)
        return ids

    def register_group_services(self, alloc, tg, address: str = "") -> List[str]:
        """Register GROUP-level services; a service with a Connect sidecar
        also registers its proxy service (Kind=connect-proxy, the
        reference's groupServiceHook + sidecar registration)."""
        from ..structs.structs import CONNECT_PROXY_PREFIX

        def group_port(label: str) -> int:
            ar = alloc.allocated_resources
            if ar is None or not label:
                return 0
            for net in ar.shared.networks:
                for p in list(net.dynamic_ports) + list(net.reserved_ports):
                    if p.label == label:
                        return p.value
            # group asks may have landed on a task's offer
            for tr in ar.tasks.values():
                for net in tr.networks:
                    for p in list(net.dynamic_ports) + list(net.reserved_ports):
                        if p.label == label:
                            return p.value
            return 0

        ids: List[str] = []
        for svc in getattr(tg, "services", []) or []:
            sid = f"_nomad-group-{alloc.id}-{svc.name}"
            for c in getattr(svc, "checks", []) or []:
                if self.is_script_check(c):
                    # group-level script checks need a task to exec in
                    # (reference check.task field) — not wired here yet
                    logger.warning(
                        "group service %s: script checks on group services "
                        "are not supported; check %r skipped",
                        svc.name, c.get("name", ""),
                    )
            checks = [
                b for b in (
                    self._check_body(svc.name, c)
                    for c in getattr(svc, "checks", []) or []
                ) if b is not None
            ]
            try:
                self.register_service(
                    sid, svc.name, address=address,
                    port=group_port(svc.port_label),
                    tags=svc.tags, checks=checks or None,
                )
                ids.append(sid)
            except ConsulError as e:
                logger.warning("registering %s failed: %s", sid, e)
                continue
            if getattr(svc, "has_sidecar", lambda: False)():
                proxy_label = f"{CONNECT_PROXY_PREFIX}-{svc.name}"
                proxy_id = f"{sid}-sidecar-proxy"
                sidecar = (svc.connect or {}).get("sidecar_service") or {}
                proxy_cfg = dict(sidecar.get("proxy") or {})
                proxy_cfg.setdefault("DestinationServiceName", svc.name)
                proxy_cfg.setdefault("DestinationServiceID", sid)
                try:
                    self.register_service(
                        proxy_id, f"{svc.name}-sidecar-proxy",
                        address=address,
                        port=group_port(proxy_label),
                        tags=svc.tags,
                        kind="connect-proxy",
                        proxy=proxy_cfg,
                    )
                    ids.append(proxy_id)
                except ConsulError as e:
                    logger.warning("registering %s failed: %s", proxy_id, e)
        return ids

    def deregister_ids(self, ids: List[str]) -> None:
        for sid in ids:
            try:
                self.deregister_service(sid)
            except ConsulError as e:
                logger.warning("deregistering %s failed: %s", sid, e)


# ---------------------------------------------------------------------------
# In-tree mock Consul
# ---------------------------------------------------------------------------


class MockConsulServer:
    """The slice of Consul's agent API the integration uses."""

    def __init__(self) -> None:
        import http.server
        import socketserver

        self.services: Dict[str, dict] = {}
        self.checks: Dict[str, dict] = {}
        self.kv: Dict[str, str] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, obj=None) -> None:
                payload = json.dumps(obj).encode() if obj is not None else b""
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_PUT(self):
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length)
                if self.path.startswith("/v1/kv/"):
                    key = self.path[len("/v1/kv/"):]
                    with outer._lock:
                        outer.kv[key] = raw.decode()
                    return self._reply(200, True)
                body = json.loads(raw or b"{}")
                if self.path == "/v1/agent/check/register":
                    with outer._lock:
                        outer.checks[body["ID"]] = {
                            "Name": body.get("Name", ""),
                            "ServiceID": body.get("ServiceID", ""),
                            "TTL": body.get("TTL", ""),
                            "Status": "critical",
                            "Output": "",
                        }
                    return self._reply(200)
                if self.path.startswith("/v1/agent/check/update/"):
                    cid = self.path.rsplit("/", 1)[1]
                    with outer._lock:
                        chk = outer.checks.get(cid)
                        if chk is None:
                            return self._reply(404, {"error": "unknown check"})
                        chk["Status"] = body.get("Status", "")
                        chk["Output"] = body.get("Output", "")
                    return self._reply(200)
                if self.path.startswith("/v1/agent/check/deregister/"):
                    cid = self.path.rsplit("/", 1)[1]
                    with outer._lock:
                        outer.checks.pop(cid, None)
                    return self._reply(200)
                if self.path == "/v1/agent/service/register":
                    with outer._lock:
                        outer.services[body["ID"]] = body
                    return self._reply(200)
                if self.path.startswith("/v1/agent/service/deregister/"):
                    sid = self.path.rsplit("/", 1)[1]
                    with outer._lock:
                        outer.services.pop(sid, None)
                    return self._reply(200)
                return self._reply(404, {"error": "no handler"})

            def do_GET(self):
                if self.path == "/v1/agent/services":
                    with outer._lock:
                        return self._reply(200, dict(outer.services))
                if self.path.startswith("/v1/kv/"):
                    import base64

                    key = self.path[len("/v1/kv/"):]
                    with outer._lock:
                        val = outer.kv.get(key)
                    if val is None:
                        return self._reply(404, [])
                    return self._reply(200, [{
                        "Key": key,
                        "Value": base64.b64encode(val.encode()).decode(),
                    }])
                return self._reply(404, {"error": "no handler"})

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.address = "http://{}:{}".format(*self._srv.server_address)
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    def start(self) -> "MockConsulServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
