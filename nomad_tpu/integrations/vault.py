"""Vault integration: per-task token derivation / renewal / revocation.

Fills the role of reference ``nomad/vault.go`` (1,349 LoC vaultClient):
the leader derives child tokens for tasks that carry a ``vault`` stanza
(CreateToken with the task's policies, vault.go DeriveToken), tracks the
token accessors so allocations that die get their tokens revoked
(RevokeTokens / MarkForRevocation), and renews its own server token.
Transport is Vault's plain HTTP API; ``MockVaultServer`` is the in-tree
stand-in (the reference tests use a real dev-mode Vault binary —
nomad/vault_testing.go; zero-egress environments get the mock).
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger("nomad_tpu.vault")


@dataclass
class VaultConfig:
    enabled: bool = False
    address: str = ""  # e.g. http://127.0.0.1:8200
    token: str = ""  # server's own (root/periodic) token
    task_token_ttl: str = "72h"
    allow_unauthenticated: bool = True  # jobs may use vault without a token


class VaultError(Exception):
    pass


class VaultClient:
    """Server-side Vault API client (vault.go vaultClient)."""

    def __init__(self, config: VaultConfig) -> None:
        self.config = config

    @property
    def enabled(self) -> bool:
        return self.config.enabled and bool(self.config.address)

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              token: Optional[str] = None) -> dict:
        req = urllib.request.Request(
            self.config.address + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"X-Vault-Token": token or self.config.token},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            raise VaultError(f"vault {path}: {e.code} {e.read().decode(errors='replace')}")
        except OSError as e:
            raise VaultError(f"vault unreachable at {self.config.address}: {e}")

    # -- token lifecycle -------------------------------------------------

    def derive_token(self, policies: List[str]) -> Dict[str, str]:
        """Child token restricted to the task's policies (vault.go
        DeriveToken → auth/token/create). Returns {token, accessor}."""
        out = self._call("POST", "/v1/auth/token/create", {
            "policies": policies,
            "ttl": self.config.task_token_ttl,
            "display_name": "nomad-task",
            "renewable": True,
        })
        auth = out.get("auth") or {}
        if not auth.get("client_token"):
            raise VaultError("vault returned no client token")
        return {"token": auth["client_token"], "accessor": auth.get("accessor", "")}

    def renew(self, token: str) -> None:
        self._call("POST", "/v1/auth/token/renew", {"token": token})

    def revoke_accessor(self, accessor: str) -> None:
        self._call("POST", "/v1/auth/token/revoke-accessor", {"accessor": accessor})

    def revoke_accessors(self, accessors: List[str]) -> List[str]:
        """Best-effort batch revoke; returns the accessors that failed
        (leader retries those later, vault.go RevokeTokens)."""
        failed = []
        for acc in accessors:
            try:
                self.revoke_accessor(acc)
            except VaultError as e:
                logger.warning("revoking accessor %s failed: %s", acc[:12], e)
                failed.append(acc)
        return failed

    def read_secret(self, path: str, token: Optional[str] = None) -> dict:
        """KV-v1 style secret read (the template hook's {{ secret }}
        source): GET /v1/<path> → the response's ``data`` map."""
        out = self._call("GET", "/v1/" + path.lstrip("/"), token=token)
        return out.get("data") or {}

    def lookup_self(self) -> dict:
        return self._call("GET", "/v1/auth/token/lookup-self")


# ---------------------------------------------------------------------------
# In-tree mock Vault (vault_testing.go slot)
# ---------------------------------------------------------------------------


@dataclass
class MockToken:
    token: str
    accessor: str
    policies: List[str] = field(default_factory=list)
    ttl: str = ""
    revoked: bool = False
    renewals: int = 0


class MockVaultServer:
    """Just enough of Vault's token API for the integration tests."""

    def __init__(self, root_token: str = "root") -> None:
        import http.server
        import socketserver

        self.root_token = root_token
        self.tokens: Dict[str, MockToken] = {}
        # path -> data map served at GET /v1/<path> (KV-v1 style; the
        # template hook's {{ secret }} source)
        self.secrets: Dict[str, dict] = {}
        self.by_accessor: Dict[str, MockToken] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _reply(self, code: int, obj) -> None:
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                auth = self.headers.get("X-Vault-Token", "")
                if not outer._valid(auth):
                    return self._reply(403, {"errors": ["permission denied"]})
                if self.path == "/v1/auth/token/create":
                    tok = outer._create(body)
                    return self._reply(200, {"auth": {
                        "client_token": tok.token,
                        "accessor": tok.accessor,
                        "policies": tok.policies,
                    }})
                if self.path == "/v1/auth/token/renew":
                    with outer._lock:
                        t = outer.tokens.get(body.get("token", ""))
                        if t is None or t.revoked:
                            return self._reply(400, {"errors": ["bad token"]})
                        t.renewals += 1
                    return self._reply(200, {"auth": {"client_token": t.token}})
                if self.path == "/v1/auth/token/revoke-accessor":
                    with outer._lock:
                        t = outer.by_accessor.get(body.get("accessor", ""))
                        if t is None:
                            return self._reply(400, {"errors": ["unknown accessor"]})
                        t.revoked = True
                    return self._reply(204, {})
                return self._reply(404, {"errors": ["no handler"]})

            def do_GET(self):
                auth = self.headers.get("X-Vault-Token", "")
                if self.path == "/v1/auth/token/lookup-self":
                    with outer._lock:
                        t = outer.tokens.get(auth)
                    if auth == outer.root_token:
                        return self._reply(200, {"data": {"policies": ["root"]}})
                    if t is None or t.revoked:
                        return self._reply(403, {"errors": ["permission denied"]})
                    return self._reply(200, {"data": {"policies": t.policies}})
                if self.path.startswith("/v1/secret/"):
                    if not outer._valid(auth):
                        return self._reply(403, {"errors": ["permission denied"]})
                    key = self.path[len("/v1/"):]
                    with outer._lock:
                        data = outer.secrets.get(key)
                    if data is None:
                        return self._reply(404, {"errors": ["not found"]})
                    return self._reply(200, {"data": data})
                return self._reply(404, {"errors": ["no handler"]})

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.address = "http://{}:{}".format(*self._srv.server_address)
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)

    def _valid(self, token: str) -> bool:
        if token == self.root_token:
            return True
        with self._lock:
            t = self.tokens.get(token)
        return t is not None and not t.revoked

    def _create(self, body: dict) -> MockToken:
        tok = MockToken(
            token=f"s.{uuid.uuid4().hex[:24]}",
            accessor=uuid.uuid4().hex[:24],
            policies=list(body.get("policies") or []),
            ttl=str(body.get("ttl", "")),
        )
        with self._lock:
            self.tokens[tok.token] = tok
            self.by_accessor[tok.accessor] = tok
        return tok

    def start(self) -> "MockVaultServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
