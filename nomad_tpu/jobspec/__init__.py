"""HCL1 jobspec parsing (reference jobspec/ package)."""

from .hcl import HCLError, HCLObject, parse as parse_hcl
from .parse import parse_duration_ns, parse_file, parse_job

__all__ = [
    "HCLError",
    "HCLObject",
    "parse_hcl",
    "parse_duration_ns",
    "parse_file",
    "parse_job",
]
