"""A small HCL1 reader.

The reference parses jobspecs with hashicorp/hcl (HCL1) into an AST that
``jobspec/parse.go:27 Parse`` walks.  We implement the same surface here from
scratch: a hand-written lexer + recursive-descent parser producing plain
Python structures that ``nomad_tpu/jobspec/parse.py`` maps onto structs.

Supported HCL1 surface (everything jobspecs use):

* attributes  ``key = value``
* blocks      ``key "label" "label2" { ... }`` (labels optional, repeatable)
* values: quoted strings (with Go escape sequences; ``${...}`` interpolation
  is preserved verbatim — interpolation happens later, at task-env time, as in
  the reference), heredocs (``<<EOF`` and indented ``<<-EOF``), integers
  (decimal/hex), floats, booleans, lists ``[a, b,]``, and objects
  ``{ k = v }``
* comments: ``#``, ``//`` and ``/* ... */``

The parse result models HCL1's object semantics: a *body* is an ``HCLObject``
— an ordered multi-map, because the same key may repeat (``group "a" {}``
``group "b" {}``) and order matters for merging.  A block with labels becomes
nested single-key objects, exactly like HCL1's JSON form:
``job "x" { ... }`` → ``("job", HCLObject[("x", HCLObject[...])])``.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Tuple


class HCLError(ValueError):
    def __init__(self, msg: str, line: int) -> None:
        super().__init__(f"{msg} (line {line})")
        self.line = line


class HCLObject:
    """Ordered multi-map of key → value (value: scalar, list, HCLObject)."""

    __slots__ = ("items",)

    def __init__(self, items: Optional[List[Tuple[str, Any]]] = None) -> None:
        self.items: List[Tuple[str, Any]] = items if items is not None else []

    def add(self, key: str, value: Any) -> None:
        self.items.append((key, value))

    def get_all(self, key: str) -> List[Any]:
        return [v for k, v in self.items if k == key]

    def get(self, key: str, default: Any = None) -> Any:
        """Last value wins for scalar attributes (HCL1 semantics)."""
        out = default
        for k, v in self.items:
            if k == key:
                out = v
        return out

    def keys(self) -> List[str]:
        seen: List[str] = []
        for k, _ in self.items:
            if k not in seen:
                seen.append(k)
        return seen

    def __contains__(self, key: str) -> bool:
        return any(k == key for k, _ in self.items)

    def __iter__(self) -> Iterator[Tuple[str, Any]]:
        return iter(self.items)

    def __len__(self) -> int:
        return len(self.items)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"HCLObject({self.items!r})"

    def to_plain(self) -> Any:
        """Collapse to plain dicts/lists (repeated keys -> list)."""
        out: dict = {}
        for k in self.keys():
            vals = [
                v.to_plain() if isinstance(v, HCLObject) else v
                for v in self.get_all(k)
            ]
            out[k] = vals[0] if len(vals) == 1 else vals
        return out


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_IDENT_START = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_IDENT_CHARS = _IDENT_START | set("0123456789-.")


class _Token:
    __slots__ = ("kind", "value", "line")

    def __init__(self, kind: str, value: Any, line: int) -> None:
        self.kind = kind  # IDENT STRING NUMBER LBRACE RBRACE LBRACK RBRACK EQ COMMA COLON EOF
        self.value = value
        self.line = line

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"_Token({self.kind}, {self.value!r}, line={self.line})"


_ESCAPES = {
    "n": "\n",
    "t": "\t",
    "r": "\r",
    '"': '"',
    "\\": "\\",
    "'": "'",
    "a": "\a",
    "b": "\b",
    "f": "\f",
    "v": "\v",
    "0": "\0",
}


def _lex(src: str) -> List[_Token]:
    toks: List[_Token] = []
    i, n, line = 0, len(src), 1
    while i < n:
        c = src[i]
        if c == "\n":
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#" or src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            if end < 0:
                raise HCLError("unterminated block comment", line)
            line += src.count("\n", i, end)
            i = end + 2
            continue
        if c == "{":
            toks.append(_Token("LBRACE", "{", line))
            i += 1
            continue
        if c == "}":
            toks.append(_Token("RBRACE", "}", line))
            i += 1
            continue
        if c == "[":
            toks.append(_Token("LBRACK", "[", line))
            i += 1
            continue
        if c == "]":
            toks.append(_Token("RBRACK", "]", line))
            i += 1
            continue
        if c == "=":
            toks.append(_Token("EQ", "=", line))
            i += 1
            continue
        if c == ",":
            toks.append(_Token("COMMA", ",", line))
            i += 1
            continue
        if c == ":":
            toks.append(_Token("COLON", ":", line))
            i += 1
            continue
        if src.startswith("<<", i):
            i, line, text = _lex_heredoc(src, i, line)
            toks.append(_Token("STRING", text, line))
            continue
        if c == '"':
            i, line, text = _lex_string(src, i, line)
            toks.append(_Token("STRING", text, line))
            continue
        if c.isdigit() or (c == "-" and i + 1 < n and src[i + 1].isdigit()):
            i, num = _lex_number(src, i, line)
            toks.append(_Token("NUMBER", num, line))
            continue
        if c in _IDENT_START:
            j = i
            while j < n and src[j] in _IDENT_CHARS:
                j += 1
            toks.append(_Token("IDENT", src[i:j], line))
            i = j
            continue
        raise HCLError(f"unexpected character {c!r}", line)
    toks.append(_Token("EOF", None, line))
    return toks


def _lex_string(src: str, i: int, line: int) -> Tuple[int, int, str]:
    # i points at the opening quote
    out: List[str] = []
    i += 1
    n = len(src)
    while i < n:
        c = src[i]
        if c == '"':
            return i + 1, line, "".join(out)
        if c == "\n":
            raise HCLError("newline in string", line)
        if c == "\\":
            if i + 1 >= n:
                raise HCLError("unterminated escape", line)
            e = src[i + 1]
            if e in _ESCAPES:
                out.append(_ESCAPES[e])
                i += 2
                continue
            if e == "u" and i + 5 < n:
                out.append(chr(int(src[i + 2 : i + 6], 16)))
                i += 6
                continue
            # Unknown escape: keep verbatim (lenient, like HCL1 printer round-trips)
            out.append(c + e)
            i += 2
            continue
        if c == "$" and i + 1 < n and src[i + 1] == "{":
            # Preserve interpolation expressions verbatim, including nested braces.
            depth = 0
            j = i
            while j < n:
                if src[j] == "{":
                    depth += 1
                elif src[j] == "}":
                    depth -= 1
                    if depth == 0:
                        break
                j += 1
            if j >= n:
                raise HCLError("unterminated interpolation", line)
            out.append(src[i : j + 1])
            i = j + 1
            continue
        out.append(c)
        i += 1
    raise HCLError("unterminated string", line)


def _lex_heredoc(src: str, i: int, line: int) -> Tuple[int, int, str]:
    # i points at "<<"; optionally "<<-" for indented heredoc
    j = i + 2
    indented = j < len(src) and src[j] == "-"
    if indented:
        j += 1
    k = j
    while k < len(src) and src[k] not in "\n\r":
        k += 1
    marker = src[j:k].strip()
    if not marker:
        raise HCLError("heredoc missing marker", line)
    if k < len(src) and src[k] == "\r":
        k += 1
    if k >= len(src) or src[k] != "\n":
        raise HCLError("heredoc marker must end the line", line)
    k += 1
    start_line = line
    line += 1
    lines: List[str] = []
    while True:
        if k >= len(src):
            raise HCLError("unterminated heredoc", start_line)
        end = src.find("\n", k)
        if end < 0:
            end = len(src)
        raw = src[k:end]
        if raw.strip() == marker:
            k = end + 1 if end < len(src) else end
            line += 1
            break
        lines.append(raw)
        k = end + 1 if end < len(src) else end
        line += 1
    if indented and lines:
        # Strip the smallest common leading whitespace (HCL1 <<- semantics)
        def indent_of(s: str) -> int:
            return len(s) - len(s.lstrip()) if s.strip() else 1 << 30

        pad = min((indent_of(s) for s in lines), default=0)
        if pad and pad < (1 << 30):
            lines = [s[pad:] if s.strip() else s for s in lines]
    text = "\n".join(lines)
    if text:
        text += "\n"
    return k, line, text


def _lex_number(src: str, i: int, line: int) -> Tuple[int, Any]:
    j = i
    n = len(src)
    if src[j] == "-":
        j += 1
    if src.startswith("0x", j) or src.startswith("0X", j):
        k = j + 2
        while k < n and src[k] in "0123456789abcdefABCDEF":
            k += 1
        return k, int(src[i:k], 16)
    k = j
    isfloat = False
    while k < n and (src[k].isdigit() or src[k] in ".eE+-"):
        if src[k] in ".eE":
            isfloat = True
        if src[k] in "+-" and src[k - 1] not in "eE":
            break
        k += 1
    text = src[i:k]
    try:
        return k, float(text) if isfloat else int(text)
    except ValueError:
        raise HCLError(f"bad number literal {text!r}", line)


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, toks: List[_Token]) -> None:
        self.toks = toks
        self.pos = 0

    def peek(self) -> _Token:
        return self.toks[self.pos]

    def next(self) -> _Token:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def expect(self, kind: str) -> _Token:
        t = self.next()
        if t.kind != kind:
            raise HCLError(f"expected {kind}, got {t.kind} {t.value!r}", t.line)
        return t

    def parse_body(self, top: bool = False) -> HCLObject:
        obj = HCLObject()
        while True:
            t = self.peek()
            if t.kind == "EOF":
                if not top:
                    raise HCLError("unexpected end of input, missing '}'", t.line)
                return obj
            if t.kind == "RBRACE":
                if top:
                    raise HCLError("unexpected '}'", t.line)
                self.next()
                return obj
            if t.kind == "COMMA":  # stray commas between object items are legal
                self.next()
                continue
            if t.kind not in ("IDENT", "STRING"):
                raise HCLError(f"expected key, got {t.kind} {t.value!r}", t.line)
            key = self.next().value
            labels: List[str] = []
            while self.peek().kind in ("STRING", "IDENT") and self.peek().kind != "EOF":
                labels.append(self.next().value)
            t = self.peek()
            if t.kind == "EQ":
                if labels:
                    raise HCLError("unexpected '=' after block labels", t.line)
                self.next()
                obj.add(key, self.parse_value())
            elif t.kind == "LBRACE":
                self.next()
                body = self.parse_body()
                # Nest labels: job "x" {..} -> job: { x: {..} }
                for label in reversed(labels):
                    wrapper = HCLObject()
                    wrapper.add(label, body)
                    body = wrapper
                obj.add(key, body)
            else:
                raise HCLError(
                    f"expected '=' or '{{' after {key!r}, got {t.kind}", t.line
                )

    def parse_value(self) -> Any:
        t = self.next()
        if t.kind in ("STRING", "NUMBER"):
            return t.value
        if t.kind == "IDENT":
            if t.value == "true":
                return True
            if t.value == "false":
                return False
            raise HCLError(f"unexpected identifier {t.value!r} as value", t.line)
        if t.kind == "LBRACK":
            out: List[Any] = []
            while True:
                nt = self.peek()
                if nt.kind == "RBRACK":
                    self.next()
                    return out
                out.append(self.parse_value())
                nt = self.peek()
                if nt.kind == "COMMA":
                    self.next()
                elif nt.kind != "RBRACK":
                    raise HCLError("expected ',' or ']' in list", nt.line)
        if t.kind == "LBRACE":
            return self.parse_body()
        raise HCLError(f"unexpected token {t.kind} {t.value!r}", t.line)


def parse(src: str) -> HCLObject:
    """Parse HCL1 source into an :class:`HCLObject` tree."""
    return _Parser(_lex(src)).parse_body(top=True)
