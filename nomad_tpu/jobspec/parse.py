"""Jobspec → structs mapping (reference jobspec/parse.go:27 Parse,
parse_job.go, parse_group.go, parse_task.go, parse_service.go,
parse_network.go).

The reference decodes HCL1 into ``api.Job``; here we map straight onto the
framework's canonical structs (``nomad_tpu.structs``), which the HTTP agent
already converts to/from wire JSON.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..structs.structs import (
    Affinity,
    Constraint,
    EphemeralDisk,
    Job,
    MigrateStrategy,
    NetworkResource,
    ParameterizedJobConfig,
    PeriodicConfig,
    Port,
    RequestedDevice,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Service,
    Spread,
    SpreadTarget,
    Task,
    TaskGroup,
    UpdateStrategy,
    VolumeMount,
    VolumeRequest,
)
from .hcl import HCLError, HCLObject, parse as parse_hcl

__all__ = ["parse_job", "parse_file", "parse_duration_ns", "HCLError"]


# ---------------------------------------------------------------------------
# Small decoding helpers
# ---------------------------------------------------------------------------

_DUR_UNITS = {
    "ns": 1,
    "us": 10**3,
    "µs": 10**3,
    "ms": 10**6,
    "s": 10**9,
    "m": 60 * 10**9,
    "h": 3600 * 10**9,
    "d": 24 * 3600 * 10**9,
}


def parse_duration_ns(v: Any) -> int:
    """Go ``time.ParseDuration`` semantics ("1h30m", "10s", "250ms") → ns.

    Bare numbers are treated as nanoseconds, matching mapstructure decoding of
    integers into time.Duration in the reference.
    """
    if isinstance(v, bool):
        raise HCLError(f"invalid duration {v!r}", 0)
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip()
    if not s:
        return 0
    neg = s.startswith("-")
    if neg or s.startswith("+"):
        s = s[1:]
    total = 0.0
    i, n = 0, len(s)
    matched = False
    while i < n:
        j = i
        while j < n and (s[j].isdigit() or s[j] == "."):
            j += 1
        if j == i:
            raise HCLError(f"invalid duration {v!r}", 0)
        num = float(s[i:j])
        k = j
        while k < n and not (s[k].isdigit() or s[k] == "."):
            k += 1
        unit = s[j:k]
        if unit not in _DUR_UNITS:
            raise HCLError(f"unknown duration unit {unit!r} in {v!r}", 0)
        total += num * _DUR_UNITS[unit]
        matched = True
        i = k
    if not matched:
        raise HCLError(f"invalid duration {v!r}", 0)
    return -int(total) if neg else int(total)


def _str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _int(v: Any, what: str) -> int:
    if isinstance(v, bool):
        raise HCLError(f"{what}: expected number, got bool", 0)
    if isinstance(v, (int, float)):
        return int(v)
    try:
        return int(str(v), 0)
    except ValueError:
        raise HCLError(f"{what}: expected number, got {v!r}", 0)


def _bool(v: Any, what: str) -> bool:
    if isinstance(v, bool):
        return v
    if isinstance(v, str):
        if v in ("true", "1"):
            return True
        if v in ("false", "0"):
            return False
    raise HCLError(f"{what}: expected bool, got {v!r}", 0)


def _strmap(obj: Any, what: str) -> Dict[str, str]:
    if obj is None:
        return {}
    if not isinstance(obj, HCLObject):
        raise HCLError(f"{what}: expected a block/map", 0)
    return {k: _str(v) for k, v in obj}


def _plain(v: Any) -> Any:
    if isinstance(v, HCLObject):
        out: Dict[str, Any] = {}
        for k in v.keys():
            vals = [_plain(x) for x in v.get_all(k)]
            out[k] = vals[0] if len(vals) == 1 else vals
        return out
    if isinstance(v, list):
        return [_plain(x) for x in v]
    return v


def _labelled_blocks(obj: HCLObject, key: str, what: str) -> List[tuple]:
    """Yield (label, body) for blocks like ``group "name" { ... }``."""
    out = []
    for body in obj.get_all(key):
        if not isinstance(body, HCLObject):
            raise HCLError(f"{what} must be a block", 0)
        if len(body) != 1 or not isinstance(body.items[0][1], HCLObject):
            raise HCLError(f"{what} requires exactly one label", 0)
        out.append(body.items[0])
    return out


# ---------------------------------------------------------------------------
# Constraint / affinity / spread (reference parse.go parseConstraints,
# parseAffinities, parseSpread — including the operator sugar keys)
# ---------------------------------------------------------------------------

_CONSTRAINT_SUGAR = (
    "version",
    "semver",
    "regexp",
    "set_contains",
    "set_contains_any",
    "set_contains_all",
)


def _parse_constraint_like(o: HCLObject, cls, what: str):
    ltarget = _str(o.get("attribute", ""))
    rtarget = _str(o.get("value", ""))
    operand = _str(o.get("operator", "="))
    for sugar in _CONSTRAINT_SUGAR:
        if sugar in o:
            operand = "set_contains" if sugar == "set_contains_all" else sugar
            rtarget = _str(o.get(sugar))
    if "distinct_hosts" in o:
        if not _bool(o.get("distinct_hosts"), what):
            raise HCLError("distinct_hosts should be set to true or not set at all", 0)
        operand = "distinct_hosts"
        ltarget = rtarget = ""
    if "distinct_property" in o:
        operand = "distinct_property"
        ltarget = _str(o.get("distinct_property"))
        rtarget = _str(o.get("value", ""))
    if "is_set" in o or "is_not_set" in o:
        operand = "is_set" if "is_set" in o else "is_not_set"
        rtarget = ""
    if cls is Constraint:
        return Constraint(ltarget=ltarget, rtarget=rtarget, operand=operand)
    return Affinity(
        ltarget=ltarget,
        rtarget=rtarget,
        operand=operand,
        weight=_int(o.get("weight", 50), f"{what}.weight"),
    )


def _parse_constraints(obj: HCLObject) -> List[Constraint]:
    return [
        _parse_constraint_like(o, Constraint, "constraint")
        for o in obj.get_all("constraint")
    ]


def _parse_affinities(obj: HCLObject) -> List[Affinity]:
    return [
        _parse_constraint_like(o, Affinity, "affinity") for o in obj.get_all("affinity")
    ]


def _parse_spreads(obj: HCLObject) -> List[Spread]:
    out: List[Spread] = []
    for o in obj.get_all("spread"):
        targets = [
            SpreadTarget(value=label, percent=_int(body.get("percent", 0), "percent"))
            for label, body in _labelled_blocks(o, "target", "spread target")
        ]
        out.append(
            Spread(
                attribute=_str(o.get("attribute", "")),
                weight=_int(o.get("weight", 50), "spread.weight"),
                spread_target=targets,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Policies / strategies
# ---------------------------------------------------------------------------


def _parse_update(o: HCLObject) -> UpdateStrategy:
    u = UpdateStrategy()
    if "stagger" in o:
        u.stagger_ns = parse_duration_ns(o.get("stagger"))
    if "max_parallel" in o:
        u.max_parallel = _int(o.get("max_parallel"), "update.max_parallel")
    if "health_check" in o:
        u.health_check = _str(o.get("health_check"))
    if "min_healthy_time" in o:
        u.min_healthy_time_ns = parse_duration_ns(o.get("min_healthy_time"))
    if "healthy_deadline" in o:
        u.healthy_deadline_ns = parse_duration_ns(o.get("healthy_deadline"))
    if "progress_deadline" in o:
        u.progress_deadline_ns = parse_duration_ns(o.get("progress_deadline"))
    if "auto_revert" in o:
        u.auto_revert = _bool(o.get("auto_revert"), "update.auto_revert")
    if "auto_promote" in o:
        u.auto_promote = _bool(o.get("auto_promote"), "update.auto_promote")
    if "canary" in o:
        u.canary = _int(o.get("canary"), "update.canary")
    return u


def _parse_restart(o: HCLObject) -> RestartPolicy:
    r = RestartPolicy()
    if "attempts" in o:
        r.attempts = _int(o.get("attempts"), "restart.attempts")
    if "interval" in o:
        r.interval_ns = parse_duration_ns(o.get("interval"))
    if "delay" in o:
        r.delay_ns = parse_duration_ns(o.get("delay"))
    if "mode" in o:
        r.mode = _str(o.get("mode"))
    return r


def _parse_reschedule(o: HCLObject) -> ReschedulePolicy:
    p = ReschedulePolicy()
    if "attempts" in o:
        p.attempts = _int(o.get("attempts"), "reschedule.attempts")
    if "interval" in o:
        p.interval_ns = parse_duration_ns(o.get("interval"))
    if "delay" in o:
        p.delay_ns = parse_duration_ns(o.get("delay"))
    if "delay_function" in o:
        p.delay_function = _str(o.get("delay_function"))
    if "max_delay" in o:
        p.max_delay_ns = parse_duration_ns(o.get("max_delay"))
    if "unlimited" in o:
        p.unlimited = _bool(o.get("unlimited"), "reschedule.unlimited")
    return p


def _parse_migrate(o: HCLObject) -> MigrateStrategy:
    m = MigrateStrategy()
    if "max_parallel" in o:
        m.max_parallel = _int(o.get("max_parallel"), "migrate.max_parallel")
    if "health_check" in o:
        m.health_check = _str(o.get("health_check"))
    if "min_healthy_time" in o:
        m.min_healthy_time_ns = parse_duration_ns(o.get("min_healthy_time"))
    if "healthy_deadline" in o:
        m.healthy_deadline_ns = parse_duration_ns(o.get("healthy_deadline"))
    return m


def _parse_ephemeral_disk(o: HCLObject) -> EphemeralDisk:
    d = EphemeralDisk()
    if "sticky" in o:
        d.sticky = _bool(o.get("sticky"), "ephemeral_disk.sticky")
    if "size" in o:
        d.size_mb = _int(o.get("size"), "ephemeral_disk.size")
    if "migrate" in o:
        d.migrate = _bool(o.get("migrate"), "ephemeral_disk.migrate")
    return d


# ---------------------------------------------------------------------------
# Network / resources / services
# ---------------------------------------------------------------------------


def _parse_ports(o: HCLObject, net: NetworkResource) -> None:
    for label, body in _labelled_blocks(o, "port", "port"):
        static = body.get("static")
        to = _int(body.get("to", 0), "port.to") if "to" in body else 0
        if static is not None:
            net.reserved_ports.append(
                Port(label=label, value=_int(static, "port.static"), to=to)
            )
        else:
            net.dynamic_ports.append(Port(label=label, value=0, to=to))


def _parse_network(o: HCLObject) -> NetworkResource:
    net = NetworkResource()
    if "mode" in o:
        net.mode = _str(o.get("mode"))
    if "mbits" in o:
        net.mbits = _int(o.get("mbits"), "network.mbits")
    _parse_ports(o, net)
    return net


def _parse_device(name: str, o: HCLObject) -> RequestedDevice:
    return RequestedDevice(
        name=name,
        count=_int(o.get("count", 1), "device.count"),
        constraints=_parse_constraints(o),
        affinities=_parse_affinities(o),
    )


def _parse_resources(o: HCLObject) -> Resources:
    res = Resources()
    if "cpu" in o:
        res.cpu = _int(o.get("cpu"), "resources.cpu")
    if "memory" in o:
        res.memory_mb = _int(o.get("memory"), "resources.memory")
    if "disk" in o:
        res.disk_mb = _int(o.get("disk"), "resources.disk")
    for body in o.get_all("network"):
        res.networks.append(_parse_network(body))
    for label, body in _labelled_blocks(o, "device", "device"):
        res.devices.append(_parse_device(label, body))
    return res


def _parse_service(o: HCLObject, task_name: str) -> Service:
    name = _str(o.get("name", ""))
    if not name:
        name = f"${{JOB}}-{task_name}" if task_name else ""
    tags = [_str(t) for t in (o.get("tags") or [])]
    checks = [_plain(body) for body in o.get_all("check")]
    connect = None
    for body in o.get_all("connect"):
        # Consul Connect stanza (reference parse_service.go parseConnect):
        # kept as plain dicts — sidecar_service {port, proxy{...}} and
        # sidecar_task {driver, config{...}, resources{...}}
        connect = {}
        for sidecar in body.get_all("sidecar_service"):
            connect["sidecar_service"] = _plain(sidecar)
        for st in body.get_all("sidecar_task"):
            connect["sidecar_task"] = _plain(st)
        if _bool(body.get("native", False), "connect.native"):
            connect["native"] = True
    return Service(
        name=name, port_label=_str(o.get("port", "")), tags=tags, checks=checks,
        connect=connect,
    )


# ---------------------------------------------------------------------------
# Task / group / job
# ---------------------------------------------------------------------------


def _parse_task(name: str, o: HCLObject) -> Task:
    t = Task(name=name)
    t.driver = _str(o.get("driver", ""))
    t.user = _str(o.get("user", ""))
    if "leader" in o:
        t.leader = _bool(o.get("leader"), "task.leader")
    if "kill_timeout" in o:
        t.kill_timeout_ns = parse_duration_ns(o.get("kill_timeout"))
    if "kill_signal" in o:
        t.kill_signal = _str(o.get("kill_signal"))
    for body in o.get_all("config"):
        cfg = _plain(body)
        if not isinstance(cfg, dict):
            raise HCLError("task config must be a block", 0)
        t.config.update(cfg)
    for body in o.get_all("env"):
        t.env.update(_strmap(body, "env"))
    for body in o.get_all("meta"):
        t.meta.update(_strmap(body, "meta"))
    for body in o.get_all("resources"):
        t.resources = _parse_resources(body)
    t.constraints = _parse_constraints(o)
    t.affinities = _parse_affinities(o)
    for body in o.get_all("service"):
        t.services.append(_parse_service(body, name))
    for body in o.get_all("volume_mount"):
        vm = _plain(body)
        t.volume_mounts.append(VolumeMount(
            volume=vm.get("volume", ""),
            destination=vm.get("destination", ""),
            read_only=bool(vm.get("read_only", False)),
        ))
    for body in o.get_all("artifact"):
        t.artifacts.append(_plain(body))
    for body in o.get_all("template"):
        tpl = _plain(body)
        tpl.setdefault("change_mode", "restart")
        tpl.setdefault("splay", "5s")
        tpl.setdefault("perms", "0644")
        t.templates.append(tpl)
    vault = o.get("vault")
    if vault is not None:
        v = _plain(vault)
        v.setdefault("env", True)
        v.setdefault("change_mode", "restart")
        t.vault = v
    for body in o.get_all("restart"):
        t.restart_policy = _parse_restart(body)
    dp = o.get("dispatch_payload")
    if dp is not None:
        t.dispatch_payload_file = _str(dp.get("file", ""))
    if "logs" in o:
        from ..structs.structs import LogConfig

        logs = _plain(o.get("logs"))
        t.log_config = LogConfig(
            max_files=int(logs.get("max_files", 10)),
            max_file_size_mb=int(logs.get("max_file_size", 10)),
        )
    return t


def _parse_group(name: str, o: HCLObject, job_type: str) -> TaskGroup:
    g = TaskGroup(name=name)
    if "count" in o:
        g.count = _int(o.get("count"), "group.count")
    g.constraints = _parse_constraints(o)
    g.affinities = _parse_affinities(o)
    g.spreads = _parse_spreads(o)
    for body in o.get_all("restart"):
        g.restart_policy = _parse_restart(body)
    for body in o.get_all("reschedule"):
        g.reschedule_policy = _parse_reschedule(body)
    for body in o.get_all("ephemeral_disk"):
        g.ephemeral_disk = _parse_ephemeral_disk(body)
    for body in o.get_all("update"):
        g.update = _parse_update(body)
    for body in o.get_all("migrate"):
        g.migrate = _parse_migrate(body)
    for body in o.get_all("network"):
        g.networks.append(_parse_network(body))
    for label, body in _labelled_blocks(o, "volume", "volume"):
        g.volumes[label] = VolumeRequest(
            name=label,
            type=_str(body.get("type", "host")),
            source=_str(body.get("source", "")),
            read_only=_bool(body.get("read_only", False), "volume.read_only"),
        )
    for body in o.get_all("meta"):
        g.meta.update(_strmap(body, "meta"))
    # GROUP-level services — where Consul Connect stanzas live
    # (reference parse_group.go service blocks; unnamed group services
    # default to "<job>-<group>")
    for body in o.get_all("service"):
        svc = _parse_service(body, "")
        if not svc.name:
            svc.name = f"${{JOB}}-{name}"
        g.services.append(svc)
    for label, body in _labelled_blocks(o, "task", "task"):
        g.tasks.append(_parse_task(label, body))
    if not g.tasks:
        raise HCLError(f"group {name!r} has no tasks", 0)
    return g


def parse_job(src: str) -> Job:
    """Parse an HCL jobspec into a :class:`Job` (reference parse.go:27).

    Exactly one top-level ``job`` block is required.
    """
    root = parse_hcl(src)
    jobs = _labelled_blocks(root, "job", "job")
    if len(jobs) != 1:
        raise HCLError(f"expected exactly one 'job' block, got {len(jobs)}", 0)
    job_id, o = jobs[0]

    job = Job(id=job_id, name=job_id)
    if "name" in o:
        job.name = _str(o.get("name"))
    if "id" in o:
        job.id = _str(o.get("id"))
    if "region" in o:
        job.region = _str(o.get("region"))
    if "namespace" in o:
        job.namespace = _str(o.get("namespace"))
    if "type" in o:
        job.type = _str(o.get("type"))
    if "priority" in o:
        job.priority = _int(o.get("priority"), "job.priority")
    if "all_at_once" in o:
        job.all_at_once = _bool(o.get("all_at_once"), "job.all_at_once")
    if "datacenters" in o:
        job.datacenters = [_str(d) for d in (o.get("datacenters") or [])]
    job.constraints = _parse_constraints(o)
    job.affinities = _parse_affinities(o)
    job.spreads = _parse_spreads(o)
    for body in o.get_all("update"):
        job.update = _parse_update(body)
    for body in o.get_all("meta"):
        job.meta.update(_strmap(body, "meta"))
    for body in o.get_all("periodic"):
        p = PeriodicConfig(enabled=True)
        if "cron" in body:
            p.spec = _str(body.get("cron"))
            p.spec_type = "cron"
        if "prohibit_overlap" in body:
            p.prohibit_overlap = _bool(
                body.get("prohibit_overlap"), "periodic.prohibit_overlap"
            )
        if "time_zone" in body:
            p.timezone = _str(body.get("time_zone"))
        if "enabled" in body:
            p.enabled = _bool(body.get("enabled"), "periodic.enabled")
        job.periodic = p
    for body in o.get_all("parameterized"):
        job.parameterized = ParameterizedJobConfig(
            payload=_str(body.get("payload", "optional")),
            meta_required=[_str(x) for x in (body.get("meta_required") or [])],
            meta_optional=[_str(x) for x in (body.get("meta_optional") or [])],
        )
    for label, body in _labelled_blocks(o, "group", "group"):
        job.task_groups.append(_parse_group(label, body, job.type))
    # A bare task at job level becomes a single-task group of the same name
    # (reference parse_job.go: "If we have tasks outside, create TaskGroups")
    for label, body in _labelled_blocks(o, "task", "task"):
        task = _parse_task(label, body)
        job.task_groups.append(TaskGroup(name=label, count=1, tasks=[task]))
    if not job.task_groups:
        raise HCLError(f"job {job_id!r} has no task groups", 0)
    names = [g.name for g in job.task_groups]
    if len(names) != len(set(names)):
        raise HCLError("duplicate task group names", 0)
    # Service-name ${JOB} interpolation happens at parse time (the
    # reference interpolates in taskenv; nothing downstream here resolves
    # it, so defaulted "<job>-<group>" names must be concrete)
    for tg in job.task_groups:
        for svc in tg.services:
            svc.name = svc.name.replace("${JOB}", job.name)
        for task in tg.tasks:
            for svc in task.services:
                svc.name = svc.name.replace("${JOB}", job.name)
    return job


def parse_file(path: str) -> Job:
    with open(path, "r", encoding="utf-8") as f:
        return parse_job(f.read())
