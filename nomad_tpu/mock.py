"""Canonical test fixtures (reference ``nomad/mock/mock.go``)."""
from __future__ import annotations

from .structs.structs import (
    ALLOC_CLIENT_PENDING,
    ALLOC_DESIRED_RUN,
    EVAL_STATUS_PENDING,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    JOB_STATUS_PENDING,
    JOB_TYPE_BATCH,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    AllocatedResources,
    AllocatedSharedResources,
    AllocatedTaskResources,
    Allocation,
    Constraint,
    DriverInfo,
    EphemeralDisk,
    Evaluation,
    Job,
    MigrateStrategy,
    NetworkResource,
    Node,
    NodeDeviceInstance,
    NodeDeviceResource,
    NodeReservedResources,
    NodeResources,
    Port,
    ReschedulePolicy,
    Resources,
    RestartPolicy,
    Task,
    TaskGroup,
    generate_uuid,
)

MINUTE_NS = 60 * 10**9
SECOND_NS = 10**9


def node() -> Node:
    n = Node(
        id=generate_uuid(),
        datacenter="dc1",
        name="foobar",
        drivers={
            "exec": DriverInfo(detected=True, healthy=True),
            "mock_driver": DriverInfo(detected=True, healthy=True),
        },
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.5.0",
            "driver.exec": "1",
            "driver.mock_driver": "1",
        },
        node_resources=NodeResources(
            cpu_shares=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            networks=[
                NetworkResource(device="eth0", cidr="192.168.0.100/32", ip="192.168.0.100", mbits=1000)
            ],
        ),
        reserved_resources=NodeReservedResources(
            cpu_shares=100,
            memory_mb=256,
            disk_mb=4 * 1024,
            reserved_host_ports="22",
        ),
        meta={"pci-dss": "true", "database": "mysql", "version": "5.6"},
        node_class="linux-medium-pci",
    )
    n.compute_class()
    return n


def nvidia_node() -> Node:
    n = node()
    n.node_resources.devices = [
        NodeDeviceResource(
            type="gpu",
            vendor="nvidia",
            name="1080ti",
            attributes={
                "memory_mb": 11264,
                "cuda_cores": 3584,
                "graphics_clock_mhz": 1480,
                "memory_bandwidth_gbps": 11,
            },
            instances=[
                NodeDeviceInstance(id=generate_uuid(), healthy=True),
                NodeDeviceInstance(id=generate_uuid(), healthy=True),
            ],
        )
    ]
    n.compute_class()
    return n


def job() -> Job:
    j = Job(
        region="global",
        id=f"mock-service-{generate_uuid()}",
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        datacenters=["dc1"],
        constraints=[Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")],
        task_groups=[
            TaskGroup(
                name="web",
                count=10,
                ephemeral_disk=EphemeralDisk(size_mb=150),
                restart_policy=RestartPolicy(
                    attempts=3, interval_ns=10 * MINUTE_NS, delay_ns=MINUTE_NS, mode="delay"
                ),
                reschedule_policy=ReschedulePolicy(
                    attempts=2,
                    interval_ns=10 * MINUTE_NS,
                    delay_ns=5 * SECOND_NS,
                    delay_function="constant",
                ),
                migrate=MigrateStrategy(),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={"FOO": "bar"},
                        resources=Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                NetworkResource(
                                    mbits=50,
                                    dynamic_ports=[Port(label="http"), Port(label="admin")],
                                )
                            ],
                        ),
                        meta={"foo": "bar"},
                    )
                ],
                meta={"elb_check_type": "http", "elb_check_interval": "30s", "elb_check_min": "3"},
            )
        ],
        meta={"owner": "armon"},
        status=JOB_STATUS_PENDING,
        version=0,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    return j


def batch_job() -> Job:
    j = Job(
        region="global",
        id=f"mock-batch-{generate_uuid()}",
        name="batch-job",
        type=JOB_TYPE_BATCH,
        priority=50,
        datacenters=["dc1"],
        task_groups=[
            TaskGroup(
                name="worker",
                count=10,
                ephemeral_disk=EphemeralDisk(size_mb=150),
                restart_policy=RestartPolicy(
                    attempts=3, interval_ns=10 * MINUTE_NS, delay_ns=5 * SECOND_NS, mode="delay"
                ),
                reschedule_policy=ReschedulePolicy(
                    attempts=2,
                    interval_ns=10 * MINUTE_NS,
                    delay_ns=5 * SECOND_NS,
                    delay_function="constant",
                ),
                tasks=[
                    Task(
                        name="worker",
                        driver="mock_driver",
                        config={"run_for": "500ms"},
                        env={"FOO": "bar"},
                        resources=Resources(cpu=100, memory_mb=100),
                        meta={"foo": "bar"},
                    )
                ],
            )
        ],
        status=JOB_STATUS_PENDING,
        create_index=43,
        modify_index=99,
        job_modify_index=99,
    )
    return j


def system_job() -> Job:
    j = Job(
        region="global",
        id=f"mock-system-{generate_uuid()}",
        name="my-job",
        type=JOB_TYPE_SYSTEM,
        priority=100,
        datacenters=["dc1"],
        constraints=[Constraint(ltarget="${attr.kernel.name}", rtarget="linux", operand="=")],
        task_groups=[
            TaskGroup(
                name="web",
                count=1,
                ephemeral_disk=EphemeralDisk(size_mb=50),
                restart_policy=RestartPolicy(
                    attempts=3, interval_ns=10 * MINUTE_NS, delay_ns=MINUTE_NS, mode="delay"
                ),
                tasks=[
                    Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={},
                        resources=Resources(cpu=500, memory_mb=256),
                    )
                ],
            )
        ],
        meta={"owner": "armon"},
        status=JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    return j


def eval() -> Evaluation:
    return Evaluation(
        id=generate_uuid(),
        priority=50,
        type=JOB_TYPE_SERVICE,
        job_id=generate_uuid(),
        status=EVAL_STATUS_PENDING,
        triggered_by=EVAL_TRIGGER_JOB_REGISTER,
    )


def alloc() -> Allocation:
    j = job()
    a = Allocation(
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        namespace="default",
        task_group="web",
        allocated_resources=AllocatedResources(
            tasks={
                "web": AllocatedTaskResources(
                    cpu_shares=500,
                    memory_mb=256,
                    networks=[
                        NetworkResource(
                            device="eth0",
                            ip="192.168.0.100",
                            mbits=50,
                            reserved_ports=[Port(label="admin", value=5000)],
                            dynamic_ports=[Port(label="http", value=9876)],
                        )
                    ],
                )
            },
            shared=AllocatedSharedResources(disk_mb=150),
        ),
        job=j,
        job_id=j.id,
        desired_status=ALLOC_DESIRED_RUN,
        client_status=ALLOC_CLIENT_PENDING,
    )
    a.name = f"{j.id}.web[0]"
    return a
