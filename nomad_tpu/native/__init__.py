"""Native (C++) runtime substrate bindings.

The reference leans on vendored native-grade infrastructure — raft-boltdb
for the log (nomad/server.go:1079), libcontainer for task isolation
(drivers/shared/executor/executor_linux.go:50). Here those are first-party
C++ (``native/``), bound over ctypes; ``ensure_built`` compiles them on
demand with the in-image toolchain and caches the artifacts.
"""
from __future__ import annotations

import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
BUILD_DIR = os.path.join(NATIVE_DIR, "build")
_build_lock = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def ensure_built(target: str) -> str:
    """Build (once) and return the path of a native artifact
    (``libnomadlog.so`` or ``nomad-executor``)."""
    path = os.path.join(BUILD_DIR, target)
    with _build_lock:
        sources = {
            "libnomadlog.so": os.path.join(NATIVE_DIR, "nomadlog", "nomadlog.cpp"),
            "nomad-executor": os.path.join(NATIVE_DIR, "executor", "nomad_executor.cpp"),
        }
        src = sources.get(target)
        if src is None:
            raise NativeBuildError(f"unknown native target {target!r}")
        if os.path.exists(path) and os.path.getmtime(path) >= os.path.getmtime(src):
            return path
        proc = subprocess.run(
            ["make", "-C", NATIVE_DIR, f"build/{target}"],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build of {target} failed:\n{proc.stdout}\n{proc.stderr}"
            )
        return path
