"""ctypes binding for the C++ segmented log (native/nomadlog).

The durable raft-log store (reference raft-boltdb). Record payloads are
opaque bytes; the raft layer picks the codec (pickle in-proc, msgpack on
the wire).
"""
from __future__ import annotations

import ctypes
from typing import Optional

from . import ensure_built

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = ensure_built("libnomadlog.so")
    lib = ctypes.CDLL(path)
    lib.nomadlog_open.restype = ctypes.c_void_p
    lib.nomadlog_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.nomadlog_append.restype = ctypes.c_int
    lib.nomadlog_append.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.nomadlog_sync.restype = ctypes.c_int
    lib.nomadlog_sync.argtypes = [ctypes.c_void_p]
    lib.nomadlog_first_index.restype = ctypes.c_uint64
    lib.nomadlog_first_index.argtypes = [ctypes.c_void_p]
    lib.nomadlog_last_index.restype = ctypes.c_uint64
    lib.nomadlog_last_index.argtypes = [ctypes.c_void_p]
    lib.nomadlog_get.restype = ctypes.c_int
    lib.nomadlog_get.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.nomadlog_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.nomadlog_truncate_before.restype = ctypes.c_int
    lib.nomadlog_truncate_before.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nomadlog_truncate_after.restype = ctypes.c_int
    lib.nomadlog_truncate_after.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.nomadlog_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeLog:
    """Durable append-only log over the C++ engine."""

    def __init__(self, directory: str, segment_bytes: int = 64 << 20) -> None:
        self._lib = _load()
        self._h = self._lib.nomadlog_open(directory.encode(), segment_bytes)
        if not self._h:
            raise OSError(f"nomadlog_open({directory}) failed")

    def append(self, index: int, data: bytes, sync: bool = False) -> None:
        rc = self._lib.nomadlog_append(self._h, index, data, len(data))
        if rc != 0:
            raise OSError(f"nomadlog_append({index}) failed")
        if sync:
            self.sync()

    def sync(self) -> None:
        if self._lib.nomadlog_sync(self._h) != 0:
            raise OSError("nomadlog_sync failed")

    @property
    def first_index(self) -> int:
        return self._lib.nomadlog_first_index(self._h)

    @property
    def last_index(self) -> int:
        return self._lib.nomadlog_last_index(self._h)

    def get(self, index: int) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint32()
        rc = self._lib.nomadlog_get(self._h, index, ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            return None
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.nomadlog_free(out)

    def truncate_before(self, upto: int) -> None:
        self._lib.nomadlog_truncate_before(self._h, upto)

    def truncate_after(self, from_index: int) -> None:
        self._lib.nomadlog_truncate_after(self._h, from_index)

    def close(self) -> None:
        if self._h:
            self._lib.nomadlog_close(self._h)
            self._h = None

    def __enter__(self) -> "NativeLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
